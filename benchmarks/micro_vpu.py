"""VPU microbenchmarks: what is the achievable i32 gate-op rate on this chip?

Anchors the roofline for the DCF walk kernel (ops/pallas_eval.py).  The walk
is pure VPU work — XOR/AND planes, no MXU, no HBM pressure — so its ceiling
is the rate at which Mosaic-compiled elementwise i32 ops retire.  Probes,
all single-grid-step Pallas kernels looping in VMEM:

  chain[k]   k independent add/and/xor dependency chains on [16, L] tiles:
             measures issue throughput vs latency (ILP sweep).
  sbox       the Boyar-Peralta 113-gate S-box applied back-to-back:
             the walk spends ~2/3 of its ops here.
  aes        full bitsliced AES-256 (14 rounds: sbox + shift + mix + ark):
             everything but the DCF-level logic.

Timing notes: on the tunneled dev device, ``block_until_ready`` does not
block, so completion is forced by fetching a small digest (same trick as
bench.py).  Each probe is timed at two loop counts and the rate is taken
from the SLOPE, cancelling the fixed ~85ms dispatch+sync round-trip.

Usage: python -m benchmarks.micro_vpu [--lanes 256] [--iters N]
Prints one JSON line per probe: {probe, word_ops, seconds, tera_ops}.
"""

from __future__ import annotations

import argparse
import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from dcf_tpu.ops.aes_bitsliced import (
    aes256_encrypt_blocks_bitmajor_v3,
    aes256_encrypt_planes_bitmajor,
    aes256_encrypt_planes_bitmajor_v2,
    prep_rk_bitmajor_v3,
    round_key_masks_bitmajor,
)
from dcf_tpu.ops.sbox_circuit import sbox_planes_bp113


def _chain_kernel(x_ref, y_ref, *, iters: int, k: int):
    c = x_ref[0]
    r = x_ref[1 % x_ref.shape[0]]
    states = tuple(x_ref[i % x_ref.shape[0]] ^ jnp.int32(i) for i in range(k))

    def body(i, ss):
        # 3 dependent ops per chain (add, and, xor); chains independent.
        # Non-idempotent (the add) so the compiler cannot collapse the loop.
        return tuple((s + c) ^ (s & r) for s in ss)

    out = jax.lax.fori_loop(0, iters, body, states)
    acc = out[0]
    for s in out[1:]:
        acc = acc ^ s
    y_ref[:] = acc


def _sbox_kernel(x_ref, y_ref, *, iters: int):
    ones = jnp.int32(-1)
    planes = tuple(x_ref[i] for i in range(8))

    def body(i, ps):
        return tuple(sbox_planes_bp113(list(ps), ones))

    out = jax.lax.fori_loop(0, iters, body, planes)
    acc = out[0]
    for p in out[1:]:
        acc = acc ^ p
    y_ref[0] = acc


def _aes_kernel(rk_ref, x_ref, y_ref, *, iters: int, enc):
    ones = jnp.int32(-1)
    rk = rk_ref[:]

    def body(i, s):
        return enc(jnp, rk, s, ones)

    y_ref[:] = jax.lax.fori_loop(0, iters, body, x_ref[:])


def _sync(y) -> None:
    np.asarray(jnp.max(y.reshape(-1)[-8:]))


def _time_one(fn_builder, args, out_shape, iters: int, reps: int = 3) -> float:
    f = jax.jit(lambda *a: pl.pallas_call(
        fn_builder(iters), out_shape=out_shape)(*a))
    _sync(f(*args))  # compile + warmup
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _sync(f(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _slope(fn_builder, args, out_shape, iters: int):
    """Seconds per `iters` loop iterations, fixed overhead cancelled."""
    t1 = _time_one(fn_builder, args, out_shape, iters)
    t2 = _time_one(fn_builder, args, out_shape, 2 * iters)
    return max(t2 - t1, 1e-9), t1


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lanes", type=int, default=256,
                    help="lane width L of the [16, L] tiles (walk uses 2*wt)")
    ap.add_argument("--iters", type=int, default=40000)
    args = ap.parse_args()
    lanes, iters = args.lanes, args.iters
    rng = np.random.default_rng(0)

    tile_words = 16 * lanes

    for k in (1, 2, 4, 8):
        x = jnp.asarray(
            rng.integers(-(2**31), 2**31, (max(k, 2), 16, lanes), dtype=np.int64
                         ).astype(np.int32))
        sec, t1 = _slope(
            lambda it: partial(_chain_kernel, iters=it, k=k), (x,),
            jax.ShapeDtypeStruct((16, lanes), jnp.int32), iters)
        word_ops = 3 * k * tile_words * iters
        print(json.dumps({
            "probe": f"chain[{k}]", "word_ops": word_ops, "seconds": sec,
            "tera_ops": round(word_ops / sec / 1e12, 3),
            "t_single": round(t1, 4)}))

    x = jnp.asarray(
        rng.integers(-(2**31), 2**31, (8, 16, lanes), dtype=np.int64
                     ).astype(np.int32))
    sbox_iters = max(1, iters // 8)
    sec, t1 = _slope(lambda it: partial(_sbox_kernel, iters=it), (x,),
                     jax.ShapeDtypeStruct((1, 16, lanes), jnp.int32),
                     sbox_iters)
    word_ops = 113 * tile_words * sbox_iters
    print(json.dumps({
        "probe": "sbox", "word_ops": word_ops, "seconds": sec,
        "tera_ops": round(word_ops / sec / 1e12, 3),
        "t_single": round(t1, 4)}))

    rk = jnp.asarray(round_key_masks_bitmajor(bytes(range(32))))
    st = jnp.asarray(
        rng.integers(-(2**31), 2**31, (128, lanes), dtype=np.int64
                     ).astype(np.int32))
    aes_iters = max(1, iters // 100)
    # Gate-op accounting per encryption (see ROOFLINE.md): 14 sbox layers,
    # 13 mix layers (4-term xor tree over 128 planes + 2 xtime tap sets),
    # 15 ARK xors over 128 planes.  tile_words = 16*lanes; 128 planes = 8*tw.
    sbox_ops = 14 * 113 * tile_words
    ark_ops = 15 * 8 * tile_words
    mix_ops = 13 * (4 * 8 + 6) * tile_words
    word_ops = (sbox_ops + ark_ops + mix_ops) * aes_iters
    def _v3_with_prep(xp, rk_all, state, ones):
        # rk prep runs per loop iteration here; the walk kernel hoists it,
        # so v3's real advantage is slightly larger than this probe shows.
        l = state.shape[-1]
        s3 = state.reshape(8, 16, l)
        out = aes256_encrypt_blocks_bitmajor_v3(
            xp, prep_rk_bitmajor_v3(xp, rk_all),
            [s3[i] for i in range(8)], ones)
        return xp.stack(out).reshape(128, l)

    for name, enc in (("aes256", aes256_encrypt_planes_bitmajor),
                      ("aes256_v2", aes256_encrypt_planes_bitmajor_v2),
                      ("aes256_v3", _v3_with_prep)):
        sec, t1 = _slope(
            lambda it: partial(_aes_kernel, iters=it, enc=enc), (rk, st),
            jax.ShapeDtypeStruct((128, lanes), jnp.int32), aes_iters)
        print(json.dumps({
            "probe": name, "word_ops": word_ops, "seconds": sec,
            "tera_ops": round(word_ops / sec / 1e12, 3),
            "t_single": round(t1, 4),
            # one [128, lanes] application encrypts 32*lanes 16-byte blocks
            "ns_per_16B_block": round(
                sec / aes_iters / (32 * lanes) * 1e9, 3)}))


if __name__ == "__main__":
    main()
