"""Measure and pin the pir_bench single-core numpy-EvalAll denominator.

``pir_bench``'s ``vs_baseline`` compares served PIR queries/s against
"what would the obviously-correct host implementation serve": the
single-core numpy full-domain expansion (``backends.evalall
.dpf_tree_expand_np`` + ``dpf_finalize_np``) of one DPF key over the
n=16 domain — one EvalAll IS one PIR query's dominant cost (the GF(2)
inner product is noise next to 2^17 PRG calls).  Same pinning
discipline as ``cpu_baseline.py`` (CPU_BASELINE.md): fixed workload,
warmup passes, >= 40 timed samples, median pinned with the p10-p90
band and host state recorded alongside, committed once — the
denominator must not move between bench runs.

Fixed workload: 1 key, lam=32 (the DPF device width), n=16 domain,
party 0, drawn from the same seed the bench uses.  ``pir_bench``
rescales the pin by 2^16 / 2^n for its other domain sizes — EvalAll
cost is linear in leaf count.

Writes the ``"dpf": {"evalall_n16": ...}`` entry into
``benchmarks/cpu_baseline.json`` (other fields untouched) and prints
the record.

Usage: python benchmarks/dpf_baseline.py [--samples N]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time
import warnings

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_BITS = 16
LAM = 32
KEYS = 1


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=40)
    args = ap.parse_args()

    from benchmarks.cpu_baseline import host_state
    from dcf_tpu.backends.evalall import dpf_finalize_np, dpf_tree_expand_np
    from dcf_tpu.spec import ReferenceContractWarning
    from dcf_tpu.ops.prg import HirosePrgNp
    from dcf_tpu.protocols.dpf import dpf_gen_batch

    rng = np.random.default_rng(2026)
    cipher_keys = [bytes(rng.integers(0, 256, 32, dtype=np.uint8))
                   for _ in range(18)]
    with warnings.catch_warnings():
        # lam=32 is the documented reference-contract deviation the DPF
        # device kernel requires; the warning is the facade's job.
        warnings.simplefilter("ignore", ReferenceContractWarning)
        prg = HirosePrgNp(LAM, cipher_keys)
    n_bytes = N_BITS // 8
    alphas = np.array(
        [list(int(a).to_bytes(n_bytes, "big"))
         for a in rng.integers(0, 1 << N_BITS, KEYS)], dtype=np.uint8)
    betas = rng.integers(0, 256, (KEYS, LAM), dtype=np.uint8)
    s0s = rng.integers(0, 256, (KEYS, 2, LAM), dtype=np.uint8)
    bundle = dpf_gen_batch(prg, alphas, betas, s0s)
    kb = bundle.for_party(0)

    def one_query():
        s, t = dpf_tree_expand_np(prg, kb, 0, N_BITS)
        dpf_finalize_np(kb, s, t)

    for _ in range(4):  # warmup (turbo burst / cache warm)
        one_query()
    rates = []
    for _ in range(max(args.samples, 8)):
        t0 = time.perf_counter()
        one_query()
        rates.append(KEYS / (time.perf_counter() - t0))
    rates = np.array(rates)
    entry = {
        "queries_per_sec": round(float(np.median(rates)), 3),
        "band_queries_per_sec": [
            round(float(np.percentile(rates, 10)), 3),
            round(float(np.percentile(rates, 90)), 3)],
        "band": "p10-p90 of per-sample rates",
        "samples": len(rates),
        "keys": KEYS,
        "n_bits": N_BITS,
        "workload": (f"numpy dpf_tree_expand_np + dpf_finalize_np, "
                     f"K={KEYS} key, n={N_BITS} domain, lam={LAM}, "
                     "single core, one party (one query = one EvalAll)"),
        "date": datetime.date.today().isoformat(),
        **host_state(),
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cpu_baseline.json")
    with open(path) as f:
        pinned = json.load(f)
    pinned.setdefault("dpf", {})[f"evalall_n{N_BITS}"] = entry
    with open(path, "w") as f:
        json.dump(pinned, f, indent=1)
        f.write("\n")
    print(json.dumps(entry, indent=1))


if __name__ == "__main__":
    main()
