"""Measure and pin the canonical single-core CPU baseline.

The flagship headline (bench.py) is a ratio against the single-core C++
AES-NI eval rate — the stand-in for the reference's single-core Rust path
(`/root/reference/benches/dcf_batch_eval.rs:17-39` run serially).  Round 3
measured that denominator in-process with 3 quick samples, and its
run-to-run swing (86-112 k evals/s) moved the headline through the 100x
mark on noise alone.  This script is the pinned protocol
(benchmarks/CPU_BASELINE.md):

  * fixed workload: the flagship shape — 1 key, N=16-byte domain, lam=16,
    LT_BETA, party 0 — on a fixed 2^15-point batch (~0.3 s/sample);
  * 8 untimed warmup passes (~2.5 s — this 1-vCPU VM serves a ~25%-fast
    turbo burst for the first couple of seconds; sustained rate is what
    the reference's minutes-long criterion runs see);
  * then >= 40 timed in-process samples (~13 s window, so hypervisor
    steal-time variation is sampled, not dodged): the pin is the MEDIAN,
    with the p10-p90 spread recorded alongside;
  * host state recorded alongside the number (CPU model, core count,
    1-min loadavg, AES-NI availability).

Writes ``benchmarks/cpu_baseline.json`` (the artifact bench.py uses as
the vs_baseline denominator) and prints the record.  Re-run + re-commit
only with a stated reason — the point of pinning is that the denominator
does not move between bench runs.

Usage: python benchmarks/cpu_baseline.py [--samples N]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

M = 1 << 15
LAM = 16
N_BYTES = 16


def host_state() -> dict:
    model = "unknown"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return {
        "cpu_model": model,
        "cpu_count": os.cpu_count(),
        "loadavg_1min": round(os.getloadavg()[0], 2),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=40)
    args = ap.parse_args()

    from dcf_tpu.gen import random_s0s
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.spec import Bound

    rng = np.random.default_rng(2026)
    cipher_keys = [rng.bytes(32), rng.bytes(32)]
    native = NativeDcf(LAM, cipher_keys)
    alphas = rng.integers(0, 256, (1, N_BYTES), dtype=np.uint8)
    betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
    bundle = native.gen_batch(alphas, betas, random_s0s(1, LAM, rng),
                              Bound.LT_BETA)
    xs = rng.integers(0, 256, (M, N_BYTES), dtype=np.uint8)

    for _ in range(8):  # warmup: page-in + ride out the VM's turbo burst
        native.eval(0, bundle, xs, num_threads=1)
    samples = []
    for i in range(max(args.samples, 10)):
        t0 = time.perf_counter()
        native.eval(0, bundle, xs, num_threads=1)
        samples.append(time.perf_counter() - t0)
    arr = np.array(samples)
    med = float(np.median(arr))
    mad = float(np.median(np.abs(arr - med)))
    rates = M / arr
    rate = M / med
    record = {
        "evals_per_sec": round(rate, 1),
        "band_evals_per_sec": [round(float(np.percentile(rates, 10)), 1),
                               round(float(np.percentile(rates, 90)), 1)],
        "band": "p10-p90 of per-sample rates",
        "median_s": round(med, 5),
        "mad_s": round(mad, 6),
        "samples": len(samples),
        "batch_points": M,
        "workload": "1 key, N=16B domain, lam=16, LT_BETA, party 0, "
                    "single thread",
        "aesni": bool(native.has_aesni),
        "date": datetime.date.today().isoformat(),
        **host_state(),
    }
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "cpu_baseline.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps(record, indent=1))
    print(f"\npinned: {rate:,.0f} evals/s "
          f"(band {record['band_evals_per_sec'][0]:,.0f}-"
          f"{record['band_evals_per_sec'][1]:,.0f}) -> {out}")


if __name__ == "__main__":
    main()
