"""Measure and pin the canonical single-core CPU baseline.

The flagship headline (bench.py) is a ratio against the single-core C++
AES-NI eval rate — the stand-in for the reference's single-core Rust path
(`/root/reference/benches/dcf_batch_eval.rs:17-39` run serially).  Round 3
measured that denominator in-process with 3 quick samples, and its
run-to-run swing (86-112 k evals/s) moved the headline through the 100x
mark on noise alone.  This script is the pinned protocol
(benchmarks/CPU_BASELINE.md):

  * fixed workload: the flagship shape — 1 key, N=16-byte domain, lam=16,
    LT_BETA, party 0 — on a fixed 2^15-point batch (~0.3 s/sample);
  * 8 untimed warmup passes (~2.5 s — this 1-vCPU VM serves a ~25%-fast
    turbo burst for the first couple of seconds; sustained rate is what
    the reference's minutes-long criterion runs see);
  * then >= 40 timed in-process samples (~13 s window, so hypervisor
    steal-time variation is sampled, not dodged): the pin is the MEDIAN,
    with the p10-p90 spread recorded alongside;
  * host state recorded alongside the number (CPU model, core count,
    1-min loadavg, AES-NI availability).

Writes ``benchmarks/cpu_baseline.json`` (the artifact bench.py uses as
the vs_baseline denominator) and prints the record.  Re-run + re-commit
only with a stated reason — the point of pinning is that the denominator
does not move between bench runs.

Round 5 extends the artifact with per-shape entries under ``"shapes"``
(currently ``n32``: the BASELINE config-2 literal 4-byte domain, same
protocol, batch scaled to keep ~0.3 s/sample) so the other literal
shapes' speedup claims get pinned denominators too; the flagship
top-level fields are unchanged (bench.py reads them verbatim).

Usage: python benchmarks/cpu_baseline.py [--samples N]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

M = 1 << 15
LAM = 16
N_BYTES = 16


def host_state() -> dict:
    model = "unknown"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return {
        "cpu_model": model,
        "cpu_count": os.cpu_count(),
        "loadavg_1min": round(os.getloadavg()[0], 2),
    }


def _measure_shape(native, rng, n_bytes: int, m: int, n_samples: int,
                   random_s0s, Bound, lam: int = LAM) -> dict:
    """The pinned protocol at one shape: 8 warmups, >= n_samples timed
    in-process samples, median + p10-p90."""
    import numpy as np

    alphas = rng.integers(0, 256, (1, n_bytes), dtype=np.uint8)
    betas = rng.integers(0, 256, (1, lam), dtype=np.uint8)
    bundle = native.gen_batch(alphas, betas, random_s0s(1, lam, rng),
                              Bound.LT_BETA)
    xs = rng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    for _ in range(8):  # warmup: page-in + ride out the VM's turbo burst
        native.eval(0, bundle, xs, num_threads=1)
    samples = []
    for _ in range(max(n_samples, 10)):
        t0 = time.perf_counter()
        native.eval(0, bundle, xs, num_threads=1)
        samples.append(time.perf_counter() - t0)
    arr = np.array(samples)
    med = float(np.median(arr))
    mad = float(np.median(np.abs(arr - med)))
    rates = m / arr
    return {
        "evals_per_sec": round(m / med, 1),
        "band_evals_per_sec": [round(float(np.percentile(rates, 10)), 1),
                               round(float(np.percentile(rates, 90)), 1)],
        "band": "p10-p90 of per-sample rates",
        "median_s": round(med, 5),
        "mad_s": round(mad, 6),
        "samples": len(samples),
        "batch_points": m,
        "workload": f"1 key, N={n_bytes}B domain, lam={lam}, LT_BETA, "
                    "party 0, single thread",
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=40)
    ap.add_argument("--re-pin-flagship", action="store_true",
                    help="re-measure the flagship top-level fields too "
                         "(the pin's whole point is that they do NOT "
                         "move; state the reason in the commit).  By "
                         "default an existing artifact's flagship pin is "
                         "preserved.")
    ap.add_argument("--re-pin-shapes", action="store_true",
                    help="re-measure per-shape entries that already exist "
                         "in the artifact (same rule as the flagship: an "
                         "existing pin must not move without a stated "
                         "reason).  By default only MISSING shape entries "
                         "are measured and existing ones are preserved.")
    args = ap.parse_args()

    from dcf_tpu.gen import random_s0s
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.spec import Bound

    rng = np.random.default_rng(2026)
    cipher_keys = [rng.bytes(32), rng.bytes(32)]
    native = NativeDcf(LAM, cipher_keys)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "cpu_baseline.json")
    existing = None
    if not args.re_pin_flagship:
        try:
            with open(out) as f:
                existing = json.load(f)
        except OSError:
            pass
    if existing is None:
        flagship = {
            **_measure_shape(native, rng, N_BYTES, M, args.samples,
                             random_s0s, Bound),
            "aesni": bool(native.has_aesni),
            "date": datetime.date.today().isoformat(),
            **host_state(),
        }
    else:
        flagship = {k: v for k, v in existing.items() if k != "shapes"}
        print("flagship pin preserved from existing artifact "
              f"({flagship['date']})")
    # Existing shape entries are pins too: preserved unless explicitly
    # re-pinned (otherwise a casual run on a loaded host would silently
    # move the per-shape denominators the ratios are computed against).
    shapes = dict((existing or {}).get("shapes", {}))
    if "n32" in shapes and not args.re_pin_shapes:
        print("n32 shape pin preserved from existing artifact")
    else:
        # Config-2 literal shape (n=32): ~4x the flagship rate, so the
        # batch is scaled 4x to keep the ~0.3 s/sample protocol window.
        shapes["n32"] = {
            **_measure_shape(native, rng, 4, M * 4, args.samples,
                             random_s0s, Bound),
            "date": datetime.date.today().isoformat(),
            "loadavg_1min": round(os.getloadavg()[0], 2),
        }

    # Round 6 (PR 3): pinned denominators for the remaining literal
    # BASELINE shapes (VERDICT round-5 item 2) — lam=128 / lam=256 /
    # lam=16384, each with its own cipher set and native core, batch
    # scaled to the ~0.3 s/sample window.  secure_relu needs no entry:
    # its per-eval shape is the flagship's (the table in BASELINE.md
    # reuses that pin).
    #
    # Cross-host transfer: a pin is a property of the PINNED host.  When
    # this script runs on a DIFFERENT host (e.g. a build box without the
    # TPU-host's clock), raw local rates would not be comparable to the
    # committed flagship/n32 pins or to chip rates recorded on the pin
    # host — so a same-session flagship reference is measured alongside,
    # and if it deviates > 10% from the committed flagship pin, each new
    # entry's ``evals_per_sec`` is the flagship-ratio TRANSFER
    # (local_rate * pinned_flagship / session_flagship), with the raw
    # local numbers kept in the entry.  Both hosts must agree on AES-NI
    # for the transfer to be meaningful; that is recorded too.
    import warnings

    missing = [t for t in ("lam128", "lam256", "lam16384")
               if t not in shapes or args.re_pin_shapes]
    if not missing:
        print("lam128/lam256/lam16384 shape pins preserved from "
              "existing artifact")
    else:
        session_flag = _measure_shape(native, rng, N_BYTES, M // 2,
                                      args.samples, random_s0s, Bound)
        pinned_rate = flagship["evals_per_sec"]
        scale = pinned_rate / session_flag["evals_per_sec"]
        anchored = abs(scale - 1.0) > 0.10
        if anchored:
            print(f"host differs from the pin host (session flagship "
                  f"{session_flag['evals_per_sec']:,.0f} vs pinned "
                  f"{pinned_rate:,.0f}): recording flagship-ratio "
                  f"transferred pins (scale {scale:.3f})")
        for tag, lam, batch in (("lam128", 128, M // 4),
                                ("lam256", 256, M // 4),
                                ("lam16384", 16384, 128)):
            if tag not in missing:
                continue
            ck = [rng.bytes(32) for _ in range(max(18, 2 * (lam // 16)))]
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                nat = NativeDcf(lam, ck)
            entry = _measure_shape(nat, rng, N_BYTES, batch, args.samples,
                                   random_s0s, Bound, lam=lam)
            # AES-NI is recorded on every entry (direct or transferred):
            # it is the validity condition a future cross-host transfer
            # checks against.
            entry["aesni"] = bool(nat.has_aesni)
            if anchored:
                entry.update(
                    local_evals_per_sec=entry["evals_per_sec"],
                    local_band_evals_per_sec=entry["band_evals_per_sec"],
                    session_flagship_evals_per_sec=round(
                        session_flag["evals_per_sec"], 1),
                    anchor=("flagship-ratio transfer: measured on a "
                            "non-pin host, scaled by pinned/session "
                            "flagship (CPU_BASELINE.md)"),
                    evals_per_sec=round(
                        entry["evals_per_sec"] * scale, 1),
                    band_evals_per_sec=[
                        round(v * scale, 1)
                        for v in entry["band_evals_per_sec"]],
                )
            shapes[tag] = {
                **entry,
                "date": datetime.date.today().isoformat(),
                "loadavg_1min": round(os.getloadavg()[0], 2),
                **host_state(),
            }
    # ISSUE 10: pinned KEYGEN denominators for keygen_bench's
    # vs_baseline — single-core numpy ``gen_batch`` (the numpy-oracle
    # discipline of protocols.mic_m8: "what would the obviously-correct
    # host implementation generate"), K=64 keys on the flagship N=16-byte
    # domain at lam in {128, 256} (the hybrid-family shapes the Pallas
    # keygen kernel serves).  Same pin protocol: warmups, >= 40 timed
    # in-process samples, median + p10-p90 band, host state recorded,
    # committed once; existing entries are preserved unless
    # --re-pin-shapes.  NO flagship-ratio transfer applies here — that
    # anchor scales AES-NI C++ rates between hosts, and these pins are
    # pure numpy (the mic_m8 rule): re-pin directly on the host that
    # will anchor the ratios, with a stated reason, and read the
    # recorded host state before comparing across machines.
    keygen = dict((existing or {}).get("keygen", {}))
    missing_kg = [t for t in ("lam128", "lam256")
                  if t not in keygen or args.re_pin_shapes]
    if not missing_kg:
        print("keygen lam128/lam256 pins preserved from existing artifact")
    else:
        from dcf_tpu.gen import gen_batch
        from dcf_tpu.ops.prg import HirosePrgNp

        for tag, lam in (("lam128", 128), ("lam256", 256)):
            if tag not in missing_kg:
                continue
            ck = [rng.bytes(32) for _ in range(18)]
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                prg = HirosePrgNp(lam, ck)
            k_keys = 64
            alphas = rng.integers(0, 256, (k_keys, N_BYTES),
                                  dtype=np.uint8)
            betas = rng.integers(0, 256, (k_keys, lam), dtype=np.uint8)
            s0s = random_s0s(k_keys, lam, rng)
            # 8 warmups, same as _measure_shape: at ~1.1 s/call this
            # rides out the turbo burst with a wide margin; the timed
            # window floors at 40 samples so a casual --samples run
            # cannot commit a thin pin (the committed 2026-08-04
            # entries were measured at 40).
            for _ in range(8):
                gen_batch(prg, alphas, betas, s0s, Bound.LT_BETA)
            rates = []
            for _ in range(max(args.samples, 40)):
                t0 = time.perf_counter()
                gen_batch(prg, alphas, betas, s0s, Bound.LT_BETA)
                rates.append(k_keys / (time.perf_counter() - t0))
            rates = np.array(rates)
            keygen[tag] = {
                "keys_per_sec": round(float(np.median(rates)), 1),
                "band_keys_per_sec": [
                    round(float(np.percentile(rates, 10)), 1),
                    round(float(np.percentile(rates, 90)), 1)],
                "band": "p10-p90 of per-sample rates",
                "samples": len(rates),
                "keys": k_keys,
                "workload": (f"numpy gen_batch, K={k_keys} keys, "
                             f"N={N_BYTES}B domain, lam={lam}, LT_BETA, "
                             "single core"),
                "date": datetime.date.today().isoformat(),
                "loadavg_1min": round(os.getloadavg()[0], 2),
                **host_state(),
            }
            print(f"keygen {tag}: {keygen[tag]['keys_per_sec']:,.1f} "
                  "keys/s pinned")

    record = {
        **flagship,
        "shapes": shapes,
        "keygen": keygen,
    }
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps(record, indent=1))
    print(f"\npinned: flagship {flagship['evals_per_sec']:,.0f} evals/s, "
          f"n32 {shapes['n32']['evals_per_sec']:,.0f} evals/s -> {out}")


if __name__ == "__main__":
    main()
