"""Measure and pin the mic_bench single-core numpy-oracle denominator.

``mic_bench``'s ``vs_baseline`` compares served MIC points/s against
"what would the obviously-correct host implementation serve": the
single-core numpy protocol oracle (``protocols.oracle.mic_oracle``)
computing all m interval rows per point.  Same pinning discipline as
``cpu_baseline.py`` (CPU_BASELINE.md): fixed workload, warmup passes,
>= 40 timed samples, median pinned with the p10-p90 band and host state
recorded alongside, committed once — the denominator must not move
between bench runs.

Fixed workload: the mic_bench default shape — m=8 disjoint intervals on
the N=16-byte flagship domain, lam=16, a fixed 2048-point batch —
drawn from the same seed the bench uses, party-agnostic (the oracle
computes the reconstruction directly).

Writes the ``"protocols": {"mic_m8": ...}`` entry into
``benchmarks/cpu_baseline.json`` (other fields untouched) and prints
the record.

Usage: python benchmarks/protocols_baseline.py [--samples N]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

M_POINTS = 2048
M_INTERVALS = 8
LAM = 16
N_BYTES = 16


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=40)
    args = ap.parse_args()

    from benchmarks.cpu_baseline import host_state
    from dcf_tpu.protocols.oracle import mic_oracle

    rng = np.random.default_rng(2026)
    bounds = sorted(
        int.from_bytes(
            rng.integers(0, 256, N_BYTES, dtype=np.uint8).tobytes(), "big")
        for _ in range(2 * M_INTERVALS))
    intervals = [(bounds[2 * i], bounds[2 * i + 1])
                 for i in range(M_INTERVALS)]
    betas = rng.integers(0, 256, (M_INTERVALS, LAM), dtype=np.uint8)
    xs = rng.integers(0, 256, (M_POINTS, N_BYTES), dtype=np.uint8)

    for _ in range(4):  # warmup (turbo burst / cache warm)
        mic_oracle(xs, intervals, betas)
    rates = []
    for _ in range(max(args.samples, 8)):
        t0 = time.perf_counter()
        mic_oracle(xs, intervals, betas)
        rates.append(M_POINTS / (time.perf_counter() - t0))
    rates = np.array(rates)
    entry = {
        "points_per_sec": round(float(np.median(rates)), 1),
        "band_points_per_sec": [
            round(float(np.percentile(rates, 10)), 1),
            round(float(np.percentile(rates, 90)), 1)],
        "band": "p10-p90 of per-sample rates",
        "samples": len(rates),
        "batch_points": M_POINTS,
        "m": M_INTERVALS,
        "workload": (f"numpy mic_oracle, m={M_INTERVALS} disjoint "
                     f"intervals, N={N_BYTES}B domain, lam={LAM}, "
                     "single core, reconstruction (not one party)"),
        "date": datetime.date.today().isoformat(),
        **host_state(),
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cpu_baseline.json")
    with open(path) as f:
        pinned = json.load(f)
    pinned.setdefault("protocols", {})[f"mic_m{M_INTERVALS}"] = entry
    with open(path, "w") as f:
        json.dump(pinned, f, indent=1)
        f.write("\n")
    print(json.dumps(entry, indent=1))


if __name__ == "__main__":
    main()
