"""Frontier gather/repack probes — the cost structure behind the
prefix-sharing evaluator (backends.pallas_prefix, ROOFLINE.md round 5).

A batch of M random points shares the top k ~ log2(M) GGM walk levels;
expanding them once as a tree and walking only n-k levels saves
(k-2)/n of the walk work IF each point can fetch its (s, v, t) carry
from the 2^k-node frontier cheaply.  These probes price that fetch on
the real chip and record why the shipped design looks the way it does:

  take_rows8[k]   jnp.take of [2^k, 8]-int32 rows (s||v fused, 32 B) with
                  2^20 random indices.  ~3.4-3.7 ms for k <= 21, ~4x
                  CLIFF at 2^22 rows (the 128 MB table) ->
                  prefix_levels is clamped to 21.
  take_rows9      the same with 36 B rows: ~2x slower (non-power-of-2
                  row width) -> the t-bit is NOT a 9th column; it rides
                  in s's structurally-zero masked bit (plane 15, the
                  Hirose 8*lam-1 mask) at no gather cost.
  take_col        a single int32 column: ~7 ms — per-index cost
                  dominates, so SPLITTING the gather is the wrong move.
  xla_pack        best-of-breed XLA repack of gathered rows into the
                  walk kernel's bit-major planes: ~4.4 ms PER TABLE ->
                  the repack lives INSIDE the walk kernel instead
                  (ops.pallas_prefix.rows_to_state_planes: 5-step
                  butterfly bit transpose, ~0.5 ms/table, fused).
  relayout        the XLA [M, 8] -> [8, 32(rev), W] tile relayout that
                  remains outside the kernel: ~1 ms.

Net shipped cost at M = 2^20: gather+relayout ~4.4 ms ~= 6 walk levels
— the floor that caps config 2 (n=32, k=21) at ~80 M evals/s (1.86x the
from-root walk) instead of the ideal 32/11 = 2.9x, and the flagship
(n=128) at +13%.

Usage: python -m benchmarks.micro_gather [--logm 20]
Prints one JSON line per probe.
"""

from __future__ import annotations

import argparse
import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dcf_tpu.errors import ShapeError
from dcf_tpu.utils.benchtime import device_sync, measure_sync_rtt

WALK_MS_PER_LEVEL = 0.757  # RESULTS_r04 config-2: 24.3 ms / 32 levels


# ---------------------------------------------------------------------------
# In-kernel gather (round 6): the XLA `take` verdict was declared "priced
# dead for now" on XLA evidence alone; this is the idiomatic Pallas
# counter-candidate — scalar-prefetched indices + per-row HBM->VMEM DMAs
# kept n_flight deep so the gather engine always has copies in flight.
# ---------------------------------------------------------------------------


def _dma_gather_kernel(idx_ref, tbl_ref, out_ref, sems, *,
                       rows_per_block: int, n_flight: int):
    """One grid step gathers ``rows_per_block`` rows into its out block:
    row r's copy starts as soon as slot r % n_flight retires, so up to
    n_flight row DMAs are in flight at once (double buffering
    generalized n-deep)."""
    base = pl.program_id(0) * rows_per_block

    def copy_desc(r):
        return pltpu.make_async_copy(
            tbl_ref.at[pl.ds(idx_ref[base + r], 1)],
            out_ref.at[pl.ds(r, 1)],
            sems.at[r % n_flight])

    def body(r, carry):
        @pl.when(r >= n_flight)
        def _():  # retire this slot's previous copy before reuse
            copy_desc(r - n_flight).wait()
        copy_desc(r).start()
        return carry

    jax.lax.fori_loop(0, rows_per_block, body, 0)

    def drain(j, carry):
        copy_desc(rows_per_block - n_flight + j).wait()
        return carry

    jax.lax.fori_loop(0, min(n_flight, rows_per_block), drain, 0)


def pallas_dma_gather(tbl, idx, rows_per_block: int = 512,
                      n_flight: int = 8, interpret: bool = False):
    """Gather ``tbl[idx]`` ([2^k, 8] int32 rows) with per-row async DMAs
    from HBM, indices scalar-prefetched to SMEM.  Bit-identical to
    ``jnp.take(tbl, idx, axis=0)`` (tests/test_hybrid_prefix.py)."""
    m = idx.shape[0]
    if m % rows_per_block:
        raise ShapeError(f"m={m} not a multiple of {rows_per_block}")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m // rows_per_block,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],  # table in HBM
        out_specs=pl.BlockSpec((rows_per_block, 8),
                               lambda i, idx_ref: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((n_flight,))],
    )
    return pl.pallas_call(
        partial(_dma_gather_kernel, rows_per_block=rows_per_block,
                n_flight=n_flight),
        out_shape=jax.ShapeDtypeStruct((m, 8), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(idx, tbl)


def xla_pack(rows_i32):
    """Best XLA-side repack found (of 6 formulations tried): transpose
    the tiny axis first, replicate, per-row shift, minor-axis reduce.
    Kept as the measured justification for doing this in-kernel."""
    m = rows_i32.shape[0]
    u = jax.lax.bitcast_convert_type(rows_i32, jnp.uint32).T  # [4, M]
    rep = jnp.take(u, jnp.arange(128) // 32, axis=0)  # [128, M]
    sh = (jnp.arange(128, dtype=jnp.uint32) % 32)[:, None]
    bits = ((rep >> sh) & jnp.uint32(1)).astype(jnp.uint8)
    return jnp.sum(bits.reshape(128, m // 32, 32).astype(jnp.uint32)
                   << jnp.arange(32, dtype=jnp.uint32)[None, None, :],
                   axis=-1, dtype=jnp.uint32)


def _timed(fn, args, label, dispatches=32, reps=5):
    out = fn(*args)
    jax.tree_util.tree_map(device_sync, out)
    rtt = measure_sync_rtt(jax.tree_util.tree_leaves(out)[0])
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(dispatches):
            out = fn(*args)
        jax.tree_util.tree_map(device_sync, out)
        samples.append(
            max(time.perf_counter() - t0 - rtt, 1e-9) / dispatches)
    med = float(np.median(samples))
    mad = float(np.median(np.abs(np.array(samples) - med)))
    print(json.dumps({"probe": label, "ms": round(med * 1e3, 3),
                      "mad_ms": round(mad * 1e3, 3)}))
    return med


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--logm", type=int, default=20)
    ap.add_argument("--dispatches", type=int, default=32)
    args = ap.parse_args()
    m = 1 << args.logm

    rng = np.random.default_rng(7)
    dev = jax.devices()[0]
    print(json.dumps({"device": f"{dev.platform} "
                      f"{getattr(dev, 'device_kind', '')}", "m": m}))

    take = jax.jit(lambda t, i: jnp.take(t, i, axis=0))
    for logk in (16, 20, 21, 22):
        k = 1 << logk
        tbl = jnp.asarray(rng.integers(-(2**31), 2**31, (k, 8),
                                       dtype=np.int64).astype(np.int32))
        idx = jnp.asarray(rng.integers(0, k, (m,)).astype(np.int32))
        _timed(take, (tbl, idx), f"take_rows8_k{logk}", args.dispatches)

    k = 1 << min(args.logm, 20)
    idx = jnp.asarray(rng.integers(0, k, (m,)).astype(np.int32))
    tbl9 = jnp.asarray(rng.integers(-(2**31), 2**31, (k, 9),
                                    dtype=np.int64).astype(np.int32))
    _timed(take, (tbl9, idx), "take_rows9_k20", args.dispatches)
    col = jnp.asarray(rng.integers(-(2**31), 2**31, (k,),
                                   dtype=np.int64).astype(np.int32))
    _timed(jax.jit(lambda t, i: jnp.take(t, i)), (col, idx),
           "take_col_k20", args.dispatches)

    rows4 = jnp.asarray(rng.integers(-(2**31), 2**31, (m, 4),
                                     dtype=np.int64).astype(np.int32))
    t_pack = _timed(jax.jit(xla_pack), (rows4,), "xla_pack_one_table",
                    args.dispatches)

    tbl8 = jnp.asarray(rng.integers(-(2**31), 2**31, (k, 8),
                                    dtype=np.int64).astype(np.int32))

    def gather_relayout(t, i):
        rows = jnp.take(t, i, axis=0)
        return rows.T.reshape(8, m // 32, 32).transpose(0, 2, 1)[:, 31::-1]

    t_gr = _timed(jax.jit(gather_relayout), (tbl8, idx),
                  "gather_relayout_shipped", args.dispatches)

    # Round 6: the Pallas scalar-prefetch / per-row-DMA gather vs the XLA
    # take — the kernel-level candidate ROOFLINE round 5 left unpriced.
    # Off-TPU it runs under the interpreter on a reduced batch purely as
    # a correctness + disclosure record (an interpreter wall time says
    # nothing about the chip); on TPU it is the real measurement.
    interp = dev.platform != "tpu"
    logm_dma = min(args.logm, 12) if interp else args.logm
    m_dma = 1 << logm_dma
    idx_dma = jnp.asarray(
        rng.integers(0, k, (m_dma,)).astype(np.int32))
    t_dma = None
    try:
        fn_dma = jax.jit(partial(pallas_dma_gather, interpret=interp))
        got = fn_dma(tbl8, idx_dma)
        ok = bool(np.array_equal(np.asarray(got),
                                 np.asarray(jnp.take(tbl8, idx_dma,
                                                     axis=0))))
        t_dma = _timed(fn_dma, (tbl8, idx_dma),
                       "pallas_dma_gather_k20"
                       + ("_interpret" if interp else ""),
                       dispatches=1 if interp else args.dispatches,
                       reps=2 if interp else 5)
        t_take_dma = _timed(take, (tbl8, idx_dma),
                            "take_rows8_k20_same_batch",
                            dispatches=1 if interp else args.dispatches,
                            reps=2 if interp else 5)
        print(json.dumps({
            "probe": "pallas_dma_gather_verdict",
            "m": m_dma, "bit_exact_vs_take": ok,
            "interpret": interp,
            "kernel_ms": round(t_dma * 1e3, 3),
            "take_ms_same_batch": round(t_take_dma * 1e3, 3),
            "note": ("per-row 32 B HBM DMAs, scalar-prefetched indices, "
                     "8 in flight; interpreter numbers are a correctness "
                     "record only — see ROOFLINE round 6 for the "
                     "structural analysis and the chip repro command"),
        }))
    except Exception as e:  # fallback-ok: a Mosaic/interpreter gap must
        # not kill the XLA probes this file exists to record
        print(json.dumps({"probe": "pallas_dma_gather_k20",
                          "error": f"{type(e).__name__}: {e}"}))

    print(json.dumps({
        "probe": "verdict",
        "shipped_gather_relayout_ms": round(t_gr * 1e3, 3),
        "xla_pack_per_table_ms": round(t_pack * 1e3, 3),
        "walk_levels_equivalent": round(t_gr * 1e3 / WALK_MS_PER_LEVEL, 1),
        "note": ("gather+relayout ~= 6 walk levels: the floor that caps "
                 "config-2 prefix sharing at ~1.86x instead of 2.9x; "
                 "repack rides in-kernel (ops.pallas_prefix)"),
    }))


if __name__ == "__main__":
    main()
