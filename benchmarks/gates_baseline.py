"""Measure and pin the gate_bench single-core numpy-oracle denominators.

``gate_bench``'s ``vs_baseline`` compares served gate points/s against
"what would the obviously-correct host implementation serve": the
single-core numpy gate oracles (``protocols.fixedpoint``) computing the
CLEAR-input gate function — unmask, look up / truncate, and encode the
result into the same [M, lam] lane-broadcast payload the served path
delivers (the output contract is part of the work).  Same pinning
discipline as ``protocols_baseline.py`` / CPU_BASELINE.md: fixed
workload, warmup passes, >= 40 timed samples, median pinned with the
p10-p90 band and host state recorded alongside, committed once — the
denominator must not move between bench runs, and consumers attach
``vs_baseline`` only when the pin exists (no in-run fallback, the
mic_m8 no-transfer rule).

Fixed workloads (the gate_bench default shape — 16-bit domain, f=8
fractional bits, lam=16, a fixed 2048-point batch):

* ``gates.sigmoid_m8``: the m=8 spline table lookup
  (``sigmoid_fixed_oracle`` on the unmasked input + payload encode);
* ``gates.trunc``: the faithful truncation
  (``trunc_oracle`` + payload encode).

Writes the ``"gates": {...}`` entries into
``benchmarks/cpu_baseline.json`` (other fields untouched) and prints
the records.

Usage: python benchmarks/gates_baseline.py [--samples N]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

M_POINTS = 2048
M_PIECES = 8
LAM = 16
N_BITS = 16
F_BITS = 8


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=40)
    args = ap.parse_args()

    from benchmarks.cpu_baseline import host_state
    from dcf_tpu.protocols.fixedpoint import (
        encode_lanes, sigmoid_fixed_oracle, sigmoid_table, trunc_oracle)

    n_total = 1 << N_BITS
    rng = np.random.default_rng(2026)
    cuts, values = sigmoid_table(N_BITS, F_BITS, M_PIECES)
    r_sig = int(rng.integers(0, n_total))
    r_tr = int(rng.integers(0, n_total))
    x_hat = rng.integers(0, n_total, size=M_POINTS, dtype=np.int64)

    def run_sigmoid():
        y = sigmoid_fixed_oracle((x_hat - r_sig) % n_total, cuts, values)
        return encode_lanes(y, "add16", LAM)

    def run_trunc():
        y = trunc_oracle(x_hat, r_tr, F_BITS, N_BITS)
        return encode_lanes(y, "add16", LAM)

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cpu_baseline.json")
    with open(path) as f:
        pinned = json.load(f)
    workloads = {
        f"sigmoid_m{M_PIECES}": (
            run_sigmoid,
            f"numpy sigmoid_fixed_oracle (m={M_PIECES} spline table, "
            f"f={F_BITS}) on the unmasked input + add16 lane encode, "
            f"{N_BITS}-bit domain, lam={LAM}, single core, "
            "reconstruction (not one party)"),
        "trunc": (
            run_trunc,
            f"numpy trunc_oracle (f={F_BITS} faithful truncation) + "
            f"add16 lane encode, {N_BITS}-bit domain, lam={LAM}, "
            "single core, reconstruction (not one party)"),
    }
    for tag, (fn, desc) in workloads.items():
        for _ in range(8):  # warmup (turbo burst / cache warm)
            fn()
        rates = []
        for _ in range(max(args.samples, 8)):
            t0 = time.perf_counter()
            fn()
            rates.append(M_POINTS / (time.perf_counter() - t0))
        rates = np.array(rates)
        entry = {
            "points_per_sec": round(float(np.median(rates)), 1),
            "band_points_per_sec": [
                round(float(np.percentile(rates, 10)), 1),
                round(float(np.percentile(rates, 90)), 1)],
            "band": "p10-p90 of per-sample rates",
            "samples": len(rates),
            "batch_points": M_POINTS,
            "workload": desc,
            "date": datetime.date.today().isoformat(),
            **host_state(),
        }
        pinned.setdefault("gates", {})[tag] = entry
        print(json.dumps({tag: entry}, indent=1))
    with open(path, "w") as f:
        json.dump(pinned, f, indent=1)
        f.write("\n")


if __name__ == "__main__":
    main()
