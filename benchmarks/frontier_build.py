"""Measure the prefix backend's frontier build cost (``frontier_build_ms``).

The prefix-shared evaluator expands the top ``k`` GGM levels once per
(key, party) as a gather table cached with the CW image
(``backends/pallas_prefix.py``).  That expansion is untimed key-material
prep — correctly excluded from the eval clock, like criterion's setup —
but the "ships once, like the CW image" amortization claim needs a
magnitude attached (VERDICT round 5, item 7).  This probe measures it:
wall time from a cold ``put_bundle`` to the party-0 frontier table being
device-ready, per requested key count.

One JSON line per (K, k) config::

    {"bench": "frontier_build", "k_requested": 21, "k_effective": 21,
     "keys": 1, "frontier_build_ms": ..., "nodes": 2097153,
     "platform": "tpu", "interpret": false, "repro": "..."}

``k_effective`` can be below ``k_requested``: the backend shrinks k by
ceil(log2 K) for multi-key bundles (the gather cliff is on total stacked
rows, K * 2^k) — at K=8 a requested k=21 runs at k=18.  ``interpret``
discloses a Pallas-interpreter (no-TPU) run; such numbers bound nothing
about the chip and exist only so the claim is never quoted without an
environment tag.

Usage::

    python -m benchmarks.frontier_build --k 21 --keys 1,8 [--domain-bytes 4]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def measure(k: int, keys: int, nb: int, reps: int) -> dict:
    import jax

    from dcf_tpu.backends.pallas_prefix import PrefixPallasBackend
    from dcf_tpu.gen import random_s0s
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.spec import Bound

    lam = 16
    rng = np.random.default_rng(2026)
    ck = [rng.bytes(32), rng.bytes(32)]
    native = NativeDcf(lam, ck)
    alphas = rng.integers(0, 256, (keys, nb), dtype=np.uint8)
    betas = rng.integers(0, 256, (keys, lam), dtype=np.uint8)
    bundle = native.gen_batch(
        alphas, betas, random_s0s(keys, lam, rng), Bound.LT_BETA)
    interp = jax.devices()[0].platform != "tpu"
    samples = []
    k_eff = None
    for _ in range(max(reps, 1)):
        # Cold build each rep: a fresh backend so neither the frontier
        # cache nor the shipped CW image carries over; jit caches persist
        # process-wide, so reps after the first exclude trace/compile --
        # the median is the steady-state rebuild cost, the first sample
        # (logged) includes compilation.
        be = PrefixPallasBackend(lam, ck, prefix_levels=k, interpret=interp)
        be.put_bundle(bundle.for_party(0))
        k_eff = be._k()
        t0 = time.perf_counter()
        tbl = be._frontier_tables(0)
        tbl.block_until_ready()
        samples.append(time.perf_counter() - t0)
        log(f"  K={keys} k={k_eff}: sample {samples[-1] * 1e3:.1f} ms")
    med = float(np.median(samples))
    return {
        "bench": "frontier_build",
        "k_requested": k,
        "k_effective": k_eff,
        "keys": keys,
        "frontier_build_ms": round(med * 1e3, 1),
        "first_sample_ms": round(samples[0] * 1e3, 1),
        "samples": len(samples),
        # 2^{k+1} PRG node evaluations per key (levels 1..k plus the root
        # split), the quantity the build cost scales with.
        "nodes": keys * (1 << (k_eff + 1)),
        "domain_bytes": nb,
        "platform": jax.devices()[0].platform,
        "interpret": interp,
        "repro": (f"python -m benchmarks.frontier_build --k {k} "
                  f"--keys {keys} --domain-bytes {nb} --reps {reps}"),
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--k", type=int, default=21,
                   help="requested prefix depth (default 21, the gather "
                        "cliff cap)")
    p.add_argument("--keys", default="1,8",
                   help="comma-separated key counts (default 1,8)")
    p.add_argument("--domain-bytes", type=int, default=4,
                   help="domain width in bytes (default 4, the config-2 "
                        "shape; the frontier cost depends on k, not n)")
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args(argv)
    for keys in (int(s) for s in args.keys.split(",")):
        rec = measure(args.k, keys, args.domain_bytes, args.reps)
        print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
