"""Probe: does gate EMISSION ORDER of the BP113 S-box change Mosaic's
schedule quality?

The S-box runs at ~80% of peak VPU issue (micro_vpu.py); the serial
GF(2^4)-inversion middle bounds it.  Mosaic schedules the traced jaxpr
with limited reordering, so the order we emit gates in may shape register
pressure and issue slots.  This probe rebuilds BP113 as an explicit gate
list (verified against the hand-written evaluator) and times three
emission orders back-to-back:

  published   the Boyar-Peralta paper order (what sbox_planes_bp113 does)
  asap        levelized: all depth-k gates before any depth-k+1 gate
  greedy      pressure-aware list schedule: among ready gates, prefer ones
              that kill live values (Sethi-Ullman-ish)

Usage: python -m benchmarks.micro_sbox_order [--iters N]
"""

from __future__ import annotations

import argparse
import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from dcf_tpu.ops.sbox_circuit import sbox_planes_bp113
from dcf_tpu.utils.benchtime import device_sync as _sync

# Gate list: (name, op, a, b) with op in {"^", "&", "~^"}; inputs are
# x0..x7 (MSB-first: x0 = bits[7]) or prior gate names.
BP113_GATES = [
    ("y14", "^", "x3", "x5"), ("y13", "^", "x0", "x6"),
    ("y9", "^", "x0", "x3"), ("y8", "^", "x0", "x5"),
    ("t0", "^", "x1", "x2"), ("y1", "^", "t0", "x7"),
    ("y4", "^", "y1", "x3"), ("y12", "^", "y13", "y14"),
    ("y2", "^", "y1", "x0"), ("y5", "^", "y1", "x6"),
    ("y3", "^", "y5", "y8"), ("t1", "^", "x4", "y12"),
    ("y15", "^", "t1", "x5"), ("y20", "^", "t1", "x1"),
    ("y6", "^", "y15", "x7"), ("y10", "^", "y15", "t0"),
    ("y11", "^", "y20", "y9"), ("y7", "^", "x7", "y11"),
    ("y17", "^", "y10", "y11"), ("y19", "^", "y10", "y8"),
    ("y16", "^", "t0", "y11"), ("y21", "^", "y13", "y16"),
    ("y18", "^", "x0", "y16"),
    ("t2", "&", "y12", "y15"), ("t3", "&", "y3", "y6"),
    ("t4", "^", "t3", "t2"), ("t5", "&", "y4", "x7"),
    ("t6", "^", "t5", "t2"), ("t7", "&", "y13", "y16"),
    ("t8", "&", "y5", "y1"), ("t9", "^", "t8", "t7"),
    ("t10", "&", "y2", "y7"), ("t11", "^", "t10", "t7"),
    ("t12", "&", "y9", "y11"), ("t13", "&", "y14", "y17"),
    ("t14", "^", "t13", "t12"), ("t15", "&", "y8", "y10"),
    ("t16", "^", "t15", "t12"), ("t17", "^", "t4", "t14"),
    ("t18", "^", "t6", "t16"), ("t19", "^", "t9", "t14"),
    ("t20", "^", "t11", "t16"), ("t21", "^", "t17", "y20"),
    ("t22", "^", "t18", "y19"), ("t23", "^", "t19", "y21"),
    ("t24", "^", "t20", "y18"), ("t25", "^", "t21", "t22"),
    ("t26", "&", "t21", "t23"), ("t27", "^", "t24", "t26"),
    ("t28", "&", "t25", "t27"), ("t29", "^", "t28", "t22"),
    ("t30", "^", "t23", "t24"), ("t31", "^", "t22", "t26"),
    ("t32", "&", "t31", "t30"), ("t33", "^", "t32", "t24"),
    ("t34", "^", "t23", "t33"), ("t35", "^", "t27", "t33"),
    ("t36", "&", "t24", "t35"), ("t37", "^", "t36", "t34"),
    ("t38", "^", "t27", "t36"), ("t39", "&", "t29", "t38"),
    ("t40", "^", "t25", "t39"), ("t41", "^", "t40", "t37"),
    ("t42", "^", "t29", "t33"), ("t43", "^", "t29", "t40"),
    ("t44", "^", "t33", "t37"), ("t45", "^", "t42", "t41"),
    ("z0", "&", "t44", "y15"), ("z1", "&", "t37", "y6"),
    ("z2", "&", "t33", "x7"), ("z3", "&", "t43", "y16"),
    ("z4", "&", "t40", "y1"), ("z5", "&", "t29", "y7"),
    ("z6", "&", "t42", "y11"), ("z7", "&", "t45", "y17"),
    ("z8", "&", "t41", "y10"), ("z9", "&", "t44", "y12"),
    ("z10", "&", "t37", "y3"), ("z11", "&", "t33", "y4"),
    ("z12", "&", "t43", "y13"), ("z13", "&", "t40", "y5"),
    ("z14", "&", "t29", "y2"), ("z15", "&", "t42", "y9"),
    ("z16", "&", "t45", "y14"), ("z17", "&", "t41", "y8"),
    ("t46", "^", "z15", "z16"), ("t47", "^", "z10", "z11"),
    ("t48", "^", "z5", "z13"), ("t49", "^", "z9", "z10"),
    ("t50", "^", "z2", "z12"), ("t51", "^", "z2", "z5"),
    ("t52", "^", "z7", "z8"), ("t53", "^", "z0", "z3"),
    ("t54", "^", "z6", "z7"), ("t55", "^", "z16", "z17"),
    ("t56", "^", "z12", "t48"), ("t57", "^", "t50", "t53"),
    ("t58", "^", "z4", "t46"), ("t59", "^", "z3", "t54"),
    ("t60", "^", "t46", "t57"), ("t61", "^", "z14", "t57"),
    ("t62", "^", "t52", "t58"), ("t63", "^", "t49", "t58"),
    ("t64", "^", "z4", "t59"), ("t65", "^", "t61", "t62"),
    ("t66", "^", "z1", "t63"), ("s0", "^", "t59", "t63"),
    ("s6", "~^", "t56", "t62"), ("s7", "~^", "t48", "t60"),
    ("t67", "^", "t64", "t65"), ("s3", "^", "t53", "t66"),
    ("s4", "^", "t51", "t66"), ("s5", "^", "t47", "t65"),
    ("s1", "~^", "t64", "s3"), ("s2", "~^", "t55", "t67"),
]
OUTS = ["s7", "s6", "s5", "s4", "s3", "s2", "s1", "s0"]


def eval_gates(bits, ones, order):
    env = {f"x{i}": bits[7 - i] for i in range(8)}
    for name, op, a, b in order:
        if op == "^":
            env[name] = env[a] ^ env[b]
        elif op == "&":
            env[name] = env[a] & env[b]
        else:
            env[name] = env[a] ^ env[b] ^ ones
    return [env[s] for s in OUTS]


def order_asap():
    depth = {f"x{i}": 0 for i in range(8)}
    gates = []
    for g in BP113_GATES:
        depth[g[0]] = max(depth[g[2]], depth[g[3]]) + 1
        gates.append((depth[g[0]], g))
    gates.sort(key=lambda dg: dg[0])
    return [g for _, g in gates]


def order_greedy():
    """List schedule minimizing live values: prefer gates whose emission
    kills operands (last use), then deeper-critical-path gates."""
    remaining = list(BP113_GATES)
    users: dict = {}
    for g in BP113_GATES:
        for src in (g[2], g[3]):
            users.setdefault(src, set()).add(g[0])
    # critical-path height for tie-breaking
    height: dict = {}
    for g in reversed(BP113_GATES):
        height[g[0]] = 1 + max(
            (height.get(u, 0) for u in users.get(g[0], ())), default=0)
    done = {f"x{i}" for i in range(8)}
    out = []
    remaining_users = {k: set(v) for k, v in users.items()}
    while remaining:
        ready = [g for g in remaining if g[2] in done and g[3] in done]
        def score(g):
            kills = sum(
                1 for src in {g[2], g[3]}
                if remaining_users.get(src, set()) == {g[0]})
            return (-kills, -height.get(g[0], 0))
        g = min(ready, key=score)
        remaining.remove(g)
        out.append(g)
        done.add(g[0])
        for src in (g[2], g[3]):
            remaining_users.get(src, set()).discard(g[0])
    return out


def _kernel(x_ref, y_ref, *, iters: int, order):
    ones = jnp.int32(-1)

    def body(i, ps):
        return tuple(eval_gates(list(ps), ones, order))

    out = jax.lax.fori_loop(0, iters, body, tuple(x_ref[i] for i in range(8)))
    acc = out[0]
    for p in out[1:]:
        acc = acc ^ p
    y_ref[0] = acc


def _time(order, x, out_shape, iters, reps=4):
    f = jax.jit(lambda a: pl.pallas_call(
        partial(_kernel, iters=iters, order=order), out_shape=out_shape)(a))
    _sync(f(x))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _sync(f(x))
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=6_000_000)
    ap.add_argument("--lanes", type=int, default=256)
    args = ap.parse_args()

    # Verify the gate list and both reorders against the reference impl.
    rng = np.random.default_rng(0)
    xs = np.arange(256, dtype=np.uint16)
    bits = [((xs >> i) & 1).astype(bool) for i in range(8)]
    ones = np.ones(256, dtype=bool)
    want = sbox_planes_bp113(bits, ones)
    for nm, order in (("published", BP113_GATES), ("asap", order_asap()),
                      ("greedy", order_greedy())):
        got = eval_gates(bits, ones, order)
        assert all(np.array_equal(g, w) for g, w in zip(got, want)), nm

    x = jnp.asarray(rng.integers(-(2**31), 2**31, (8, 16, args.lanes),
                                 dtype=np.int64).astype(np.int32))
    out = jax.ShapeDtypeStruct((1, 16, args.lanes), jnp.int32)
    for nm, order in (("published", BP113_GATES), ("asap", order_asap()),
                      ("greedy", order_greedy())):
        t1 = _time(order, x, out, args.iters)
        t2 = _time(order, x, out, 2 * args.iters)
        ns = max(t2 - t1, 1e-9) / args.iters * 1e9
        tera = 113 * 16 * args.lanes / ns / 1e3
        print(json.dumps({"order": nm, "ns_per_sbox": round(ns, 2),
                          "tera_ops": round(tera, 3)}))


if __name__ == "__main__":
    main()
