"""MXU probe: can the AES linear layer ride the (idle) matrix unit?

Round 3 priced every VPU-side lever and declared ~11.4 ns/lane/encryption
the cipher's floor — with one unit left unpriced: the MXU sits idle by
construction (ROOFLINE.md).  The round's linear layer + ARK is GF(2)-linear
on the 128 bit-major planes (~35% of cipher time, 1.1 us of 3.11 us per
[128, 256] application), and this repo already runs GF(2)-affine maps as
int8/bf16 matmuls (backends/large_lambda.py wide part).  This probe prices
the same trick INSIDE the cipher:

    out = M . sb  over GF(2),  M in {0,1}^(128x128)

as  unpack planes to one-bit columns -> bf16 matmul on the MXU (sums <=
128 are exact in bf16 x bf16 -> f32) -> parity (& 1) -> repack to words.

The catch is the data format: the VPU formulation works on PACKED words
(32 points per 32-bit lane), while a matmul needs each GF(2) component as
its own element — a 32x element blow-up on both sides of the MXU.  The
probe therefore measures the components separately (unpack / matmul /
parity+repack) plus the full mxu-linear cipher against the shipped v3
cipher, so the ledger can attribute where the time goes.

Matrix derivation: M is built numerically by pushing the 128 basis planes
through the v2 block formulation of ShiftRows∘MixColumns (ops/
aes_bitsliced.py:233-253) — reference semantics /root/reference/src/
prg.rs:42-73 via the AES-256 rounds — and verified bit-exactly against
the shipped cipher here AND in tests/test_mxu_probe.py.

Usage: python -m benchmarks.micro_mxu [--lanes 128] [--iters N]
Prints one JSON line per probe.  Run on the TPU (the CPU interpreter
numbers are meaningless for pricing).
"""

from __future__ import annotations

import argparse
import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from dcf_tpu.ops.aes_bitsliced import (
    _MCSR_PERMS,
    _SR_PERM,
    _xt_blocks,
    aes256_encrypt_planes_bitmajor,
    aes_walk_cipher_v3,
    prep_rk_bitmajor_v3,
    round_key_masks_bitmajor,
)
from dcf_tpu.ops.sbox_circuit import sbox_planes_bp113 as sbox_planes

__all__ = ["linear_layer_matrices", "aes256_mxu_linear"]


def linear_layer_matrices() -> tuple[np.ndarray, np.ndarray]:
    """(M, M_final): the GF(2) matrices of the AES round linear layer
    (ShiftRows∘MixColumns) and the final round's ShiftRows, over bit-major
    planes p' = bit*16 + byte.  out = M @ in mod 2; entries 0/1."""
    eye = np.eye(128, dtype=np.uint32)
    blocks = [eye[16 * i:16 * (i + 1)] for i in range(8)]  # bit i planes
    xb = _xt_blocks(blocks)
    p0, p1, p2, p3 = (_MCSR_PERMS[d] for d in range(4))
    rows = [
        xb[i][p0] ^ (xb[i] ^ blocks[i])[p1] ^ blocks[i][p2] ^ blocks[i][p3]
        for i in range(8)
    ]
    m = np.concatenate(rows, axis=0)  # [128, 128]
    m_final = np.concatenate([blocks[i][_SR_PERM] for i in range(8)], axis=0)
    return m, m_final


def _unpack_bits(sb, l: int):
    """int32 [128, L] packed planes -> int32 [128, 32L] one-bit columns
    (column k*L + l = bit k of word-column l)."""
    return jnp.concatenate(
        [(sb >> k) & jnp.int32(1) for k in range(32)], axis=1)


def _repack_bits(p, l: int):
    """int32 [128, 32L] one-bit columns -> packed int32 [128, L]."""
    acc = p[:, :l]
    for k in range(1, 32):
        acc = acc | (p[:, k * l:(k + 1) * l] << k)
    return acc


def _mxu_apply(m_bf, sb, l: int):
    """One GF(2) matmul application: unpack -> MXU bf16 dot -> parity ->
    repack.  Exact: products are 0/1 and row sums <= 128 < 256, inside
    bf16's exact-integer range, accumulated in f32."""
    u = _unpack_bits(sb, l).astype(jnp.bfloat16)
    y = jax.lax.dot(m_bf, u, preferred_element_type=jnp.float32)
    return _repack_bits(y.astype(jnp.int32) & jnp.int32(1), l)


def aes256_mxu_linear(rk_all, state, m_bf, m_final_bf):
    """AES-256 with the round linear layer + final ShiftRows on the MXU;
    S-box and ARK stay on the VPU.  Bit-identical to
    aes256_encrypt_planes_bitmajor (tests/test_mxu_probe.py)."""
    l = state.shape[-1]
    ones = jnp.int32(-1)

    def sub(s):
        s3 = s.reshape(8, 16, l)
        return jnp.stack(sbox_planes([s3[i] for i in range(8)], ones)
                         ).reshape(128, l)

    s = state ^ rk_all[0]
    for rnd in range(1, 14):
        s = _mxu_apply(m_bf, sub(s), l) ^ rk_all[rnd]
    return _mxu_apply(m_final_bf, sub(s), l) ^ rk_all[14]


# --------------------------- on-chip probes ---------------------------------


def _cipher_kernel(rk_ref, m_ref, mf_ref, x_ref, y_ref, *, iters: int,
                   variant: str):
    ones = jnp.int32(-1)
    rk = rk_ref[:]
    l = x_ref.shape[-1]
    if variant == "v3":
        rk_p = prep_rk_bitmajor_v3(jnp, rk)

        def body(i, s):
            return aes_walk_cipher_v3(jnp, rk_p, s, ones)
    else:
        m_bf = m_ref[:]
        mf_bf = mf_ref[:]

        def body(i, s):
            return aes256_mxu_linear(rk, s, m_bf, mf_bf)

    y_ref[:] = jax.lax.fori_loop(0, iters, body, x_ref[:])


def _component_kernel(m_ref, x_ref, y_ref, *, iters: int, stage: str):
    """Component attribution: each stage loops on its own output so the
    chain stays data-dependent (not hoistable)."""
    l = x_ref.shape[-1]
    m_bf = m_ref[:]

    if stage == "unpack_repack":
        def body(i, s):  # conversions only, no MXU
            return _repack_bits(_unpack_bits(s, l), l) ^ jnp.int32(i)
    elif stage == "matmul":
        def body(i, s):  # MXU only: one unpacked-width bf16 dot + parity
            y = jax.lax.dot(m_bf, s.astype(jnp.bfloat16),
                            preferred_element_type=jnp.float32)
            return y.astype(jnp.int32) & jnp.int32(1) | (s & jnp.int32(2))
    else:  # full linear application
        def body(i, s):
            return _mxu_apply(m_bf, s, l) ^ jnp.int32(i)

    y_ref[:] = jax.lax.fori_loop(0, iters, body, x_ref[:])


def _sync(y) -> None:
    np.asarray(jnp.max(y.reshape(-1)[-8:].astype(jnp.int32)))


def _time_one(fn, args, out_shape, reps: int = 3) -> float:
    f = jax.jit(lambda *a: pl.pallas_call(fn, out_shape=out_shape)(*a))
    _sync(f(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _sync(f(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _slope(fn_builder, args, out_shape, iters: int):
    """Seconds per `iters` iterations from a 2x loop-count slope, like
    micro_vpu — but self-calibrating: the loop count escalates until the
    slope itself exceeds 0.25 s, so the ~100 ms tunnel-RTT jitter cannot
    masquerade as the measurement (at small counts the raw slope of these
    sub-us bodies reads 0.0)."""
    while True:
        t1 = _time_one(fn_builder(iters), args, out_shape)
        t2 = _time_one(fn_builder(2 * iters), args, out_shape)
        delta = t2 - t1
        if delta > 0.25 or iters >= 2_000_000:
            return max(delta, 1e-9) / iters, iters, t1
        iters *= 8


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lanes", type=int, default=128,
                    help="packed lane width L (the unpacked width is 32L; "
                         "128 keeps the f32 intermediates in VMEM)")
    ap.add_argument("--iters", type=int, default=200)
    args = ap.parse_args()
    lanes, iters = args.lanes, args.iters
    rng = np.random.default_rng(0)

    m, m_final = linear_layer_matrices()

    # Host correctness gate before timing anything.
    rk = round_key_masks_bitmajor(bytes(range(32)))
    st = rng.integers(-(2 ** 31), 2 ** 31, (128, 8), dtype=np.int64
                      ).astype(np.int32)
    want = aes256_encrypt_planes_bitmajor(
        np, rk.view(np.uint32), st.view(np.uint32), np.uint32(0xFFFFFFFF))
    got = np.asarray(aes256_mxu_linear(
        jnp.asarray(rk), jnp.asarray(st), jnp.asarray(m, jnp.bfloat16),
        jnp.asarray(m_final, jnp.bfloat16)))
    assert np.array_equal(got.view(np.uint32), want), \
        "mxu-linear cipher does not match the shipped cipher"
    print(json.dumps({"probe": "correctness", "ok": True}))

    rk_j = jnp.asarray(rk)
    m_bf = jnp.asarray(m, jnp.bfloat16)
    mf_bf = jnp.asarray(m_final, jnp.bfloat16)
    st_j = jnp.asarray(rng.integers(-(2 ** 31), 2 ** 31, (128, lanes),
                                    dtype=np.int64).astype(np.int32))
    out = jax.ShapeDtypeStruct((128, lanes), jnp.int32)

    for variant in ("v3", "mxu"):
        per_app, eff, t1 = _slope(
            lambda it: partial(_cipher_kernel, iters=it, variant=variant),
            (rk_j, m_bf, mf_bf, st_j), out, iters)
        print(json.dumps({
            "probe": f"cipher_{variant}", "lanes": lanes,
            "us_per_application": round(per_app * 1e6, 3),
            "ns_per_lane_per_enc": round(per_app / (32 * lanes) * 1e9, 3),
            "iters": eff, "t_single": round(t1, 4)}))

    st_wide = jnp.asarray(rng.integers(0, 2, (128, 32 * lanes),
                                       dtype=np.int64).astype(np.int32))
    out_wide = jax.ShapeDtypeStruct((128, 32 * lanes), jnp.int32)
    for stage, a, o in (("unpack_repack", st_j, out),
                        ("matmul", st_wide, out_wide),
                        ("linear_full", st_j, out)):
        per_app, eff, t1 = _slope(
            lambda it: partial(_component_kernel, iters=it, stage=stage),
            (m_bf, a), o, iters)
        print(json.dumps({
            "probe": stage, "lanes": lanes,
            "us_per_application": round(per_app * 1e6, 3),
            "iters": eff, "t_single": round(t1, 4)}))


if __name__ == "__main__":
    main()
