"""Microbenchmark: byte-permutation styles on [16, L] bit-blocks.

The v3 cipher's linear layers run at ~48% of peak VPU issue while the
S-box runs at ~80% (micro_vpu.py) — the gap is the permutation copies
(slice+concat chains).  This probe prices the candidate encodings of a
16-row byte permutation so the kernel can pick the cheapest:

  xor3        3-term XOR, no permutation (the floor: pure compute)
  generic16   16 single-row slices + concat (the v3 final realign)
  roll8       concat(x[8:], x[:8]) — one 2-part roll
  nearroll    a real v3 round-term permutation (2D torus translation,
              8 contiguous runs -> 8-part concat)
  maskroll    (x & Me) | (roll8(x) & Mo) — the shear decomposition of
              the drift perm sr^2 (see aes_bitsliced v4 notes)
  translate2  (roll_a(x) & M1) | (roll_b(x) & M2) — 2-roll form of a
              2D torus translation (candidate round-term encoding)

Usage: python -m benchmarks.micro_perm [--lanes 256] [--iters N]
"""

from __future__ import annotations

import argparse
import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from dcf_tpu.ops.aes_bitsliced import _V3_TERM_PERMS
from dcf_tpu.utils.benchtime import device_sync as _sync

GENERIC = [0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12, 5, 14, 7]  # sr^2
# A REAL v3 round-term permutation (2D torus translation, 8 contiguous
# runs under _perm_concat) — the thing the kernel actually pays for.
NEARROLL = [int(i) for i in _V3_TERM_PERMS[0][0]]


def _perm_concat(x, perm):
    parts = []
    i = 0
    while i < len(perm):
        j = i
        while j + 1 < len(perm) and perm[j + 1] == perm[j] + 1:
            j += 1
        parts.append(x[perm[i]:perm[j] + 1])
        i = j + 1
    return jnp.concatenate(parts, axis=0)


def _kernel(x_ref, m_ref, y_ref, *, iters: int, style: str):
    me = m_ref[0]
    mo = m_ref[1]

    def step(_i, s):
        if style == "xor3":
            return s ^ me ^ mo
        if style == "generic16":
            return _perm_concat(s, GENERIC) ^ me
        if style == "roll8":
            return jnp.concatenate([s[8:], s[:8]], axis=0) ^ me
        if style == "nearroll":
            return _perm_concat(s, NEARROLL) ^ me
        if style == "maskroll":
            r = jnp.concatenate([s[8:], s[:8]], axis=0)
            return (s & me) | (r & mo)
        if style == "translate2":
            ra = jnp.concatenate([s[5:], s[:5]], axis=0)
            rb = jnp.concatenate([s[9:], s[:9]], axis=0)
            return (ra & me) | (rb & mo)
        # api-edge: probe-harness style-name contract (bench-only CLI)
        raise ValueError(style)

    y_ref[:] = jax.lax.fori_loop(0, iters, step, x_ref[:])


def _time(style, x, m, out_shape, iters, reps=3):
    f = jax.jit(lambda *a: pl.pallas_call(
        partial(_kernel, iters=iters, style=style),
        out_shape=out_shape)(*a))
    _sync(f(x, m))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _sync(f(x, m))
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lanes", type=int, default=256)
    ap.add_argument("--iters", type=int, default=2_000_000)
    args = ap.parse_args()
    lanes, iters = args.lanes, args.iters
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(-(2**31), 2**31, (16, lanes),
                                 dtype=np.int64).astype(np.int32))
    m = jnp.asarray(rng.integers(-(2**31), 2**31, (2, 16, lanes),
                                 dtype=np.int64).astype(np.int32))
    out = jax.ShapeDtypeStruct((16, lanes), jnp.int32)
    for style in ("xor3", "generic16", "roll8", "nearroll", "maskroll",
                  "translate2"):
        t1 = _time(style, x, m, out, iters)
        t2 = _time(style, x, m, out, 2 * iters)
        slope = max(t2 - t1, 1e-9)
        ns = slope / iters * 1e9
        print(json.dumps({"style": style, "ns_per_step": round(ns, 3),
                          "t1": round(t1, 3)}))


if __name__ == "__main__":
    main()
