"""Driver benchmark: flagship DCF batch-eval throughput on the local chip.

Workload: the reference's headline bench (`/root/reference/benches/
dcf_batch_eval.rs:17-39`) scaled up — one DCF key, N=16-byte domain
(n=128 scan levels), lam=16-byte range, 2^20 random points, party-0
evaluation.  Metric: DCF evals/sec/chip, with bit-exact parity checked
against the C++ host core.

Methodology (criterion analog, `dcf_batch_eval.rs:35-39`):
  * setup (untimed): keys + points staged in HBM — criterion likewise
    builds xs/ys in RAM outside the timed closure;
  * timed: the eval itself, sample_size timed samples after a separate
    warmup, forced to completion via a digest fetch (`block_until_ready`
    does not block on the tunneled device this runs under);
  * reported: median evals/s (+ MAD on stderr).  The result shares stay
    in HBM, where a downstream secure-computation consumer would read
    them — host round-trips through the development tunnel (~25 MB/s)
    are an artifact of this environment, not of the chip, and are
    reported separately on stderr.

Backend: the prefix-shared Pallas evaluator (backends.pallas_prefix —
the top-21 walk levels expanded once per key as a cached tree frontier,
per-point carries gathered, 107 levels walked; measured +13% over the
from-root walk kernel at this shape); falls back to the from-root Pallas
walk kernel, then the XLA bitsliced path, with a logged warning if
Mosaic compilation fails at any stage.

Baseline: the single-core C++ eval rate measured in-process (the stand-in
for single-core Rust per BASELINE.md — same AES-NI instruction path the
`aes` crate uses).  `vs_baseline` is the speedup over it; the north-star
target is >= 100x.

Prints exactly ONE line of JSON to stdout; progress goes to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

LAM = 16
N_BYTES = 16
M_TPU = 1 << 20  # accelerator batch (points)
M_CPU = 1 << 13  # single-core baseline batch (scaled up to a rate)
M_PARITY = 4096  # bit-exact C++-anchor subset (device parity covers all)
SAMPLES = 6  # 128 dispatches each (~12.5s); 6 samples keep the run ~75s


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def rtt_corrected_times(raw_samples, rtt_s, iters):
    """Apply the once-per-sample sync-RTT correction; returns
    (per-dispatch times, clamped count).

    A sample whose whole duration is below the measured RTT means the
    correction dominated it — that sample is meaningless, so it is
    EXCLUDED from the headline median/MAD (and disclosed via the
    ``clamped_samples`` JSON field), never floored into a fake
    near-zero time (ADVICE.md finding 1; regression-locked by
    tests/test_cli.py).
    """
    times, clamped = [], 0
    for raw in raw_samples:
        net = raw - rtt_s
        if net <= 0:
            clamped += 1
            continue
        times.append(net / iters)
    return times, clamped


def run_tpu_suite() -> str:
    """Run the on-hardware test lane (tests/test_tpu.py: all four compiled
    Mosaic kernels + DeviceKeyGen + the sharded wrappers vs the numpy
    oracle) in a subprocess and return its one-line verdict.

    Runs BEFORE this process touches the accelerator so the subprocess has
    the chip to itself during its compiles.
    """
    import subprocess

    env = dict(os.environ)
    env["DCF_TPU_TESTS"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "-m", "tpu", "-q"],
            capture_output=True, text=True, env=env, timeout=1200,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return "timeout"
    tail = [ln for ln in proc.stdout.splitlines()
            if " passed" in ln or " failed" in ln or " error" in ln]
    return tail[-1].strip() if tail else f"rc={proc.returncode}"


def main() -> None:
    from dcf_tpu.gen import random_s0s
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.spec import Bound

    log("on-TPU test lane (compiled kernels vs oracle) ...")
    tpu_tests = run_tpu_suite()
    log(f"on-TPU test lane: {tpu_tests}")

    rng = np.random.default_rng(2026)
    cipher_keys = [rng.bytes(32), rng.bytes(32)]
    native = NativeDcf(LAM, cipher_keys)
    log(f"native core: AES-NI={native.has_aesni}")

    alphas = rng.integers(0, 256, (1, N_BYTES), dtype=np.uint8)
    betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
    bundle = native.gen_batch(alphas, betas, random_s0s(1, LAM, rng), Bound.LT_BETA)
    xs = rng.integers(0, 256, (M_TPU, N_BYTES), dtype=np.uint8)

    # --- single-core CPU baseline (Rust stand-in).  The vs_baseline
    # DENOMINATOR is the pinned canonical number measured once under the
    # protocol in benchmarks/CPU_BASELINE.md (fixed batch, median of >= 10
    # in-process samples, host state recorded) and committed as
    # benchmarks/cpu_baseline.json — the round-3 in-run denominator swung
    # 86-112k evals/s run-to-run, moving the headline ratio through the
    # 100x mark on noise.  A short in-run measurement is kept as a drift
    # check and as the fallback when the artifact is absent. ---
    cpu_samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        y_cpu = native.eval(0, bundle, xs[:M_CPU], num_threads=1)
        cpu_samples.append(time.perf_counter() - t0)
    inrun_rate = M_CPU / float(np.median(cpu_samples))
    baseline_src = "in-run (no pinned artifact)"
    cpu_rate = inrun_rate
    try:
        with open("benchmarks/cpu_baseline.json") as f:
            pinned = json.load(f)
    except OSError:
        pinned = None  # genuinely absent: in-run fallback is honest
    if pinned is not None:
        # A PRESENT artifact must parse: silently falling back to the
        # noisy in-run denominator would defeat the pin.
        cpu_rate = float(pinned["evals_per_sec"])
        baseline_src = f"pinned ({pinned['date']}, CPU_BASELINE.md protocol)"
    log(f"cpu single-core: baseline {cpu_rate:,.0f} evals/s "
        f"[{baseline_src}]; in-run drift check (median of 3): "
        f"{inrun_rate:,.0f} ({inrun_rate / cpu_rate - 1:+.1%})")

    # --- accelerator backend: prefix-shared Pallas evaluator with
    # from-root-walk and XLA-bitsliced fallbacks ---
    from dcf_tpu.utils.provision import enable_compile_cache

    enable_compile_cache()
    import jax

    from dcf_tpu.utils.benchtime import DISPATCHES_PER_SAMPLE as ITERS
    from dcf_tpu.utils.benchtime import device_sync as sync

    dev = jax.devices()[0]
    log(f"jax device: {dev.platform} {getattr(dev, 'device_kind', '')}")

    party_bundle = bundle.for_party(0)

    def bring_up(cls):
        """Parity gates + staging + full-batch warmup; any Mosaic/hardware
        failure (including ones that only appear at the full 2^20 grid)
        surfaces here, inside the fallback guard.

        Parity is two-layered: a C++-core byte anchor on the first
        M_PARITY points (the cross-implementation check) and a FULL
        on-device two-party reconstruction of all 2^20 points against the
        comparison function (party 1 evaluated once on a second backend
        instance, outside the timed region).
        """
        backend = cls(LAM, cipher_keys)
        backend.put_bundle(party_bundle)
        y_small = backend.eval(0, xs[:M_PARITY])
        parity_ok = bool(np.array_equal(y_small[0], y_cpu[0, :M_PARITY]))
        log(f"parity vs C++ (first {M_PARITY} pts): "
            f"{'OK' if parity_ok else 'MISMATCH'}")
        if not parity_ok:
            raise SystemExit("bit-exact parity check failed")
        t0 = time.perf_counter()
        staged = backend.stage(xs)
        sync(staged["x_mask"])
        log(f"stage 2^20 xs (h2d + bit transpose): {time.perf_counter() - t0:.2f}s")
        t0 = time.perf_counter()
        y = backend.eval_staged(0, staged)
        sync(y)
        log(f"warmup (compile + first run): {time.perf_counter() - t0:.1f}s")
        backend.staged_to_bytes(y, 32)  # compile the d2h conversion untimed
        be1 = cls(LAM, cipher_keys)
        be1.put_bundle(bundle.for_party(1))
        y1 = be1.eval_staged(1, staged)  # the x image is party-independent
        mism = int(backend.points_mismatch_count(
            y, y1, alphas[0].tobytes(), betas[0].tobytes(), staged))
        log(f"parity (device, all {M_TPU} pts two-party): "
            f"{mism} mismatches")
        if mism:
            raise SystemExit("full on-device parity check failed")
        return backend, staged

    # Imported INSIDE the guard: a host whose jax build lacks the Pallas
    # TPU modules must fall back at import time too, not abort benchless.
    candidates = (("prefix", "dcf_tpu.backends.pallas_prefix",
                   "PrefixPallasBackend"),
                  ("pallas", "dcf_tpu.backends.pallas_backend",
                   "PallasBackend"),
                  ("bitsliced", "dcf_tpu.backends.jax_bitsliced",
                   "BitslicedBackend"))
    for pos, (name, mod, clsname) in enumerate(candidates):
        try:
            import importlib

            cls = getattr(importlib.import_module(mod), clsname)
            backend, staged = bring_up(cls)
            break
        except SystemExit:  # a failed parity gate is final, not a fallback
            raise
        except Exception as e:  # imports / Mosaic lowering / hardware
            if pos == len(candidates) - 1:
                raise
            log(f"WARNING: {name} backend failed ({type(e).__name__}: "
                f"{e}); falling back to {candidates[pos + 1][0]}")
    log(f"backend: {name}")

    # --- timed samples (ITERS dispatches per sample, criterion-style).
    # Each sample carries exactly one digest-fetch sync, whose ~85-155ms
    # round-trip is the DEV TUNNEL's latency, not chip work (ROOFLINE.md
    # "sync-starved timing"); it is measured bare here and subtracted
    # once per sample so the metric is the chip rate. ---
    from dcf_tpu.utils.benchtime import measure_sync_rtt

    rtt = measure_sync_rtt(staged["x_mask"], reps=5)
    log(f"bare sync RTT: {rtt * 1e3:.0f} ms "
        "(tunnel artifact; subtracted once per sample)")
    raw_samples = []
    for i in range(SAMPLES):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            y = backend.eval_staged(0, staged)
        sync(y)
        raw_samples.append(time.perf_counter() - t0)
    # The RTT was measured once before the loop and swings 85-155ms day
    # to day; rtt_corrected_times drops (never floors) any sample the
    # correction dominates and the count is disclosed in the JSON.
    times, clamped = rtt_corrected_times(raw_samples, rtt, ITERS)
    if clamped:
        log(f"WARNING: measured RTT ({rtt * 1e3:.0f} ms) exceeded "
            f"{clamped} whole sample(s); dropped from the headline "
            "median — treat those samples as unreliable")
    if not times:
        raise SystemExit(
            f"all {SAMPLES} samples clamped by the RTT correction; the "
            "tunnel is too noisy for a meaningful rate — rerun")
    times_a = np.array(times)
    med = float(np.median(times_a))
    mad = float(np.median(np.abs(times_a - med)))
    log(f"samples (s/eval, {ITERS} iters each): "
        f"{' '.join(f'{t:.3f}' for t in times)}")
    log(f"median {med * 1e3:.1f} ms +- MAD {mad * 1e3:.1f} ms "
        f"-> {M_TPU / med:,.0f} evals/s")
    dev_rate = M_TPU / med

    # --- result download cost (reported, not part of the chip metric) ---
    t0 = time.perf_counter()
    y_host = backend.staged_to_bytes(y, M_TPU)
    d2h_s = time.perf_counter() - t0
    log(f"full result to host (convert + d2h 16MB via tunnel): {d2h_s:.2f}s "
        f"-> end-to-end incl. download = {M_TPU / (med + d2h_s):,.0f} evals/s")
    if not np.array_equal(y_host[0, :M_PARITY], y_cpu[0, :M_PARITY]):
        raise SystemExit("staged-path parity check failed")

    # No overlapped/pipelined delivery variant: measured both ways on the
    # dev tunnel and retired.  A 2-half double-buffer with untimed warmup
    # and copy_to_host_async beat the single-shot fetch on a degraded
    # 3.4 MB/s tunnel day (4.53 s vs 4.73 s) but lost 2.1x on an
    # 8.5 MB/s day (4.01 s vs 1.87 s) — the tunnel's d2h does not
    # pipeline reliably, so the "overlap" tracks tunnel weather, not the
    # chip.  The honest end-to-end delivery number is the single-shot
    # line above; on a real host NIC (where transfer is cheap and
    # pipelinable) overlap is the obvious deployment pattern but is not
    # measurable through this environment.

    print(
        json.dumps(
            {
                "metric": "dcf_batch_eval_evals_per_sec_per_chip",
                "value": round(dev_rate, 1),
                "unit": (
                    "evals/s (n=128, lam=16B, 1 key x 2^20 points, party 0, "
                    f"{name} kernel, median of {len(times)}/{SAMPLES})"
                ),
                "vs_baseline": round(dev_rate / cpu_rate, 2),
                "vs_baseline_band": [
                    round(M_TPU / (med + mad) / cpu_rate, 2),
                    round(M_TPU / max(med - mad, 1e-9) / cpu_rate, 2),
                ],
                "baseline": baseline_src,
                "parity": (
                    f"full (device, {M_TPU} pts two-party) + "
                    f"C++ {M_PARITY}-pt anchor"
                ),
                "tpu_tests": tpu_tests,
                # 0 in a healthy run; nonzero means the RTT correction
                # dominated that many samples and the rate is unreliable.
                "clamped_samples": clamped,
            }
        )
    )


if __name__ == "__main__":
    os.chdir(os.path.dirname(os.path.abspath(__file__)))
    main()
