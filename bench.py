"""Driver benchmark: flagship DCF batch-eval throughput on the local chip.

Workload: the reference's headline bench (`/root/reference/benches/
dcf_batch_eval.rs:17-39`) scaled up — one DCF key, N=16-byte domain
(n=128 scan levels), lam=16-byte range, a large batch of random points,
party-0 evaluation.  Metric: DCF evals/sec/chip on the accelerator
backend, with bit-exact parity checked against the C++ host core.

Baseline: the single-core C++ eval rate measured in-process (the stand-in
for single-core Rust per BASELINE.md — same AES-NI instruction path the
`aes` crate uses).  `vs_baseline` is the speedup over it; the north-star
target is >= 100x.

Prints exactly ONE line of JSON to stdout; progress goes to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

LAM = 16
N_BYTES = 16
M_TPU = 1 << 20  # accelerator batch (points)
M_CPU = 1 << 13  # single-core baseline batch (scaled up to a rate)
M_PARITY = 4096  # bit-exact check subset
TIMED_REPS = 3


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    from dcf_tpu.backends.jax_bitsliced import BitslicedBackend
    from dcf_tpu.gen import random_s0s
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.spec import Bound

    rng = np.random.default_rng(2026)
    cipher_keys = [rng.bytes(32), rng.bytes(32)]
    native = NativeDcf(LAM, cipher_keys)
    log(f"native core: AES-NI={native.has_aesni}")

    alphas = rng.integers(0, 256, (1, N_BYTES), dtype=np.uint8)
    betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
    bundle = native.gen_batch(alphas, betas, random_s0s(1, LAM, rng), Bound.LT_BETA)
    xs = rng.integers(0, 256, (M_TPU, N_BYTES), dtype=np.uint8)

    # --- single-core CPU baseline (Rust stand-in) ---
    t0 = time.perf_counter()
    y_cpu = native.eval(0, bundle, xs[:M_CPU], num_threads=1)
    cpu_s = time.perf_counter() - t0
    cpu_rate = M_CPU / cpu_s
    log(f"cpu single-core: {M_CPU} pts in {cpu_s:.3f}s = {cpu_rate:,.0f} evals/s")

    # --- accelerator backend ---
    import jax

    dev = jax.devices()[0]
    log(f"jax device: {dev.platform} {getattr(dev, 'device_kind', '')}")
    backend = BitslicedBackend(LAM, cipher_keys)
    backend.put_bundle(bundle.for_party(0))

    t0 = time.perf_counter()
    y_dev = backend.eval(0, xs)  # compile + run (np.asarray syncs)
    warm_s = time.perf_counter() - t0
    log(f"warmup (compile + first run): {warm_s:.1f}s")

    best_s = float("inf")
    for i in range(TIMED_REPS):
        t0 = time.perf_counter()
        y_dev = backend.eval(0, xs)
        dt = time.perf_counter() - t0
        best_s = min(best_s, dt)
        log(f"rep {i}: {M_TPU} pts in {dt:.3f}s = {M_TPU / dt:,.0f} evals/s")
    dev_rate = M_TPU / best_s

    # --- bit-exact parity vs the host core ---
    parity_ok = bool(np.array_equal(y_dev[0, :M_PARITY], y_cpu[0, :M_PARITY]))
    log(f"parity (first {M_PARITY} pts): {'OK' if parity_ok else 'MISMATCH'}")
    if not parity_ok:
        raise SystemExit("bit-exact parity check failed")

    print(
        json.dumps(
            {
                "metric": "dcf_batch_eval_evals_per_sec_per_chip",
                "value": round(dev_rate, 1),
                "unit": "evals/s (n=128, lam=16B, 1 key x 2^20 points, party 0)",
                "vs_baseline": round(dev_rate / cpu_rate, 2),
            }
        )
    )


if __name__ == "__main__":
    os.chdir(os.path.dirname(os.path.abspath(__file__)))
    main()
