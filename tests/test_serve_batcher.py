"""Batcher padding/scatter math in isolation — no backend, no service.

Property-style over seeded cases: the plan must partition every request
in order, land inside power-of-two batches bounded by max_batch, and the
gather->scatter roundtrip must reassemble every request bit-exactly even
when batches complete out of order (the double-buffered pipeline's
reality).  Every case class the ISSUE names is pinned: ragged sizes,
single-point requests, exact power-of-two boundaries, out-of-order
completion.
"""

import numpy as np
import pytest

from dcf_tpu.errors import ShapeError
from dcf_tpu.serve.batcher import (
    gather_batch,
    next_pow2,
    plan_batches,
    scatter_batch,
)

pytestmark = pytest.mark.serve


def check_plan_invariants(sizes, max_batch, plans):
    """The structural contract every plan must satisfy."""
    per_req = {i: [] for i in range(len(sizes))}
    for plan in plans:
        assert 1 <= plan.m <= plan.padded_m <= max_batch
        assert plan.padded_m == next_pow2(plan.m)
        # spans tile [0, m) exactly, in order, without overlap
        spans = sorted(plan.spans, key=lambda s: s.batch_off)
        off = 0
        for sp in spans:
            assert sp.batch_off == off
            assert sp.length >= 1
            off += sp.length
        assert off == plan.m
        for sp in plan.spans:
            per_req[sp.req].append(sp)
    # each request is partitioned contiguously and in submission order
    for i, size in enumerate(sizes):
        chunks = per_req[i]
        assert [c.req_off for c in chunks] == sorted(
            c.req_off for c in chunks)
        off = 0
        for c in chunks:
            assert c.req_off == off
            off += c.length
        assert off == size


def roundtrip(sizes, max_batch, rng, completion_order=None):
    """gather -> fake eval (identity payload) -> scatter, optionally
    completing batches out of order; returns per-request outputs."""
    nb, k_num, lam = 3, 2, 4
    xs_list = [rng.integers(0, 256, (m, nb), dtype=np.uint8)
               for m in sizes]
    plans = plan_batches(sizes, max_batch)
    check_plan_invariants(sizes, max_batch, plans)
    outs = [np.zeros((k_num, m, lam), dtype=np.uint8) for m in sizes]
    order = (completion_order if completion_order is not None
             else range(len(plans)))
    for i in order:
        plan = plans[i]
        xb = gather_batch(xs_list, plan, nb)
        assert xb.shape == (plan.padded_m, nb)
        assert not xb[plan.m:].any()  # pad rows are zero
        # fake eval: y[k, j, :] is a tag of the input row, so scatter
        # errors (wrong row, wrong request) are detectable
        y = np.zeros((k_num, plan.padded_m, lam), dtype=np.uint8)
        for k in range(k_num):
            y[k, :, 0] = xb[:, 0]
            y[k, :, 1] = xb[:, 1]
            y[k, :, 2] = k
        scatter_batch(outs, plan, y)
    for xs, out in zip(xs_list, outs):
        for k in range(k_num):
            assert np.array_equal(out[k, :, 0], xs[:, 0])
            assert np.array_equal(out[k, :, 1], xs[:, 1])
            assert (out[k, :, 2] == k).all()
    return plans


def test_next_pow2():
    assert [next_pow2(m) for m in (1, 2, 3, 4, 5, 31, 32, 33)] == \
        [1, 2, 4, 4, 8, 32, 32, 64]


def test_ragged_sizes_seeded_property():
    rng = np.random.default_rng(0xBA7C)
    for _ in range(25):
        n_req = int(rng.integers(1, 12))
        sizes = [int(rng.integers(1, 40)) for _ in range(n_req)]
        max_batch = int(2 ** rng.integers(0, 6))
        roundtrip(sizes, max_batch, rng)


def test_single_point_requests():
    rng = np.random.default_rng(1)
    plans = roundtrip([1] * 7, 4, rng)
    assert [p.m for p in plans] == [4, 3]
    assert [p.padded_m for p in plans] == [4, 4]


def test_exact_power_of_two_boundary():
    """Totals landing exactly on max_batch produce full, unpadded
    batches (occupancy 1.0)."""
    rng = np.random.default_rng(2)
    plans = roundtrip([8, 8, 16, 32], 32, rng)
    assert [(p.m, p.padded_m) for p in plans] == [(32, 32), (32, 32)]
    assert all(p.occupancy == 1.0 for p in plans)


def test_oversized_request_splits():
    rng = np.random.default_rng(3)
    plans = roundtrip([100], 32, rng)
    assert [p.m for p in plans] == [32, 32, 32, 4]
    assert plans[-1].padded_m == 4


def test_out_of_order_completion_preserves_order():
    rng = np.random.default_rng(4)
    sizes = [int(rng.integers(1, 50)) for _ in range(9)]
    n_plans = len(plan_batches(sizes, 16))
    for _ in range(5):
        order = rng.permutation(n_plans)
        roundtrip(sizes, 16, rng, completion_order=list(order))


def test_occupancy():
    (plan,) = plan_batches([5], 32)
    assert plan.m == 5 and plan.padded_m == 8
    assert plan.occupancy == 5 / 8


def test_rejects_bad_arguments():
    with pytest.raises(ShapeError):
        plan_batches([4], 12)  # not a power of two
    with pytest.raises(ShapeError):
        plan_batches([4], 0)
    with pytest.raises(ShapeError):
        plan_batches([3, 0], 8)  # empty request
