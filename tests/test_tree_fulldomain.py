"""Tree full-domain evaluator: host oracle + device kernel parity
(interpret mode on CPU; same code is the Mosaic kernel on TPU)."""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.fulldomain import (
    TreeFullDomain,
    _finalize_np,
    tree_expand_np,
)
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.workloads import domain_points


def rand_bytes(rng, n):
    return bytes(rng.getrandbits(8) for _ in range(n))


def _bitrev(x: int, n: int) -> int:
    return int(bin(x)[2:].zfill(n)[::-1], 2)


def _setup(seed, alpha_bytes, bound=spec.Bound.LT_BETA):
    rng = random.Random(seed)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(seed)
    beta = rand_bytes(rng, 16)
    bundle = gen_batch(
        prg,
        np.frombuffer(alpha_bytes, dtype=np.uint8)[None],
        np.frombuffer(beta, dtype=np.uint8)[None],
        random_s0s(1, 16, nprng),
        bound,
    )
    return ck, prg, beta, bundle


def test_tree_expand_np_matches_pointwise_walk():
    """Host breadth-first leaves == the per-point numpy walk, with the
    bitreverse position mapping."""
    n_bits = 16
    ck, prg, beta, bundle = _setup(91, (0x2A7).to_bytes(2, "big"))
    for b in (0, 1):
        kb = bundle.for_party(b)
        s, v, t = tree_expand_np(prg, kb, b, n_bits)
        leaves = _finalize_np(kb, s, v, t)  # [2^16, 16] bitrev order
        xs = domain_points(2, 0, 256)  # spot-check first 256 domain points
        want = eval_batch_np(prg, b, kb, xs)[0]
        pos = np.array([_bitrev(x, n_bits) for x in range(256)])
        assert np.array_equal(leaves[pos], want), f"party {b}"


@pytest.mark.parametrize("gt", [False, True])
def test_tree_fulldomain_check_interpret(gt):
    alpha = 0x51C3
    ck, prg, beta, bundle = _setup(
        92, alpha.to_bytes(2, "big"),
        spec.Bound.GT_BETA if gt else spec.Bound.LT_BETA)
    fd = TreeFullDomain(16, ck, host_levels=8, interpret=True)
    assert fd.check(bundle, alpha, beta, n_bits=16, gt=gt) == 0
    # negative control: a shifted alpha flips exactly that many leaves
    assert fd.check(bundle, alpha + 7, beta, n_bits=16, gt=gt) == 7


def test_tree_device_matches_host_expansion():
    """Device pyramid leaves == the pure-host expansion, leaf for leaf."""
    alpha = 0xBE11
    ck, prg, beta, bundle = _setup(93, alpha.to_bytes(2, "big"))
    fd = TreeFullDomain(16, ck, host_levels=8, interpret=True)
    from dcf_tpu.utils.bits import bitmajor_perm, bits_lsb_to_bytes, unpack_lanes

    inv = np.argsort(bitmajor_perm(16))
    for b in (0, 1):
        kb = bundle.for_party(b)
        y = np.asarray(fd.eval_party(b, kb, 16))  # int32 [128, 2^11]
        got = bits_lsb_to_bytes(
            unpack_lanes(y.view(np.uint32)[inv]).T)  # [2^16, 16]
        s, v, t = tree_expand_np(prg, kb, b, 16)
        want = _finalize_np(kb, s, v, t)
        assert np.array_equal(got, want), f"party {b}"
