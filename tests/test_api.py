"""Top-level Dcf facade: the reference DcfImpl-equivalent entry point."""

import random

import numpy as np
import pytest

from dcf_tpu import Bound, Dcf


def rand_bytes(rng, n):
    return bytes(rng.getrandbits(8) for _ in range(n))


@pytest.mark.parametrize("backend", ["numpy", "bitsliced", "jax", "cpu"])
def test_facade_two_party_roundtrip(backend):
    rng = random.Random(99)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    dcf = Dcf(n_bytes=2, lam=16, cipher_keys=ck, backend=backend)
    nprng = np.random.default_rng(99)
    k = 3
    alphas = nprng.integers(0, 256, (k, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, 16), dtype=np.uint8)
    bundle = dcf.gen(alphas, betas, rng=nprng)
    xs = nprng.integers(0, 256, (7, 2), dtype=np.uint8)
    xs[0] = alphas[0]
    y0 = dcf.eval(0, bundle.for_party(0), xs)
    y1 = dcf.eval(1, bundle.for_party(1), xs)
    recon = y0 ^ y1
    for i in range(k):
        a = alphas[i].tobytes()
        for j in range(7):
            want = betas[i].tobytes() if xs[j].tobytes() < a else bytes(16)
            assert recon[i, j].tobytes() == want


def test_facade_auto_and_validation():
    rng = random.Random(98)
    ck = [rand_bytes(rng, 32) for _ in range(18)]  # lam>=32 uses index 17
    # auto on CPU at lam=16 -> bitsliced; lam=64 -> hybrid
    assert Dcf(2, 16, ck[:2]).backend_name == "bitsliced"
    assert Dcf(2, 64, ck).backend_name == "hybrid"
    with pytest.raises(ValueError, match="unknown backend"):
        Dcf(2, 16, ck[:2], backend="nope")
    dcf = Dcf(2, 16, ck[:2])
    with pytest.raises(ValueError, match="alphas"):
        dcf.gen(np.zeros((1, 3), dtype=np.uint8),
                np.zeros((1, 16), dtype=np.uint8))


def test_facade_gt_bound_hybrid():
    rng = random.Random(97)
    lam = 64
    ck = [rand_bytes(rng, 32) for _ in range(18)]  # index 17 needed
    dcf = Dcf(n_bytes=2, lam=lam, cipher_keys=ck)  # auto -> hybrid
    nprng = np.random.default_rng(97)
    alphas = nprng.integers(0, 256, (1, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (1, lam), dtype=np.uint8)
    bundle = dcf.gen(alphas, betas, bound=Bound.GT_BETA, rng=nprng)
    xs = nprng.integers(0, 256, (6, 2), dtype=np.uint8)
    y0 = dcf.eval(0, bundle.for_party(0), xs)
    y1 = dcf.eval(1, bundle.for_party(1), xs)
    recon = y0[0] ^ y1[0]
    a = alphas[0].tobytes()
    for j in range(6):
        want = betas[0].tobytes() if xs[j].tobytes() > a else bytes(lam)
        assert recon[j].tobytes() == want
