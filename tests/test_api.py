"""Top-level Dcf facade: the reference DcfImpl-equivalent entry point."""

import random
import warnings

import numpy as np
import pytest

from dcf_tpu import Bound, Dcf, ReferenceContractWarning
from dcf_tpu.spec import hirose_used_cipher_indices


def rand_bytes(rng, n):
    return bytes(rng.getrandbits(8) for _ in range(n))


@pytest.mark.parametrize("backend", ["numpy", "bitsliced", "jax", "cpu"])
def test_facade_two_party_roundtrip(backend):
    rng = random.Random(99)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    dcf = Dcf(n_bytes=2, lam=16, cipher_keys=ck, backend=backend)
    nprng = np.random.default_rng(99)
    k = 3
    alphas = nprng.integers(0, 256, (k, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, 16), dtype=np.uint8)
    bundle = dcf.gen(alphas, betas, rng=nprng)
    xs = nprng.integers(0, 256, (7, 2), dtype=np.uint8)
    xs[0] = alphas[0]
    y0 = dcf.eval(0, bundle.for_party(0), xs)
    y1 = dcf.eval(1, bundle.for_party(1), xs)
    recon = y0 ^ y1
    for i in range(k):
        a = alphas[i].tobytes()
        for j in range(7):
            want = betas[i].tobytes() if xs[j].tobytes() < a else bytes(16)
            assert recon[i, j].tobytes() == want


def test_facade_auto_and_validation():
    rng = random.Random(98)
    ck = [rand_bytes(rng, 32) for _ in range(18)]  # lam>=32 uses index 17
    # auto on CPU at lam=16 -> bitsliced; lam=64 -> hybrid
    assert Dcf(2, 16, ck[:2]).backend_name == "bitsliced"
    assert Dcf(2, 64, ck).backend_name == "hybrid"
    with pytest.raises(ValueError, match="unknown backend"):
        Dcf(2, 16, ck[:2], backend="nope")
    dcf = Dcf(2, 16, ck[:2])
    with pytest.raises(ValueError, match="alphas"):
        dcf.gen(np.zeros((1, 3), dtype=np.uint8),
                np.zeros((1, 16), dtype=np.uint8))


def test_reference_contract_warnings():
    """Reference-inexecutable shapes warn at the API edge (src/prg.rs:17-18):
    lam in [32, 144) (the reference's own contract cannot cover cipher
    index 17) and relaxed cipher counts (fewer than 2*(lam/16))."""
    with pytest.warns(ReferenceContractWarning, match="reference-inexecutable"):
        hirose_used_cipher_indices(64, 18)
    with pytest.warns(ReferenceContractWarning, match="relaxes the reference"):
        hirose_used_cipher_indices(16384, 18)
    rng = random.Random(96)
    ck = [rand_bytes(rng, 32) for _ in range(18)]
    with pytest.warns(ReferenceContractWarning):
        Dcf(2, 128, ck)  # the BASELINE lam=128 extension shape
    # Reference-executable shapes stay silent: lam=16 (2 keys) and
    # lam=144 at the exact contract count (18 keys).
    with warnings.catch_warnings():
        warnings.simplefilter("error", ReferenceContractWarning)
        hirose_used_cipher_indices(16, 2)
        hirose_used_cipher_indices(144, 18)
        Dcf(2, 16, ck[:2])


def test_facade_ships_once_per_party():
    """Alternating two-party eval of the same bundle ships each party's key
    image once (per-party cache slots), not once per call."""
    rng = random.Random(95)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    dcf = Dcf(n_bytes=2, lam=16, cipher_keys=ck, backend="bitsliced")
    nprng = np.random.default_rng(95)
    alphas = nprng.integers(0, 256, (1, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (1, 16), dtype=np.uint8)
    bundle = dcf.gen(alphas, betas, rng=nprng)
    xs = nprng.integers(0, 256, (5, 2), dtype=np.uint8)

    from dcf_tpu.backends.jax_bitsliced import BitslicedBackend

    ships = []
    orig = BitslicedBackend.put_bundle

    def counting_put(self, kb):
        ships.append(kb.s0s.tobytes())
        return orig(self, kb)

    import unittest.mock as mock

    with mock.patch.object(BitslicedBackend, "put_bundle", counting_put):
        for _ in range(3):  # three rounds of the documented pattern
            y0 = dcf.eval(0, bundle, xs)
            y1 = dcf.eval(1, bundle, xs)
    assert len(ships) == 2, f"expected 2 ships (one per party), got {len(ships)}"
    recon = y0[0] ^ y1[0]
    a = alphas[0].tobytes()
    for j in range(5):
        want = betas[0].tobytes() if xs[j].tobytes() < a else bytes(16)
        assert recon[j].tobytes() == want


def test_facade_mesh_routes_sharded_pallas():
    """Dcf(..., mesh=...) runs the flagship walk kernel under shard_map on
    the 8-virtual-device mesh (interpreter mode — no TPU), with the same
    ship-once-per-party semantics as the single-device facade."""
    import unittest.mock as mock

    from dcf_tpu.parallel import ShardedPallasBackend, make_mesh

    rng = random.Random(94)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    mesh = make_mesh(8)  # keys=4 x points=2
    dcf = Dcf(n_bytes=2, lam=16, cipher_keys=ck, mesh=mesh)
    assert dcf.backend_name == "pallas"  # auto at lam=16
    nprng = np.random.default_rng(94)
    k = 4  # divides the keys axis
    alphas = nprng.integers(0, 256, (k, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, 16), dtype=np.uint8)
    bundle = dcf.gen(alphas, betas, rng=nprng)
    xs = nprng.integers(0, 256, (9, 2), dtype=np.uint8)
    xs[0] = alphas[0]

    ships = []
    orig = ShardedPallasBackend.put_bundle

    def counting_put(self, kb):
        ships.append(kb.s0s.tobytes())
        return orig(self, kb)

    with mock.patch.object(ShardedPallasBackend, "put_bundle", counting_put):
        for _ in range(2):
            y0 = dcf.eval(0, bundle, xs)
            y1 = dcf.eval(1, bundle, xs)
    assert len(ships) == 2, f"expected one ship per party, got {len(ships)}"
    assert isinstance(dcf._eval_backends[0], ShardedPallasBackend)
    recon = y0 ^ y1
    for i in range(k):
        a = alphas[i].tobytes()
        for j in range(9):
            want = betas[i].tobytes() if xs[j].tobytes() < a else bytes(16)
            assert recon[i, j].tobytes() == want


def test_facade_mesh_keylanes():
    """backend='keylanes' on a mesh: one shared two-party device image
    serves both parties (shipped once, not once per party)."""
    import unittest.mock as mock

    from dcf_tpu.parallel import ShardedKeyLanesBackend, make_mesh

    rng = random.Random(93)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    mesh = make_mesh(8)
    dcf = Dcf(n_bytes=2, lam=16, cipher_keys=ck, backend="keylanes",
              mesh=mesh,
              backend_opts=dict(m_tile=2, kw_tile=1, level_chunk=4))
    nprng = np.random.default_rng(93)
    k = 40  # ragged vs the 4*32-key shard granule
    alphas = nprng.integers(0, 256, (k, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, 16), dtype=np.uint8)
    bundle = dcf.gen(alphas, betas, rng=nprng)
    xs = nprng.integers(0, 256, (6, 2), dtype=np.uint8)
    xs[0] = alphas[0]

    ships = []
    orig = ShardedKeyLanesBackend.put_bundle

    def counting_put(self, kb):
        ships.append(True)
        return orig(self, kb)

    with mock.patch.object(ShardedKeyLanesBackend, "put_bundle",
                           counting_put):
        for _ in range(2):
            y0 = dcf.eval(0, bundle, xs)
            y1 = dcf.eval(1, bundle, xs)
    assert len(ships) == 1, \
        f"the two-party image should ship once, shipped {len(ships)}x"
    recon = y0 ^ y1
    for i in range(k):
        a = alphas[i].tobytes()
        for j in range(6):
            want = betas[i].tobytes() if xs[j].tobytes() < a else bytes(16)
            assert recon[i, j].tobytes() == want
    # A party-restricted bundle cannot feed the shared image.
    with pytest.raises(ValueError, match="two-party"):
        dcf.eval(0, bundle.for_party(0), xs)


def test_facade_keylanes_no_mesh():
    """backend='keylanes' WITHOUT a mesh routes to the single-device
    KeyLanesPallasBackend — the shape cli.py secure_relu benches must be
    facade-reachable on one chip, with the same shared two-party-image
    contract as the mesh variant."""
    import unittest.mock as mock

    from dcf_tpu.backends.pallas_keylanes import KeyLanesPallasBackend

    rng = random.Random(91)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    dcf = Dcf(n_bytes=2, lam=16, cipher_keys=ck, backend="keylanes",
              backend_opts=dict(m_tile=2, kw_tile=1, level_chunk=4))
    nprng = np.random.default_rng(91)
    k = 40  # ragged vs the 32-key word granule
    alphas = nprng.integers(0, 256, (k, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, 16), dtype=np.uint8)
    bundle = dcf.gen(alphas, betas, rng=nprng)
    xs = nprng.integers(0, 256, (6, 2), dtype=np.uint8)
    xs[0] = alphas[0]

    ships = []
    orig = KeyLanesPallasBackend.put_bundle

    def counting_put(self, kb):
        ships.append(True)
        return orig(self, kb)

    with mock.patch.object(KeyLanesPallasBackend, "put_bundle",
                           counting_put):
        for _ in range(2):
            y0 = dcf.eval(0, bundle, xs)
            y1 = dcf.eval(1, bundle, xs)
    assert len(ships) == 1, \
        f"the two-party image should ship once, shipped {len(ships)}x"
    assert isinstance(dcf._eval_backends["kl"], KeyLanesPallasBackend)
    recon = y0 ^ y1
    for i in range(k):
        a = alphas[i].tobytes()
        for j in range(6):
            want = betas[i].tobytes() if xs[j].tobytes() < a else bytes(16)
            assert recon[i, j].tobytes() == want
    with pytest.raises(ValueError, match="two-party"):
        dcf.eval(0, bundle.for_party(0), xs)
    with pytest.raises(ValueError, match="lam=16 only"):
        Dcf(2, 64, [rand_bytes(rng, 32) for _ in range(18)],
            backend="keylanes")


def test_facade_prefix_no_mesh():
    """backend='prefix' routes to PrefixPallasBackend (single key) with
    the standard per-party ship-once contract."""
    from dcf_tpu.backends.pallas_prefix import PrefixPallasBackend

    rng = random.Random(90)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    dcf = Dcf(n_bytes=2, lam=16, cipher_keys=ck, backend="prefix")
    nprng = np.random.default_rng(90)
    alphas = nprng.integers(0, 256, (1, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (1, 16), dtype=np.uint8)
    bundle = dcf.gen(alphas, betas, rng=nprng)
    xs = nprng.integers(0, 256, (7, 2), dtype=np.uint8)
    xs[0] = alphas[0]
    recon = dcf.eval(0, bundle, xs) ^ dcf.eval(1, bundle, xs)
    assert isinstance(dcf._eval_backends[0], PrefixPallasBackend)
    a = alphas[0].tobytes()
    for j in range(7):
        want = betas[0].tobytes() if xs[j].tobytes() < a else bytes(16)
        assert recon[0, j].tobytes() == want
    with pytest.raises(ValueError, match="lam=16 only"):
        Dcf(2, 64, [rand_bytes(rng, 32) for _ in range(18)],
            backend="prefix")


def test_facade_mesh_validation():
    from dcf_tpu.parallel import make_mesh

    rng = random.Random(92)
    ck = [rand_bytes(rng, 32) for _ in range(18)]
    mesh = make_mesh(8)
    with pytest.raises(ValueError, match="no mesh-sharded variant"):
        Dcf(2, 16, ck[:2], backend="cpu", mesh=mesh)
    with pytest.raises(ValueError, match="lam=16 only"):
        Dcf(2, 64, ck, backend="keylanes", mesh=mesh)
    # auto at lam >= 48 routes to the sharded hybrid; 16 < lam < 48 (no
    # hybrid, no lam=16 kernel) to the XLA-sharded fallback.
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", ReferenceContractWarning)
        assert Dcf(2, 64, ck, mesh=mesh).backend_name == "hybrid"
        assert Dcf(2, 32, ck, mesh=mesh).backend_name == "bitsliced"
    with pytest.raises(ValueError, match="backend_opts"):
        Dcf(2, 16, ck[:2], backend="cpu",
            backend_opts=dict(tile_words=64))


def test_facade_gt_bound_hybrid():
    rng = random.Random(97)
    lam = 64
    ck = [rand_bytes(rng, 32) for _ in range(18)]  # index 17 needed
    dcf = Dcf(n_bytes=2, lam=lam, cipher_keys=ck)  # auto -> hybrid
    nprng = np.random.default_rng(97)
    alphas = nprng.integers(0, 256, (1, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (1, lam), dtype=np.uint8)
    bundle = dcf.gen(alphas, betas, bound=Bound.GT_BETA, rng=nprng)
    xs = nprng.integers(0, 256, (6, 2), dtype=np.uint8)
    y0 = dcf.eval(0, bundle.for_party(0), xs)
    y1 = dcf.eval(1, bundle.for_party(1), xs)
    recon = y0[0] ^ y1[0]
    a = alphas[0].tobytes()
    for j in range(6):
        want = betas[0].tobytes() if xs[j].tobytes() > a else bytes(lam)
        assert recon[j].tobytes() == want


def _extension_keys(rng, lam):
    """The CLI's cipher-key contract: 2*(lam/16), floored at 18 for
    lam >= 32 (cipher index 17 is touched by every such shape)."""
    n = max(2, 2 * (lam // 16))
    if lam >= 32:
        n = max(n, 18)
    return [rand_bytes(rng, n=32) for _ in range(n)]


def test_auto_routing_crossover():
    """The measured per-lam routing table documented in the api.py
    docstring (VERDICT round 5, item 8 doc half): lam=16 walks the
    cipher kernel family (bitsliced off-TPU, pallas on it), every
    lam >= 48 routes to the hybrid narrow-walk + GF(2)-affine split.
    Canary verdicts cache per (backend, lam), so this also proves the
    whole advertised band constructs healthily on this host."""
    import jax

    rng = random.Random(95)
    on_tpu = jax.devices()[0].platform == "tpu"
    want_16 = "pallas" if on_tpu else "bitsliced"
    for lam, want in ((16, want_16), (48, "hybrid"), (128, "hybrid"),
                      (256, "hybrid")):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", ReferenceContractWarning)
            dcf = Dcf(16, lam, _extension_keys(rng, lam), backend="auto")
        assert dcf.backend_name == want, (lam, dcf.backend_name)


@pytest.mark.slow
def test_auto_routing_crossover_lam16384():
    """The reference bench's literal lambda (2048 AES ciphers) routes to
    hybrid too — split out of the table test because its canary compile
    is the expensive one."""
    rng = random.Random(94)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", ReferenceContractWarning)
        dcf = Dcf(16, 16384, _extension_keys(rng, 16384), backend="auto")
    assert dcf.backend_name == "hybrid"
