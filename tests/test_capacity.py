"""dcf_tpu.serve.capacity: demand-driven autoscaling (ISSUE 16).

Covers the capacity controller's whole decision surface on stub
router/membership pairs driven by the injectable clock — verdict
aggregation (queue/brownout fractions via the metrics-rollup path,
cumulative-counter deltas with the restart clamp), the lifted
fail-N/recover-M hysteresis, the epoch-observed hard cooldown, every
counted safety rail, the ``capacity.decide`` seam's forced/frozen
semantics, the typed operator verbs, and the PONG load-block wire-fuzz
extension (the ISSUE 15 fuzz discipline applied to the new payload:
mangled frames die typed, the pristine load-free v2 frames keep
parsing).  The end-to-end elastic cycle against real processes rides
``pod_bench --surge`` (see tests/test_cli.py for its fail-fast
validation and the serial slow leg for the smoke).
"""

import pathlib
import struct
import zlib
from types import SimpleNamespace

import numpy as np
import pytest

from dcf_tpu.errors import KeyFormatError, StandbyExhaustedError
from dcf_tpu.serve import CapacityController, CapacityEvent, ShardSpec
from dcf_tpu.serve.capacity import (
    IDLE,
    PRESSURE,
    STEADY,
    ForcedVerdict,
)
from dcf_tpu.serve.edge import (
    MAGIC,
    T_PING,
    T_PONG,
    VERSION,
    LoadSample,
    _CRC,
    _FRAME_HEAD,
    _PING_FLAGS,
    _PING_HEAD,
    _PONG_HEAD,
    _PONG_LOAD,
    decode_ping,
    decode_response,
    encode_ping,
    encode_pong,
)
from dcf_tpu.serve.metrics import Metrics, labeled
from dcf_tpu.serve.shardmap import ShardMap
from dcf_tpu.testing import faults
from dcf_tpu.testing.faults import FakeClock

pytestmark = pytest.mark.autoscale


# ------------------------------------------------ stub pod plumbing


class StubHealth:
    """The prober surface the controller reads: ``loads()``."""

    def __init__(self):
        self.samples = {}

    def loads(self):
        return dict(self.samples)


class StubRouter:
    """The router surface the controller reads: ``map``, ``metrics``,
    ``ring_epoch``, ``health``, and the injectable clock."""

    def __init__(self, host_ids, clock):
        self.map = ShardMap([ShardSpec(h) for h in host_ids])
        self.metrics = Metrics()
        self.health = StubHealth()
        self.ring_epoch = 0
        self._clock = clock


class StubMembership:
    """The membership surface the controller drives: joins and drains
    commit a new epoch on the router, exactly like the real fences."""

    def __init__(self, router, min_hosts=1):
        self.router = router
        self.min_hosts = min_hosts
        self.joins = []
        self.drains = []
        self.stores = {}
        self.eject = False
        self.fail_join = False

    def eject_in_flight(self):
        return self.eject

    def store_for(self, host_id):
        return self.stores.get(host_id)

    def join(self, spec, store=None):
        if self.fail_join:
            raise RuntimeError("injected join failure")
        self.router.map = self.router.map.with_host(spec)
        self.router.ring_epoch += 1
        self.joins.append(spec.host_id)
        return SimpleNamespace(kind="join", host_id=spec.host_id,
                               epoch=self.router.ring_epoch)

    def drain(self, host_id):
        self.router.map = self.router.map.without_host(host_id)
        self.router.ring_epoch += 1
        self.drains.append(host_id)
        return SimpleNamespace(kind="drain", host_id=host_id,
                               epoch=self.router.ring_epoch)


def S(qp=0, ql=100, bo=False, shed=0, refused=0, misses=0):
    return LoadSample(qp, ql, bo, shed, refused, misses)


def make_pod(hosts=("a", "b"), standby=("s1",), **kw):
    ck = FakeClock()
    r = StubRouter(hosts, ck)
    mm = StubMembership(r)
    kw.setdefault("scale_out_n", 2)
    kw.setdefault("scale_in_m", 3)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("min_hosts", 1)
    cap = CapacityController(
        r, mm, standby=[ShardSpec(s) for s in standby], **kw)
    return cap, r, mm, ck


def tick(cap, ck, loads, dt=1.0):
    """Set the sampled loads, advance the clock, run one inline
    control tick."""
    cap._router.health.samples = loads
    ck.advance(dt)
    return cap.pump()


def skips(r, reason):
    return r.metrics.counter(labeled(
        "capacity_skips_total", reason=reason)).value


# ------------------------------------------------ verdict aggregation


def test_verdict_pressure_on_pooled_queue_fraction():
    """The queue signal pools ACROSS shards (rollup summation): one
    drowning shard next to one empty shard reads as the pod's true
    fraction, not either extreme."""
    cap, r, mm, ck = make_pod()
    v = tick(cap, ck, {"a": S(qp=90), "b": S(qp=80)})
    assert v.kind == PRESSURE and v.sampled == 2
    assert v.queue_fraction == pytest.approx(170 / 200)
    v = tick(cap, ck, {"a": S(qp=90), "b": S(qp=0)})
    assert v.kind == STEADY  # 90/200 = 0.45 < 0.75: pooled, not max
    assert r.metrics.counter("capacity_pressure_ticks_total").value == 1


def test_verdict_pressure_on_brownout_fraction():
    cap, r, mm, ck = make_pod()
    v = tick(cap, ck, {"a": S(bo=True), "b": S()})
    assert v.kind == PRESSURE
    assert v.brownout_fraction == pytest.approx(0.5)


def test_verdict_idle_steady_bands_and_empty_sample():
    cap, r, mm, ck = make_pod()
    assert tick(cap, ck, {"a": S(qp=2), "b": S(qp=2)}).kind == IDLE
    assert tick(cap, ck, {"a": S(qp=30), "b": S(qp=30)}).kind == STEADY
    # Brownout anywhere vetoes idle even with an empty queue.
    assert tick(cap, ck, {"a": S(bo=True, qp=0),
                          "b": S(qp=0)}).kind == PRESSURE
    # No evidence is never a scaling reason: nothing sampled -> steady.
    v = tick(cap, ck, {})
    assert v.kind == STEADY and v.sampled == 0
    v = tick(cap, ck, {"a": None, "b": None})  # answered, no surface
    assert v.kind == STEADY and v.sampled == 0


def test_verdict_counter_deltas_first_sample_and_restart_clamp():
    """Cumulative counters difference against the PREVIOUS tick: a
    host's first sample contributes zero (pre-existing totals are
    history), and a counter that went BACKWARD reads as a restart,
    never as negative demand."""
    cap, r, mm, ck = make_pod()
    v = tick(cap, ck, {"a": S(qp=1, shed=500), "b": S(qp=1)})
    assert v.kind == IDLE and v.shed_delta == 0
    v = tick(cap, ck, {"a": S(qp=1, shed=501), "b": S(qp=1)})
    assert v.kind == PRESSURE and v.shed_delta == 1
    # Shard restart: totals reset below the previous reading.
    v = tick(cap, ck, {"a": S(qp=1, shed=3), "b": S(qp=1)})
    assert v.kind == IDLE and v.shed_delta == 0
    # Refusals and pool misses flag pressure the same way.
    v = tick(cap, ck, {"a": S(qp=1, shed=3, refused=1), "b": S(qp=1)})
    assert v.kind == PRESSURE and v.refusal_delta == 1
    v = tick(cap, ck, {"a": S(qp=1, shed=3, refused=1, misses=2),
                       "b": S(qp=1)})
    assert v.kind == PRESSURE and v.pool_miss_delta == 2


def test_verdict_ignores_hosts_outside_the_ring():
    """A stale load sample for a host that already left the ring (or
    a standby that answered a probe) must not steer scaling."""
    cap, r, mm, ck = make_pod()
    v = tick(cap, ck, {"a": S(qp=2), "b": S(qp=2),
                       "ghost": S(qp=100, bo=True)})
    assert v.kind == IDLE and v.sampled == 2 and v.ring_size == 2


# ------------------------------------------------ hysteresis + cooldown


def test_scale_out_only_after_n_consecutive_pressure_ticks():
    cap, r, mm, ck = make_pod(scale_out_n=3)
    hot = {"a": S(qp=90), "b": S(qp=90)}
    calm = {"a": S(qp=30), "b": S(qp=30)}
    tick(cap, ck, hot)
    tick(cap, ck, hot)
    tick(cap, ck, calm)  # streak broken one short of the threshold
    assert mm.joins == []
    tick(cap, ck, hot)
    tick(cap, ck, hot)
    assert mm.joins == []
    tick(cap, ck, hot)  # third CONSECUTIVE -> commit
    assert mm.joins == ["s1"]
    assert cap.standby() == []
    (ev,) = cap.events()
    assert isinstance(ev, CapacityEvent)
    assert (ev.kind, ev.host_id, ev.epoch) == ("scale-out", "s1", 1)
    assert cap.events() == []  # events() drains
    assert r.metrics.counter("capacity_scale_out_total").value == 1
    assert r.metrics.gauge("capacity_standby_hosts").value == 0


def test_scale_in_drains_least_loaded_into_back_of_pool():
    cap, r, mm, ck = make_pod(hosts=("a", "b", "c"), scale_in_m=2)
    mm.stores["b"] = store = object()
    idle = {"a": S(qp=2), "b": S(qp=0), "c": S(qp=3)}
    tick(cap, ck, idle)
    assert mm.drains == []
    tick(cap, ck, idle)
    assert mm.drains == ["b"]  # smallest sampled queue_points
    # The drained host queues BEHIND the declared standby, store
    # attached — a future surge re-admits the coldest host last.
    assert cap.standby() == ["s1", "b"]
    assert cap._standby[-1] == (ShardSpec("b"), store)
    (ev,) = cap.events()
    assert (ev.kind, ev.host_id, ev.epoch) == ("scale-in", "b", 1)
    assert r.metrics.counter("capacity_scale_in_total").value == 1


def test_flap_damping_oscillating_load_zero_membership_changes():
    """The flap pin: a load walk oscillating INSIDE the hysteresis
    windows — however long — produces exactly zero ring churn."""
    cap, r, mm, ck = make_pod(scale_out_n=2, scale_in_m=2)
    hot = {"a": S(qp=90), "b": S(qp=90)}
    calm = {"a": S(qp=1), "b": S(qp=1)}
    for i in range(40):
        tick(cap, ck, hot if i % 2 else calm)
    assert mm.joins == [] and mm.drains == []
    assert cap.events() == []
    assert r.ring_epoch == 0
    assert r.metrics.counter("capacity_ticks_total").value == 40


def test_cooldown_two_back_to_back_surges_one_join():
    """The cooldown pin: a second sustained surge arriving one tick
    after a committed scale-out waits the cooldown out — exactly one
    join, the re-surge counted as ``cooldown`` skips."""
    cap, r, mm, ck = make_pod(standby=("s1", "s2"), scale_out_n=2,
                              cooldown_s=10.0)
    hot = {"a": S(qp=90), "b": S(qp=90)}
    tick(cap, ck, hot)
    tick(cap, ck, hot)  # surge 1 commits
    assert mm.joins == ["s1"]
    for _ in range(5):  # surge 2, one tick later, inside the cooldown
        tick(cap, ck, hot)
    assert mm.joins == ["s1"]
    assert skips(r, "cooldown") >= 1
    for _ in range(8):  # the clock clears the cooldown; surge holds
        tick(cap, ck, hot)
    assert mm.joins == ["s1", "s2"]


def test_external_epoch_change_resets_streaks_and_cools_down():
    """A membership commit the controller did NOT make (a health
    eject) restarts the cooldown and voids the streak evidence."""
    cap, r, mm, ck = make_pod(scale_out_n=2, cooldown_s=10.0)
    hot = {"a": S(qp=90), "b": S(qp=90)}
    tick(cap, ck, hot)  # streak 1
    r.ring_epoch += 1   # the health plane ejected someone
    tick(cap, ck, hot)  # observes the epoch: reset, streak rebuilds to 1
    tick(cap, ck, hot)  # streak 2 -> threshold, but cooled down
    assert mm.joins == []
    assert skips(r, "cooldown") == 1
    for _ in range(10):
        tick(cap, ck, hot)
    assert mm.joins == ["s1"]  # commits once the cooldown clears


# ------------------------------------------------ safety rails


def test_rail_max_hosts_and_no_standby_counted():
    cap, r, mm, ck = make_pod(standby=("s1",), scale_out_n=1,
                              cooldown_s=0.0, max_hosts=2)
    hot = {"a": S(qp=90), "b": S(qp=90)}
    tick(cap, ck, hot)
    assert mm.joins == [] and skips(r, "max_hosts") == 1
    cap.max_hosts = 4
    tick(cap, ck, hot)
    assert mm.joins == ["s1"]
    tick(cap, ck, hot)  # pool is now empty
    assert skips(r, "no_standby") == 1


def test_rail_eject_inflight_blocks_both_directions():
    cap, r, mm, ck = make_pod(scale_out_n=1, scale_in_m=1,
                              cooldown_s=0.0)
    mm.eject = True
    tick(cap, ck, {"a": S(qp=90), "b": S(qp=90)})
    tick(cap, ck, {"a": S(qp=1), "b": S(qp=1)})
    assert mm.joins == [] and mm.drains == []
    assert skips(r, "eject_inflight") == 2


def test_rail_min_hosts_floors_scale_in():
    cap, r, mm, ck = make_pod(scale_in_m=1, cooldown_s=0.0,
                              min_hosts=2)
    tick(cap, ck, {"a": S(qp=1), "b": S(qp=1)})
    assert mm.drains == [] and skips(r, "min_hosts") == 1


def test_rail_no_sample_blocks_a_blind_drain():
    """A forced-idle tick with no load samples has no victim to pick
    — counted, never a guess."""
    cap, r, mm, ck = make_pod(scale_in_m=1, cooldown_s=0.0)

    def force_idle(kind, verdict):
        raise ForcedVerdict(IDLE)

    with faults.inject("capacity.decide", handler=force_idle):
        tick(cap, ck, {})
    assert mm.drains == [] and skips(r, "no_sample") == 1


# ------------------------------------------------ the decide seam


def test_forced_verdict_forces_the_tick_and_counts():
    cap, r, mm, ck = make_pod(scale_out_n=1, cooldown_s=0.0)

    def force(kind, verdict):
        assert kind == STEADY  # the seam sees the computed verdict
        raise ForcedVerdict(PRESSURE)

    with faults.inject("capacity.decide", handler=force):
        v = tick(cap, ck, {"a": S(qp=30), "b": S(qp=30)})
    assert v.kind == PRESSURE
    assert mm.joins == ["s1"]  # the forced kind drives real scaling
    assert r.metrics.counter(
        "capacity_forced_verdicts_total").value == 1


def test_any_other_seam_raise_freezes_the_tick():
    cap, r, mm, ck = make_pod(scale_out_n=1, cooldown_s=0.0)
    hot = {"a": S(qp=90), "b": S(qp=90)}
    with faults.inject("capacity.decide", exc=RuntimeError("brake")):
        assert tick(cap, ck, hot) is None
    assert mm.joins == []
    assert skips(r, "frozen") == 1
    assert r.metrics.gauge("capacity_pressure_streak").value == 0
    tick(cap, ck, hot)  # disarmed: the very next tick acts again
    assert mm.joins == ["s1"]


def test_forced_verdict_typo_fails_the_arming_test():
    with pytest.raises(ValueError, match="verdict kind"):
        ForcedVerdict("presure")


# ------------------------------------------------ operator verbs


def test_operator_scale_out_empty_pool_raises_typed():
    cap, r, mm, ck = make_pod(standby=())
    with pytest.raises(StandbyExhaustedError, match="standby pool"):
        cap.scale_out()
    assert mm.joins == []


def test_operator_verbs_bypass_hysteresis_not_fences():
    cap, r, mm, ck = make_pod(hosts=("a", "b"), cooldown_s=1e9)
    ev = cap.scale_out()  # no streak, giant cooldown: still commits
    assert (ev.kind, ev.host_id) == ("scale-out", "s1")
    ev = cap.scale_in("a")
    assert (ev.kind, ev.host_id) == ("scale-in", "a")
    assert cap.standby() == ["a"]  # back of the pool
    assert [e.kind for e in cap.events()] == ["scale-out", "scale-in"]


def test_failed_join_returns_host_to_front_and_counts():
    cap, r, mm, ck = make_pod(standby=("s1", "s2"), scale_out_n=1,
                              cooldown_s=0.0)
    mm.fail_join = True
    hot = {"a": S(qp=90), "b": S(qp=90)}
    tick(cap, ck, hot)
    assert mm.joins == [] and cap.events() == []
    # FRONT of the pool: the retry admits the same host, keeping the
    # declared admission order.
    assert cap.standby() == ["s1", "s2"]
    assert r.metrics.counter(
        "capacity_scale_failures_total").value == 1
    mm.fail_join = False
    tick(cap, ck, hot)
    assert mm.joins == ["s1"]


# ------------------------------------------------ config contracts


@pytest.mark.parametrize("kw", [
    {"interval_s": 0.0},
    {"scale_out_n": 0},
    {"scale_in_m": 0},
    {"cooldown_s": -1.0},
    {"brownout_pressure_fraction": 0.0},
    {"queue_pressure_fraction": 1.5},
    {"queue_idle_fraction": 0.75},   # == pressure threshold
    {"min_hosts": 0},
    {"max_hosts": 1, "min_hosts": 2},
])
def test_config_validation_api_edge(kw):
    ck = FakeClock()
    r = StubRouter(("a", "b"), ck)
    with pytest.raises(ValueError):
        CapacityController(r, StubMembership(r), **kw)


def test_standby_entry_declaration_contract():
    ck = FakeClock()
    r = StubRouter(("a",), ck)
    with pytest.raises(ValueError, match="standby entries"):
        CapacityController(r, StubMembership(r),
                           standby=[("not-a-spec", None)])
    cap = CapacityController(r, StubMembership(r), min_hosts=1)
    cap.add_standby(ShardSpec("late"), store=None)
    assert cap.standby() == ["late"]
    assert r.metrics.gauge("capacity_standby_hosts").value == 1


# ------------------------------------------------ PONG load wire fuzz


def _seal(*parts):
    """A frame body with a VALID CRC trailer — corruption that beats
    the checksum, so the tests prove the structural checks too."""
    body = b"".join(parts)
    return body + _CRC.pack(zlib.crc32(body))


def test_pong_pristine_both_sizes_parse():
    """The v2 compatibility pin: the legacy load-free PONG keeps its
    exact frame size and decode; the extended one round-trips the
    ``LoadSample``."""
    assert decode_response(encode_pong(7, 5)[4:]) == ("pong", 7, 5)
    sample = S(qp=17, ql=4096, bo=True, shed=3, refused=2, misses=9)
    kind, req_id, payload = decode_response(
        encode_pong(8, 6, load=sample)[4:])
    assert (kind, req_id) == ("pong", 8)
    assert payload == (6, sample)
    assert isinstance(payload[1], LoadSample)
    # And the request side: want_load is one flags byte, legacy pings
    # keep the exact legacy size.
    assert decode_ping(encode_ping(3, 9)[4:]) == (3, 9, False)
    assert decode_ping(encode_ping(3, 9, want_load=True)[4:]) \
        == (3, 9, True)
    assert len(encode_ping(3, 9, want_load=True)) \
        == len(encode_ping(3, 9)) + _PING_FLAGS.size


def test_pong_load_block_byte_flips_die_typed():
    frame = encode_pong(
        11, 2, load=S(qp=40, ql=100, shed=5, refused=1, misses=2))
    body = frame[4:]
    rng = np.random.default_rng(0x16C)
    for off in rng.integers(0, len(body), 32):
        buf = bytearray(body)
        buf[int(off)] ^= 0x41
        with pytest.raises(KeyFormatError):
            decode_response(bytes(buf))


def test_pong_load_block_bad_sizes_die_typed_past_the_crc():
    """Truncated and oversized load blocks WITH a valid CRC still die
    on the strict two-sizes check — the size gate is load-bearing,
    not an accident of the checksum."""
    head = MAGIC + _FRAME_HEAD.pack(VERSION, T_PONG) \
        + _PONG_HEAD.pack(11, 2)
    load = _PONG_LOAD.pack(40, 100, 1, 5, 1, 2)
    for cut in (1, _PONG_LOAD.size // 2, _PONG_LOAD.size - 1):
        with pytest.raises(KeyFormatError, match="pong frame"):
            decode_response(_seal(head, load[:cut]))
    with pytest.raises(KeyFormatError, match="pong frame"):
        decode_response(_seal(head, load, b"\x00\x00\x00"))
    with pytest.raises(KeyFormatError, match="pong frame"):
        decode_response(_seal(head, load, load))
    # Raw truncations (CRC not recomputed) die typed as well.
    full = encode_pong(11, 2, load=S(qp=40))[4:]
    for n in (5, len(full) // 2, len(full) - 1):
        with pytest.raises(KeyFormatError):
            decode_response(full[:n])


def test_pong_brownout_byte_range_checked():
    head = MAGIC + _FRAME_HEAD.pack(VERSION, T_PONG) \
        + _PONG_HEAD.pack(1, 0)
    bad = _PONG_LOAD.pack(0, 100, 2, 0, 0, 0)  # brownout byte 2
    with pytest.raises(KeyFormatError, match="brownout byte"):
        decode_response(_seal(head, bad))


def test_ping_reserved_flag_bits_die_typed():
    head = MAGIC + _FRAME_HEAD.pack(VERSION, T_PING) \
        + _PING_HEAD.pack(4, 0)
    for flags in (0x02, 0x80, 0xFF):
        with pytest.raises(KeyFormatError, match="reserved bits"):
            decode_ping(_seal(head, _PING_FLAGS.pack(flags)))
    # A flags byte is only legal at exactly base+1: two flag bytes is
    # a mangled frame, not a bigger extension.
    with pytest.raises(KeyFormatError, match="ping frame"):
        decode_ping(_seal(head, _PING_FLAGS.pack(1),
                          _PING_FLAGS.pack(1)))
    with pytest.raises(KeyFormatError):
        decode_ping(struct.pack("<I", 1 << 30) + b"junk")


# ------------------------------------------------ the bench gate


def _gate_dir(tmp_path, value, floors):
    import json

    bdir = tmp_path / "benchmarks"
    bdir.mkdir()
    (bdir / "RESULTS_pod.jsonl").write_text(
        '{"value": 1.0, "note": "older line, not the claim"}\n'
        + json.dumps({"value": value}) + "\n", encoding="utf-8")
    fpath = bdir / "FLOORS.json"
    fpath.write_text(json.dumps(floors), encoding="utf-8")
    return bdir, fpath


def test_bench_gate_passes_then_fails_on_a_doctored_regression(
        tmp_path):
    """The gate's reason to exist, pinned both ways: the committed
    claim holds its floor, and a doctored regressed NEWEST line (the
    silent walk-back) fails the gate — the older passing line does
    not mask it."""
    from tools.bench_gate import main, run_gate

    pin = {"RESULTS_pod.jsonl": {
        "field": "value", "direction": "at_least", "floor": 100.0,
        "pinned_value": 143.0, "reason": "pinned by the surge run"}}
    bdir, fpath = _gate_dir(tmp_path, 143.0, pin)
    failures, report = run_gate(bdir, fpath)
    assert failures == []
    assert main(["--benchmarks", str(bdir), "--floors",
                 str(fpath)]) == 0
    # Doctor the newest line below the floor.
    with open(bdir / "RESULTS_pod.jsonl", "a", encoding="utf-8") as f:
        f.write('{"value": 12.0}\n')
    failures, report = run_gate(bdir, fpath)
    assert len(failures) == 1
    assert "fell below the pinned floor" in failures[0]
    assert "pinned by the surge run" in failures[0]  # the why travels
    assert main(["--benchmarks", str(bdir), "--floors",
                 str(fpath)]) == 1


def test_bench_gate_at_most_ceiling_and_unpinned_skip(tmp_path):
    from tools.bench_gate import run_gate

    pin = {"_meta": {"doc": "ignored"},
           "RESULTS_pod.jsonl": {
               "field": "value", "direction": "at_most",
               "floor": 200.0, "pinned_value": 143.0,
               "reason": "latency-style"}}
    bdir, fpath = _gate_dir(tmp_path, 143.0, pin)
    (bdir / "RESULTS_new.jsonl").write_text('{"value": 9}\n',
                                            encoding="utf-8")
    failures, report = run_gate(bdir, fpath)
    assert failures == []
    # The unpinned file is DISCLOSED, never silently dropped.
    assert any(r.startswith("SKIP RESULTS_new.jsonl") for r in report)
    with open(bdir / "RESULTS_pod.jsonl", "a", encoding="utf-8") as f:
        f.write('{"value": 250.0}\n')
    failures, _ = run_gate(bdir, fpath)
    assert len(failures) == 1 and "rose above" in failures[0]


def test_bench_gate_broken_pins_are_fatal_not_skips(tmp_path):
    """A floor that can no longer be read is a regression in the gate
    itself: missing file, corrupt tail, missing field, malformed
    entry — all exit-1, none reported as a pass."""
    from tools.bench_gate import run_gate

    pin = {
        "RESULTS_gone.jsonl": {"field": "value",
                               "direction": "at_least", "floor": 1.0},
        "RESULTS_pod.jsonl": {"field": "no_such_field",
                              "direction": "at_least", "floor": 1.0},
        "RESULTS_bad.jsonl": {"field": "value",
                              "direction": "sideways", "floor": 1.0},
    }
    bdir, fpath = _gate_dir(tmp_path, 143.0, pin)
    (bdir / "RESULTS_bad.jsonl").write_text('{"value": 9}\n',
                                            encoding="utf-8")
    failures, _ = run_gate(bdir, fpath)
    assert len(failures) == 3


def test_bench_gate_update_requires_reason_and_repins(tmp_path):
    """``--update`` without ``--reason`` is refused (a floor move
    without a disclosed why IS the silent walk-back); with one, the
    floor re-pins at ratio * the current newest value."""
    import json

    from tools.bench_gate import main

    pin = {"RESULTS_pod.jsonl": {
        "field": "value", "direction": "at_least", "floor": 1.0,
        "pinned_value": None, "reason": "skeleton"}}
    bdir, fpath = _gate_dir(tmp_path, 200.0, pin)
    args = ["--benchmarks", str(bdir), "--floors", str(fpath)]
    assert main(args + ["--update"]) == 2
    assert json.loads(fpath.read_text())[
        "RESULTS_pod.jsonl"]["floor"] == 1.0  # refused = untouched
    assert main(args + ["--update", "--ratio", "1.5",
                        "--reason", "x"]) == 2
    assert main(args + ["--update", "--reason",
                        "re-pin after the surge run"]) == 0
    entry = json.loads(fpath.read_text())["RESULTS_pod.jsonl"]
    assert entry["floor"] == pytest.approx(140.0)  # 0.7 * 200
    assert entry["pinned_value"] == 200.0
    assert entry["reason"] == "re-pin after the surge run"
    assert main(args) == 0  # and the fresh pin holds


def test_bench_gate_repo_floors_hold():
    """The committed FLOORS.json must be green against the committed
    RESULTS ledgers — the exact check CI runs."""
    from tools.bench_gate import run_gate

    repo = pathlib.Path(__file__).resolve().parent.parent
    failures, report = run_gate(repo / "benchmarks",
                                repo / "benchmarks" / "FLOORS.json")
    assert failures == [], "\n".join(report)
    # Every pinned entry carries its disclosed why.
    import json

    floors = json.loads(
        (repo / "benchmarks" / "FLOORS.json").read_text())
    for name, entry in floors.items():
        if not name.startswith("_"):
            assert entry.get("reason"), f"{name}: floor without a why"


# ------------------------------------------------ hygiene


def test_capacity_layer_lint_clean():
    """ISSUE 16: the autoscaling layer holds the repo's own bar —
    clean under ALL dcflint passes.  Determinism is the load-bearing
    one: every decision runs on the injectable clock."""
    from tools.dcflint import run_path

    repo = pathlib.Path(__file__).resolve().parent.parent
    assert run_path(repo / "dcf_tpu" / "serve" / "capacity.py") == []
    assert run_path(repo / "tools" / "bench_gate.py") == []
