"""ISSUE 7: the serve-resident frontier cache.

The prefix-family frontier is key material — a pure function of
(bundle, party, k) — so promoting it from the backend instance store to
a serve-resident LRU (``serve.frontier_cache``) must change WHERE the
expansion lives and nothing else.  Covered here:

* parity: cached (provider-bound) vs cold (instance-store) walks are
  bit-exact vs the numpy oracle — both parties, K=1 and K=3, the lam=16
  prefix backend, the lam=144 hybrid, and the sharded 2x2 hybrid;
* amortization semantics: a second instance of the same key hits the
  cache instead of rebuilding; budget eviction of a residency keeps the
  key's cached frontier;
* deterministic LRU: the registry's merged (images + frontiers) sweep
  evicts the coldest stamp first, pinned exactly;
* invalidation: hot-swap mid-flight fails typed (``StaleStateError``)
  and drops the key's cache entries — never a stale-frontier
  reconstruction; registry eviction clears the dropped instance's
  frontier state through the ONE ``invalidate_frontier`` hook (the
  pre-ISSUE-7 double seam); ``reset_backend_health`` sweeps everything;
* the slow Zipf soak: cache churn under 3-thread skewed load with an
  every-9th-eval fault, bit-exact before and after (serial CI leg).
"""

import threading

import numpy as np
import pytest

import dcf_tpu.api as api
from dcf_tpu import Dcf
from dcf_tpu.backends.frontier import FrontierConsumerMixin
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.errors import StaleStateError
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.serve.frontier_cache import FrontierCache, TickSource
from dcf_tpu.serve.registry import KeyRegistry
from dcf_tpu.testing import faults

pytestmark = pytest.mark.frontier_cache

NB, LAM = 2, 16


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0xF207)


@pytest.fixture(scope="module")
def ck(rng):
    return [rng.bytes(32) for _ in range(18)]


@pytest.fixture(scope="module")
def prg(ck):
    import warnings

    from dcf_tpu.spec import ReferenceContractWarning

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", ReferenceContractWarning)
        return HirosePrgNp(LAM, ck)


def gen_bundle(dcf, rng, k=1):
    alphas = rng.integers(0, 256, (k, NB), dtype=np.uint8)
    betas = rng.integers(0, 256, (k, dcf.lam), dtype=np.uint8)
    return dcf.gen(alphas, betas, rng=rng)


def oracle2(prg, bundle, xs):
    return eval_batch_np(prg, 0, bundle.for_party(0), xs) ^ \
        eval_batch_np(prg, 1, bundle.for_party(1), xs)


# ------------------------------------------------------ served parity


@pytest.mark.parametrize("k", [1, 3])
def test_served_parity_cached_vs_cold_vs_oracle(ck, prg, rng, k):
    """The acceptance parity leg: the SAME requests through a
    frontier-cached service and a cold (instance-store) service — both
    bit-exact vs the numpy oracle, both parties, K=1 and K=3."""
    dcf = Dcf(NB, LAM, ck, backend="prefix")
    bundle = gen_bundle(dcf, rng, k=k)
    xs = rng.integers(0, 256, (33, NB), dtype=np.uint8)
    want = oracle2(prg, bundle, xs)
    got = {}
    for mode, fc_on in (("cached", True), ("cold", False)):
        svc = dcf.serve(max_batch=64, frontier_cache=fc_on)
        svc.register_key("key", bundle)
        f0 = svc.submit("key", xs, b=0)
        f1 = svc.submit("key", xs, b=1)
        svc.pump()
        got[mode] = f0.result(1) ^ f1.result(1)
        assert np.array_equal(got[mode], want), mode
        snap = svc.metrics_snapshot()
        if fc_on:
            # stage-time warm = one miss per party; the evals hit
            assert snap["serve_frontier_misses_total"] == 2
            assert snap["serve_frontier_hits_total"] >= 2
            assert snap["serve_frontier_cache_entries"] == 2
        else:
            assert "serve_frontier_misses_total" not in snap
    assert np.array_equal(got["cached"], got["cold"])


def test_hybrid_provider_parity_k3_both_parties(rng):
    """The lam=144 hybrid (prefix_levels=6), K=3: a provider-bound
    instance's walk is bit-exact vs the instance-store walk and the
    full-width oracle — and a SECOND instance of the same key image
    consumes the cached expansion instead of rebuilding (the re-stage
    amortization the serve layer buys)."""
    import warnings

    from dcf_tpu.backends.large_lambda import LargeLambdaBackend
    from dcf_tpu.gen import gen_batch, random_s0s
    from dcf_tpu.spec import Bound, ReferenceContractWarning

    lam = 144
    ck = [rng.bytes(32) for _ in range(2 * (lam // 16) + 2)]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", ReferenceContractWarning)
        prg = HirosePrgNp(lam, ck)
    alphas = rng.integers(0, 256, (3, NB), dtype=np.uint8)
    betas = rng.integers(0, 256, (3, lam), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(3, lam, rng),
                       Bound.LT_BETA)
    xs = rng.integers(0, 256, (9, NB), dtype=np.uint8)
    xs[0] = alphas[0]

    fc = FrontierCache()
    for b in (0, 1):
        kb = bundle.for_party(b)
        cold = LargeLambdaBackend(lam, ck, prefix_levels=6, interpret=True)
        want = eval_batch_np(prg, b, kb, xs)
        assert np.array_equal(cold.eval(b, xs, bundle=kb), want)

        warm = LargeLambdaBackend(lam, ck, prefix_levels=6, interpret=True)
        warm.put_bundle(kb)
        warm.frontier_provider = fc.bind("key", 1)  # after put_bundle
        assert np.array_equal(warm.eval(b, xs), want)

        restaged = LargeLambdaBackend(lam, ck, prefix_levels=6,
                                      interpret=True)
        restaged.put_bundle(kb)
        restaged.frontier_provider = fc.bind("key", 1)
        assert np.array_equal(restaged.eval(b, xs), want)
    # one build per party; the re-staged instances were pure hits
    assert len(fc.lru_entries()) == 2
    assert fc._c_misses.value == 2
    assert fc._c_hits.value >= 2


def test_sharded_2x2_provider_parity(rng):
    """The sharded hybrid on the virtual 2x2 mesh with a provider bound:
    the cache holds the mesh-PLACED tables and the walk stays bit-exact
    vs the oracle, both parties."""
    import warnings

    from dcf_tpu.gen import gen_batch, random_s0s
    from dcf_tpu.parallel import ShardedLargeLambdaBackend, make_mesh
    from dcf_tpu.spec import Bound, ReferenceContractWarning

    lam = 144
    ck = [rng.bytes(32) for _ in range(2 * (lam // 16) + 2)]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", ReferenceContractWarning)
        prg = HirosePrgNp(lam, ck)
    alphas = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    betas = rng.integers(0, 256, (2, lam), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(2, lam, rng),
                       Bound.LT_BETA)
    xs = rng.integers(0, 256, (9, NB), dtype=np.uint8)

    mesh = make_mesh(shape=(2, 2))
    fc = FrontierCache()
    for b in (0, 1):
        kb = bundle.for_party(b)
        be = ShardedLargeLambdaBackend(lam, ck, mesh, interpret=True,
                                       prefix_levels=6)
        be.put_bundle(kb)
        be.frontier_provider = fc.bind("key", 1)
        want = eval_batch_np(prg, b, kb, xs)
        assert np.array_equal(be.eval(b, xs), want), f"party {b}"
    assert fc._c_misses.value == 2


# --------------------------------------------------- LRU + invalidation


class _FakeBundle:
    """Just enough bundle for KeyRegistry.register/for_party."""

    def __init__(self):
        self.s0s = np.zeros((1, 2, LAM), dtype=np.uint8)

    def for_party(self, b):
        return self


class _FakeFrontierBackend(FrontierConsumerMixin):
    """A minimal frontier consumer: 64-byte tables, build calls counted
    globally so cache hits are observable across instances."""

    prefix_levels = 4
    builds: list = []

    def __init__(self):
        self.invalidate_frontier()

    def put_bundle(self, kb):
        self.invalidate_frontier()

    def _k(self):
        return 4

    def _build_frontier_tables(self, b):
        _FakeFrontierBackend.builds.append(int(b))
        return np.zeros(64, dtype=np.uint8)


def make_registry(budget):
    fc = FrontierCache(ticks=TickSource())
    reg = KeyRegistry(_FakeFrontierBackend, device_bytes_budget=budget,
                      frontier_cache=fc)
    _FakeFrontierBackend.builds = []
    return reg, fc


def test_merged_lru_eviction_order_is_deterministic():
    """Tiny budget, known touch order: the merged sweep evicts the
    coldest FRONTIER stamp (a re-touched key's frontier survives a
    colder key's), pinned exactly — eviction order is a pure function
    of the request sequence."""
    reg, fc = make_registry(budget=3 * 64)
    for key in ("a", "b", "c"):
        reg.register(key, _FakeBundle())
        reg.resident(key, 0)  # stage + warm: fits exactly at 3 keys
    assert sorted(k[0] for _, k, _ in fc.lru_entries()) == ["a", "b", "c"]
    # re-touch a's frontier (a cache consult, like an eval dispatch)
    reg.resident("a", 0)._frontier_tables(0)
    reg.register("d", _FakeBundle())
    reg.resident("d", 0)  # over budget: the coldest frontier is b's
    held = sorted(k[0] for _, k, _ in fc.lru_entries())
    assert held == ["a", "c", "d"]
    # b's next touch rebuilds (a miss), evicting the now-coldest c
    reg.resident("b", 0)._frontier_tables(0)
    held = sorted(k[0] for _, k, _ in fc.lru_entries())
    assert held == ["a", "b", "d"]
    assert _FakeFrontierBackend.builds == [0, 0, 0, 0, 0]


def test_budget_eviction_of_residency_keeps_cached_frontier():
    """The amortization itself: budget-evicting a key's RESIDENCY (an
    uncounted 0-byte fake image here, evicted by stamp) leaves its
    cached frontier alone, so the re-staged instance is a pure hit —
    zero rebuilds."""
    reg, fc = make_registry(budget=4 * 64)
    for key in ("a", "b"):
        reg.register(key, _FakeBundle())
        reg.resident(key, 0)
    assert _FakeFrontierBackend.builds == [0, 0]
    # drop a's residency through the budget path by hand-evicting: the
    # entry-level hook is NOT used (that one invalidates the cache)
    entry = reg._entries["a"]
    res = entry.residents.pop(0)
    res.be.invalidate_frontier()  # what _enforce_budget does
    assert res.be.frontier_provider is None
    reg.resident("a", 0)  # re-stage: ensure_frontier hits the cache
    assert _FakeFrontierBackend.builds == [0, 0]  # no rebuild
    assert len(fc.lru_entries()) == 2


def test_entry_eviction_routes_through_one_invalidation_hook():
    """The ISSUE-7 satellite seam: unregister/hot-swap eviction clears
    the dropped instance's local frontier state AND unbinds its
    provider (an in-flight closure pinning the instance must not keep
    frontier bytes resident or serve the next key image), and drops the
    key's cache entries."""
    reg, fc = make_registry(budget=0)
    reg.register("a", _FakeBundle())
    be = reg.resident("a", 0)
    assert be.frontier_provider is not None
    assert len(fc.lru_entries()) == 1
    reg.unregister("a")
    assert be.frontier_provider is None  # unbound through the hook
    assert be._frontier == {}
    assert fc.lru_entries() == []  # cache entries invalidated too


def test_cold_instance_frontier_cleared_on_registry_eviction(ck, rng):
    """Same seam without a serve cache (frontier_cache=False): the
    instance-store frontier of an evicted residency is cleared even
    while a reference pins the instance — before the shared hook, those
    bytes stayed device-resident and uncounted."""
    dcf = Dcf(NB, LAM, ck, backend="prefix")
    svc = dcf.serve(max_batch=32, frontier_cache=False)
    svc.register_key("key", gen_bundle(dcf, rng))
    xs = rng.integers(0, 256, (8, NB), dtype=np.uint8)
    svc.submit("key", xs, b=0)
    svc.pump()
    be = svc.registry.resident("key", 0)
    assert be._frontier  # the lazy instance-store build happened
    svc.registry.evict_key("key")
    assert be._frontier == {}


def test_hot_swap_mid_flight_stale_not_stale_frontier(ck, prg, rng):
    """Hot-swap while a group snapshot is in flight: ``resident`` with
    the stale generation raises StaleStateError (never a reconstruction
    against the OLD key's cached frontier), the swapped key's cache
    entries are dropped, and fresh submissions serve the NEW bundle
    bit-exactly under a new generation's entries."""
    dcf = Dcf(NB, LAM, ck, backend="prefix")
    svc = dcf.serve(max_batch=64)
    b1 = gen_bundle(dcf, rng)
    svc.register_key("key", b1)
    xs = rng.integers(0, 256, (16, NB), dtype=np.uint8)
    f0 = svc.submit("key", xs, b=0)
    f1 = svc.submit("key", xs, b=1)
    svc.pump()
    assert np.array_equal(f0.result(1) ^ f1.result(1), oracle2(prg, b1, xs))
    _, _, gen = svc.registry.snapshot("key")
    old_keys = {k for _, k, _ in svc.frontier_cache.lru_entries()}
    assert {k[1] for k in old_keys} == {gen}

    b2 = gen_bundle(dcf, rng)
    svc.register_key("key", b2)  # hot-swap
    with pytest.raises(StaleStateError):
        svc.registry.resident("key", 0, gen)
    assert svc.frontier_cache.lru_entries() == []  # old frontiers gone
    f0 = svc.submit("key", xs, b=0)
    f1 = svc.submit("key", xs, b=1)
    svc.pump()
    assert np.array_equal(f0.result(1) ^ f1.result(1), oracle2(prg, b2, xs))
    new_keys = {k for _, k, _ in svc.frontier_cache.lru_entries()}
    assert old_keys.isdisjoint(new_keys)  # generation is part of the key


def test_reset_backend_health_sweeps_the_cache(ck, rng):
    """The shared invalidation path: frontier state derived from a
    backend declared dead must not outlive ``reset_backend_health``."""
    dcf = Dcf(NB, LAM, ck, backend="prefix")
    svc = dcf.serve(max_batch=32)
    svc.register_key("key", gen_bundle(dcf, rng))
    svc.submit("key", rng.integers(0, 256, (4, NB), dtype=np.uint8))
    svc.pump()
    assert svc.frontier_cache.lru_entries()
    api.reset_backend_health()
    assert svc.frontier_cache.lru_entries() == []


# ------------------------------------------------------ cache internals


def test_concurrent_miss_converges_on_first_insert():
    """Two racing misses: the first insert wins, the loser converges on
    it (the race costs a build, never correctness or a double-count)."""
    fc = FrontierCache()
    inner = np.ones(8, dtype=np.uint8)

    def racing_build():
        # simulate the concurrent thread inserting first
        fc.get(("k", 1, 0, 4), lambda: inner)
        return np.zeros(8, dtype=np.uint8)

    got = fc.get(("k", 1, 0, 4), racing_build)
    assert got is inner  # converged on the first insert
    assert len(fc.lru_entries()) == 1
    assert fc._c_misses.value == 2  # both paths were misses
    assert fc.total_bytes() == 8  # counted once


def test_invalidation_mid_build_does_not_resurrect_dead_state():
    """A build racing an invalidation (reset_backend_health or a
    hot-swap firing while the 2^k expansion runs outside the lock) must
    not re-insert tables computed against the dead/superseded state:
    the epoch bump makes the raced insert a no-op — the in-flight
    caller gets its tables (its batch fails/retries through the reset
    path anyway), the cache stays swept."""
    fc = FrontierCache()

    def build_during_reset():
        fc.invalidate_all()  # the shared reset path fires mid-build
        return np.zeros(8, dtype=np.uint8)

    got = fc.get(("k", 1, 0, 4), build_during_reset)
    assert got.nbytes == 8  # the caller is still served
    assert fc.lru_entries() == []  # but nothing persisted
    assert fc.total_bytes() == 0

    def build_during_hot_swap():
        fc.invalidate_key("k")  # generation bump sweeps this key
        return np.zeros(8, dtype=np.uint8)

    fc.get(("k", 1, 0, 4), build_during_hot_swap)
    assert fc.lru_entries() == []  # no orphan bytes in the budget
    # a clean build afterwards persists normally
    fc.get(("k", 2, 0, 4), lambda: np.zeros(8, dtype=np.uint8))
    assert len(fc.lru_entries()) == 1


def test_tick_source_is_shared_and_total():
    ts = TickSource()
    fc = FrontierCache(ticks=ts)
    reg = KeyRegistry(_FakeFrontierBackend, frontier_cache=fc)
    assert reg._ticks is ts is fc.ticks
    seen = [ts.next() for _ in range(3)]
    assert seen == sorted(seen) and len(set(seen)) == 3


def test_growth_hook_runs_outside_the_lock():
    fc = FrontierCache()
    state = {}

    def hook():
        # re-entering the cache from the hook must not deadlock
        state["entries"] = len(fc.lru_entries())

    fc.set_growth_hook(hook)
    fc.get(("k", 1, 0, 4), lambda: np.zeros(4, dtype=np.uint8))
    assert state["entries"] == 1


# ------------------------------------------------------- the Zipf soak


@pytest.mark.slow
def test_zipf_soak_cache_churn_under_faults(ck, prg, rng):
    """Serial-CI-leg soak: 3-thread Zipf(1.2) closed-loop load over 8
    keys under a byte budget tight enough to churn residencies AND
    frontiers, with every 9th serve.eval failing.  The service must
    stay up, hit the cache (amortization under churn), recover every
    injected failure typed, and still serve bit-exactly afterwards."""
    from dcf_tpu.serve.loadgen import closed_loop

    dcf = Dcf(NB, LAM, ck, backend="prefix")
    svc = dcf.serve(max_batch=64, max_delay_ms=2.0, retries=1,
                    max_queued_points=4096)
    bundles = {}
    for i in range(8):
        bundles[f"z{i}"] = gen_bundle(dcf, rng)
        svc.register_key(f"z{i}", bundles[f"z{i}"])

    calls = {"n": 0}

    def every_ninth(*_args):
        calls["n"] += 1
        if calls["n"] % 9 == 0:
            raise faults.InjectedFault("intermittent eval failure")

    with svc:
        m = 1
        while m <= 64:  # warm the ladder before the timed window
            svc.evaluate("z0",
                         rng.integers(0, 256, (m, NB), dtype=np.uint8),
                         timeout=180)
            m *= 2
        # Tighten the budget so the soak churns: after the ladder only
        # z0/party-0 is staged, so 4x its footprint fits roughly half
        # of the 8-key working set.
        snap0 = svc.metrics_snapshot()
        svc.registry.device_bytes_budget = max(
            1, (snap0["serve_resident_device_bytes"]
                + snap0["serve_frontier_cache_bytes"]) * 4)
        with faults.inject("serve.eval", handler=every_ninth):
            res = closed_loop(
                svc, sorted(bundles), duration_s=5.0, concurrency=3,
                min_points=1, max_points=48, seed=11, skew=1.2)
            rounds = 1
            while calls["n"] < 9 and rounds < 4:
                more = closed_loop(
                    svc, sorted(bundles), duration_s=5.0, concurrency=3,
                    min_points=1, max_points=48, seed=11 + rounds,
                    skew=1.2)
                res.requests_ok += more.requests_ok
                res.points_ok += more.points_ok
                res.requests_failed += more.requests_failed
                res.requests_shed += more.requests_shed
                rounds += 1
        # post-soak, faults disarmed: parity is still bit-exact
        xs = rng.integers(0, 256, (9, NB), dtype=np.uint8)
        y0 = svc.evaluate("z1", xs, b=0, timeout=60)
        y1 = svc.evaluate("z1", xs, b=1, timeout=60)
        assert np.array_equal(y0 ^ y1, oracle2(prg, bundles["z1"], xs))

    assert res.requests_ok > 0
    snap = svc.metrics_snapshot()
    assert snap["serve_queue_depth"] == 0
    assert snap["serve_queue_points"] == 0
    assert calls["n"] >= 9  # the fault really fired
    assert snap["serve_retries_total"] >= 1
    hits = snap["serve_frontier_hits_total"]
    misses = snap["serve_frontier_misses_total"]
    assert hits > 0 and hits / (hits + misses) >= 0.5


# thread-safety smoke for the cache itself (not slow: tiny tables)


def test_cache_get_thread_smoke():
    fc = FrontierCache()
    errs = []

    def worker(i):
        try:
            for j in range(50):
                key = ("k", 1, i % 2, 4 + j % 3)
                t = fc.get(key, lambda: np.zeros(16, dtype=np.uint8))
                assert t.nbytes == 16
        except Exception as e:  # pragma: no cover - diagnostic
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    assert len(fc.lru_entries()) == 6
