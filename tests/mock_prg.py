"""A trivially-fast, non-cryptographic Prg implementation.

Proves the Prg seam (dcf_tpu/ops/prg.py module docstring; reference
``trait Prg``, /root/reference/src/lib.rs:52-58): the GGM walk is generic
over the PRG construction, so the whole gen/eval protocol logic must work
unchanged with THIS construction substituted for Hirose/AES-256 — and the
spec / numpy / jax twins of it must stay bit-identical to each other.

The mock keeps the Hirose *dataflow* (truncated block loop, feed-forward
into both halves, t-bits sourced from half 0 before masking, 8*lam-1-bit
mask) but replaces the AES-256 block cipher with a 3-operation byte mix:

    mix(block)[i] = ((block[(i + 3) % 16] * 5 + 17 * i) & 0xFF) ^ 0xA5

so a spec-level PRG call costs ~100 byte ops instead of ~10k (14 AES
rounds in pure Python) — protocol-logic parity tests that don't test the
cipher itself run two orders of magnitude faster through it.  It needs no
cipher keys; the jax twin accepts and ignores ``round_keys`` to satisfy
the device-level protocol signature.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from dcf_tpu.ops.prg import PrgOut

__all__ = ["MockPrgSpec", "MockPrgNp", "mock_prg_gen_jax"]

_ROT = 3
_MUL = 5
_ADD = 17
_XOR = 0xA5


def _mix_bytes(block: bytes) -> bytes:
    return bytes(
        ((block[(i + _ROT) % 16] * _MUL + _ADD * i) & 0xFF) ^ _XOR
        for i in range(16)
    )


class MockPrgSpec:
    """Bytes-level twin (the ``spec.HirosePrgSpec`` interface)."""

    def __init__(self, lam: int):
        assert lam % 16 == 0
        self.lam = lam

    def gen(self, seed: bytes) -> list[tuple[bytes, bytes, bool]]:
        lam = self.lam
        assert len(seed) == lam
        seed_p = bytes(b ^ 0xFF for b in seed)
        buf0 = [bytearray(lam), bytearray(lam)]
        buf1 = [bytearray(lam), bytearray(lam)]
        for k in range(min(2, lam // 16)):
            lo, hi = 16 * k, 16 * (k + 1)
            buf0[k][lo:hi] = _mix_bytes(seed[lo:hi])
            buf1[k][lo:hi] = _mix_bytes(seed_p[lo:hi])
        for k in range(2):
            buf0[k] = bytearray(a ^ b for a, b in zip(buf0[k], seed))
            buf1[k] = bytearray(a ^ b for a, b in zip(buf1[k], seed_p))
        bit0 = bool(buf0[0][0] & 1)
        bit1 = bool(buf1[0][0] & 1)
        for buf in (buf0[0], buf0[1], buf1[0], buf1[1]):
            buf[lam - 1] &= 0xFE
        return [
            (bytes(buf0[0]), bytes(buf1[0]), bit0),
            (bytes(buf0[1]), bytes(buf1[1]), bit1),
        ]


def _mix_np(blocks: np.ndarray) -> np.ndarray:
    """uint8 [..., 16] -> uint8 [..., 16] (wrapping uint8 arithmetic)."""
    idx = np.arange(16, dtype=np.uint8)
    rolled = blocks[..., (idx + _ROT) % 16]
    return (rolled * np.uint8(_MUL) + idx * np.uint8(_ADD)) ^ np.uint8(_XOR)


class MockPrgNp:
    """Batched numpy twin (the ``HirosePrgNp`` interface)."""

    def __init__(self, lam: int, mask: bool = True):
        assert lam % 16 == 0
        self.lam = lam
        self.mask = mask

    def gen(self, seeds: np.ndarray) -> PrgOut:
        lam = self.lam
        assert seeds.dtype == np.uint8 and seeds.shape[-1] == lam
        seed_p = seeds ^ np.uint8(0xFF)
        batch = seeds.shape[:-1]
        buf0 = np.zeros((*batch, 2, lam), dtype=np.uint8)
        buf1 = np.zeros((*batch, 2, lam), dtype=np.uint8)
        for k in range(min(2, lam // 16)):
            lo, hi = 16 * k, 16 * (k + 1)
            buf0[..., k, lo:hi] = _mix_np(seeds[..., lo:hi])
            buf1[..., k, lo:hi] = _mix_np(seed_p[..., lo:hi])
        buf0 ^= seeds[..., None, :]
        buf1 ^= seed_p[..., None, :]
        t_l = buf0[..., 0, 0] & np.uint8(1)
        t_r = buf1[..., 0, 0] & np.uint8(1)
        if self.mask:
            buf0[..., lam - 1] &= np.uint8(0xFE)
            buf1[..., lam - 1] &= np.uint8(0xFE)
        return PrgOut(
            s_l=buf0[..., 0, :], v_l=buf1[..., 0, :], t_l=t_l,
            s_r=buf0[..., 1, :], v_r=buf1[..., 1, :], t_r=t_r,
        )


def mock_prg_gen_jax(round_keys, lam: int, seeds: jnp.ndarray):
    """Device-level twin (the ``eval_core`` ``prg_fn`` signature).

    ``round_keys`` is accepted and ignored — the mock is keyless.
    """
    seed_p = seeds ^ jnp.uint8(0xFF)
    batch = seeds.shape[:-1]
    idx = jnp.arange(16, dtype=jnp.uint8)
    perm = (idx + _ROT) % 16

    def mix(blocks):
        return (blocks[..., perm] * jnp.uint8(_MUL)
                + idx * jnp.uint8(_ADD)) ^ jnp.uint8(_XOR)

    n_enc = min(2, lam // 16)

    def assemble(src, which):
        out = jnp.zeros((*batch, lam), dtype=jnp.uint8)
        if which < n_enc:
            lo = 16 * which
            out = out.at[..., lo:lo + 16].set(mix(src[..., lo:lo + 16]))
        return out

    buf0 = [assemble(seeds, 0) ^ seeds, assemble(seeds, 1) ^ seeds]
    buf1 = [assemble(seed_p, 0) ^ seed_p, assemble(seed_p, 1) ^ seed_p]
    t_l = buf0[0][..., 0] & jnp.uint8(1)
    t_r = buf1[0][..., 0] & jnp.uint8(1)
    mask = jnp.full((lam,), 0xFF, dtype=jnp.uint8).at[lam - 1].set(0xFE)
    buf0 = [b & mask for b in buf0]
    buf1 = [b & mask for b in buf1]
    return buf0[0], buf1[0], t_l, buf0[1], buf1[1], t_r
