"""Bench CLI: runs, emits valid JSON, parity gate passes."""

import json

import pytest


def run_cli(capsys, argv):
    from dcf_tpu import cli

    cli.main(argv)
    out = capsys.readouterr().out.strip().splitlines()
    return [json.loads(line) for line in out]


def test_cli_dcf_latency(capsys):
    recs = run_cli(capsys, ["dcf", "--backend=cpu1", "--reps=1"])
    assert [r["bench"] for r in recs] == ["dcf_gen", "dcf_eval_1pt"]
    assert all(r["value"] > 0 for r in recs)


def test_cli_batch_eval_numpy_with_check(capsys):
    recs = run_cli(
        capsys,
        ["dcf_batch_eval", "--backend=numpy", "--points=64", "--reps=1",
         "--check"],
    )
    assert recs[0]["metric"] == "evals_per_sec"
    assert recs[0]["backend"] == "numpy"


def test_cli_rejects_pallas_large_lambda():
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="lam=16"):
        cli.main(["dcf_large_lambda", "--backend=pallas"])


@pytest.mark.slow
def test_cli_large_lambda_hybrid_smoke(capsys):
    """The staged hybrid CLI path end to end WITHOUT --check — the flow
    that once shipped without its put_bundle call and crashed at bench
    time with a green suite."""
    recs = run_cli(
        capsys,
        ["dcf_large_lambda", "--backend=hybrid", "--points=32", "--reps=1"],
    )
    assert recs[0]["backend"] == "hybrid"
    assert recs[0]["value"] > 0
    # and with the parity gate on
    recs = run_cli(
        capsys,
        ["dcf_large_lambda", "--backend=hybrid", "--points=64", "--reps=1",
         "--check"],
    )
    assert recs[0]["value"] > 0
