"""Bench CLI: runs, emits valid JSON, parity gate passes."""

import json

import pytest


def run_cli(capsys, argv):
    from dcf_tpu import cli

    cli.main(argv)
    out = capsys.readouterr().out.strip().splitlines()
    return [json.loads(line) for line in out]


def test_cli_dcf_latency(capsys):
    recs = run_cli(capsys, ["dcf", "--backend=cpu1", "--reps=1"])
    assert [r["bench"] for r in recs] == ["dcf_gen", "dcf_eval_1pt"]
    assert all(r["value"] > 0 for r in recs)


def test_cli_batch_eval_numpy_with_check(capsys):
    recs = run_cli(
        capsys,
        ["dcf_batch_eval", "--backend=numpy", "--points=64", "--reps=1",
         "--check"],
    )
    assert recs[0]["metric"] == "evals_per_sec"
    assert recs[0]["backend"] == "numpy"


def test_cli_rejects_pallas_large_lambda():
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="lam=16"):
        cli.main(["dcf_large_lambda", "--backend=pallas"])


@pytest.mark.slow
def test_cli_large_lambda_hybrid_smoke(capsys):
    """The staged hybrid CLI path end to end WITHOUT --check — the flow
    that once shipped without its put_bundle call and crashed at bench
    time with a green suite."""
    recs = run_cli(
        capsys,
        ["dcf_large_lambda", "--backend=hybrid", "--points=32", "--reps=1"],
    )
    assert recs[0]["backend"] == "hybrid"
    assert recs[0]["value"] > 0
    # and with the parity gate on
    recs = run_cli(
        capsys,
        ["dcf_large_lambda", "--backend=hybrid", "--points=64", "--reps=1",
         "--check"],
    )
    assert recs[0]["value"] > 0


@pytest.mark.slow
def test_cli_mid_lambda_hybrid_prefix_smoke(capsys):
    """The mid-lambda hybrid-prefix bench path end to end in the serial
    CI leg (round-6 valley work): lam=128 through --prefix-levels with
    the parity gate on, then the flag's hybrid-only contract."""
    recs = run_cli(
        capsys,
        ["dcf_large_lambda", "--backend=hybrid", "--lam=128",
         "--points=64", "--reps=1", "--prefix-levels=6", "--check"],
    )
    assert recs[0]["backend"] == "hybrid"
    assert recs[0]["value"] > 0

    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="prefix-levels"):
        cli.main(["dcf_batch_eval", "--backend=numpy",
                  "--prefix-levels=6"])


def test_pinned_ratio_corrupt_baseline(tmp_path):
    """ADVICE finding 2, regression-locked: a corrupt (or absent)
    benchmarks/cpu_baseline.json must yield {} — the bench line then
    simply omits vs_baseline instead of aborting the whole run or
    silently rationing against garbage."""
    from dcf_tpu.cli import _pinned_ratio

    corrupt = tmp_path / "cpu_baseline.json"
    corrupt.write_text("{ not json at all")
    assert _pinned_ratio(16, 1, 1e6, baseline_path=str(corrupt)) == {}
    absent = tmp_path / "nope.json"
    assert _pinned_ratio(16, 1, 1e6, baseline_path=str(absent)) == {}
    # and a healthy pin still produces the ratio, so the {} above is the
    # corrupt-file path, not a broken test
    healthy = tmp_path / "ok.json"
    healthy.write_text(json.dumps({"evals_per_sec": 5e5, "date": "x"}))
    rec = _pinned_ratio(16, 1, 1e6, baseline_path=str(healthy))
    assert rec["vs_baseline"] == 2.0


@pytest.mark.keygen
def test_cli_keygen_bench_validates_lam_fast():
    """keygen_bench's lam contract dies loudly BEFORE any keygen or
    compile work (the _parse_priority_mix discipline)."""
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="lam >= 48"):
        cli.main(["keygen_bench", "--lam=16"])
    with pytest.raises(SystemExit, match="lam >= 48"):
        cli.main(["keygen_bench", "--lam=40"])


@pytest.mark.keygen
def test_pinned_ratio_keygen_shapes(tmp_path):
    """_pinned_ratio's keygen route (ISSUE 10): the ratio comes from
    the ``keygen.lam{lam}`` pin in keys/s, only at the pin's own key
    count, survives interpreted runs WITH the disclosure note, and
    stays {} for corrupt/missing artifacts or unknown shapes."""
    from dcf_tpu.cli import _pinned_ratio

    healthy = tmp_path / "ok.json"
    healthy.write_text(json.dumps(
        {"keygen": {"lam128": {"keys_per_sec": 50.0, "keys": 64}}}))
    rec = _pinned_ratio(16, 64, 100.0, lam=128, keygen=True,
                        baseline_path=str(healthy))
    assert rec["vs_baseline"] == 2.0
    # interpreted keeps the ratio but discloses the numerator in-line
    rec_i = _pinned_ratio(16, 64, 100.0, lam=128, keygen=True,
                          interpreted=True, baseline_path=str(healthy))
    assert rec_i["vs_baseline"] == 2.0
    assert "interpret-mode numerator" in rec_i["baseline"]
    # wrong K, unknown lam, corrupt artifact -> no silent ratio
    assert _pinned_ratio(16, 8, 100.0, lam=128, keygen=True,
                         baseline_path=str(healthy)) == {}
    assert _pinned_ratio(16, 64, 100.0, lam=256, keygen=True,
                         baseline_path=str(healthy)) == {}
    corrupt = tmp_path / "bad.json"
    corrupt.write_text("{ nope")
    assert _pinned_ratio(16, 64, 100.0, lam=128, keygen=True,
                         baseline_path=str(corrupt)) == {}


@pytest.mark.keyfactory
def test_cli_keyfactory_bench_validates_flags_fast():
    """keyfactory_bench's flag contracts die loudly BEFORE the pool
    fills and parity gates spend real time (the _parse_priority_mix
    discipline), and --keyfactory without --crash-restart is refused
    by chaos_bench."""
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="lam >= 16"):
        cli.main(["keyfactory_bench", "--lam=8"])
    with pytest.raises(SystemExit, match="serves through"):
        cli.main(["keyfactory_bench", "--backend=pallas"])
    with pytest.raises(SystemExit, match="lam >= 48"):
        cli.main(["keyfactory_bench", "--backend=hybrid", "--lam=16"])
    with pytest.raises(SystemExit, match="crash-restart"):
        cli.main(["chaos_bench", "--backend=numpy", "--keyfactory",
                  "--duration=1"])


@pytest.mark.slow
@pytest.mark.keyfactory
def test_cli_keyfactory_bench_smoke(capsys, tmp_path):
    """The slow serial-leg CLI smoke (ISSUE 11): keyfactory_bench end
    to end at a small host-refill shape — both parity gates, the
    sustained publish-to-servable fills, the >= 10x pool-hit latency
    acceptance assertion (SystemExit if violated), and a short churn
    leg."""
    recs = run_cli(
        capsys,
        ["keyfactory_bench", "--lam=128", "--keys=8", "--reps=2",
         "--duration=2", "--concurrency=2", "--host-refill",
         "--min-req-points=2", "--max-req-points=8",
         f"--store-dir={tmp_path / 'kf'}", "--seed=11"],
    )
    assert len(recs) == 1
    rec = recs[0]
    assert rec["bench"] == "keyfactory_bench"
    assert rec["metric"] == "keys_per_sec" and rec["value"] > 0
    assert rec["pool_hit_speedup"] >= 10
    assert rec["device_fallbacks"] == 0
    assert rec["pool_misses"] >= 1  # the fallback gate leg is counted
    assert rec["churn_sessions_ok"] >= 1
    assert "repro" in rec
    assert (tmp_path / "kf" / "MANIFEST.dcfm").exists()


@pytest.mark.slow
@pytest.mark.keyfactory
def test_cli_chaos_crash_restart_keyfactory_smoke(capsys, tmp_path):
    """ISSUE 11: chaos_bench --crash-restart --keyfactory end to end —
    batched durable refills, a kill between the frame writes and the
    manifest flip, and a warm restart restoring the un-claimed pool
    supply with zero torn entries, zero re-keygen and generations
    held (the harness raises SystemExit otherwise)."""
    recs = run_cli(
        capsys,
        ["chaos_bench", "--backend=numpy", "--crash-restart",
         "--keyfactory", "--duration=2", "--max-batch=64",
         "--concurrency=2", "--fault-window=6",
         "--breaker-cooldown=0.05",
         f"--store-dir={tmp_path / 'store'}"],
    )
    rec = recs[0]
    assert rec["scenario"] == "crash-restart"
    assert rec["assertions_failed"] == []
    assert rec["regen_count"] == 0 and rec["quarantined"] == 0
    assert rec["pool_published"] == 6
    assert rec["pool_claimed_pre_kill"] == 2
    assert rec["pool_restored"] == 4


@pytest.mark.slow
@pytest.mark.keygen
def test_cli_keygen_bench_smoke(capsys):
    """The slow serial-leg CLI smoke (ISSUE 10): keygen_bench end to
    end at lam=128 with a single-K sweep — the reconstruction gate, the
    MIC 2m leg, the JSONL line with legs + interpret disclosure."""
    recs = run_cli(capsys, ["keygen_bench", "--lam=128", "--reps=1",
                            "--keys=2", "--intervals=2", "--seed=7"])
    assert len(recs) == 1
    rec = recs[0]
    assert rec["bench"] == "keygen_bench"
    assert rec["metric"] == "keys_per_sec"
    assert rec["value"] > 0
    assert rec["lam"] == 128
    assert [leg["keys"] for leg in rec["legs"]] == [2]
    assert rec["mic_keys_per_sec"] > 0
    assert rec["host_gen_batch_keys_per_sec"] > 0
    assert "repro" in rec
    if rec["interpreted"]:
        assert "interpret" in rec["unit"]


def test_bench_clamped_samples_excluded():
    """ADVICE finding 1, regression-locked: a sample the sync-RTT
    correction dominates is EXCLUDED from the headline median (and
    counted), never floored into a fake near-zero time that would drag
    the median down."""
    import statistics

    from bench import rtt_corrected_times

    # one poisoned sample (0.08s < rtt=0.1) among honest ~0.5s samples
    times, clamped = rtt_corrected_times(
        [0.5, 0.08, 0.52, 0.54], rtt_s=0.1, iters=2)
    assert clamped == 1
    assert len(times) == 3
    # headline median over the surviving samples only
    assert statistics.median(times) == (0.52 - 0.1) / 2
    # all-clamped degenerates to an empty list (bench.py then aborts
    # rather than print a rate)
    times, clamped = rtt_corrected_times([0.05, 0.09], rtt_s=0.1, iters=2)
    assert times == [] and clamped == 2


@pytest.mark.slow
@pytest.mark.protocols
def test_cli_mic_bench_smoke(capsys):
    """mic_bench end to end on the numpy backend (tiny closed loop):
    parity gate vs the protocol oracle, a valid JSONL line with the
    served-points metric, the staged-MicEvaluator companion rate, and
    the pinned numpy-oracle vs_baseline (the committed pin covers the
    default m=8)."""
    recs = run_cli(
        capsys,
        ["mic_bench", "--backend=numpy", "--duration=1", "--reps=1",
         "--max-batch=256", "--concurrency=2"],
    )
    assert recs[0]["bench"] == "mic_bench"
    assert recs[0]["metric"] == "points_per_sec"
    assert recs[0]["intervals"] == 8
    assert recs[0]["value"] > 0
    assert recs[0]["staged_mic_points_per_sec"] > 0
    assert "vs_baseline" in recs[0]  # the committed mic_m8 pin resolves


def test_cli_mic_bench_rejects_non_facade_backend():
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="mic_bench"):
        cli.main(["mic_bench", "--backend=cpu"])


@pytest.mark.slow
@pytest.mark.chaos
def test_cli_chaos_bench_smoke(capsys):
    """chaos_bench end to end on the numpy backend: the declarative
    fail-N-then-recover schedule runs to recovery, the harness's own
    resilience assertions hold (it raises SystemExit otherwise — the
    CI-soak contract), and the JSONL line records the breaker walk and
    the class-split outcome."""
    recs = run_cli(
        capsys,
        ["chaos_bench", "--backend=numpy", "--duration=3",
         "--max-batch=64", "--concurrency=3", "--fault-window=8",
         "--breaker-failures=2", "--breaker-cooldown=0.05"],
    )
    assert recs[0]["bench"] == "chaos_bench"
    assert recs[0]["assertions_failed"] == []
    assert recs[0]["fault_evals_failed"] == 8
    assert recs[0]["breaker_opens"] >= 1
    assert recs[0]["breaker_closes"] >= 1
    assert recs[0]["by_class"]["critical"].get("shed", 0) == 0


def test_cli_chaos_bench_rejects_non_facade_backend():
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="chaos_bench"):
        cli.main(["chaos_bench", "--backend=cpu"])


@pytest.mark.slow
@pytest.mark.edge
def test_cli_edge_bench_smoke(capsys):
    """ISSUE 12: edge_bench end to end — wire parity vs the C++ core,
    the single-feed ingest probe, interleaved wire/in-process legs
    with the >= 0.8 ratio gate, the 8-connection soak under the
    deterministic edge.read fault (reconnects observed, zero
    mismatches), a fully-hinted refusal leg, and the open-loop latency
    leg with its metric reconciliation (the harness raises SystemExit
    if any gate fails — the CI-soak contract)."""
    recs = run_cli(
        capsys,
        ["edge_bench", "--duration=6", "--max-batch=2048"],
    )
    assert recs[0]["bench"] == "edge_bench"
    assert recs[0]["wire_vs_inprocess"] >= 0.8
    assert recs[0]["ingest_single_feed"] is True
    assert recs[0]["soak_mismatches"] == 0
    assert recs[0]["soak_reconnects"] >= 1
    assert recs[0]["refusals"] >= 1
    assert recs[0]["refusals_hinted"] == recs[0]["refusals"]
    assert recs[0]["open_loop_reconciled"] is True
    assert "interpret" in recs[0]["unit"] or \
        recs[0]["platform"] == "tpu"


@pytest.mark.edge
def test_cli_edge_bench_validates_flags_fast():
    """edge_bench applies the fail-fast flag discipline: a bad backend,
    connection count or request-size range dies loudly before the
    bundle gen / warmup ladder spend real time."""
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="edge_bench"):
        cli.main(["edge_bench", "--backend=sharded"])
    with pytest.raises(SystemExit, match="connections"):
        cli.main(["edge_bench", "--connections=0"])
    with pytest.raises(SystemExit, match="request-size range"):
        cli.main(["edge_bench", "--max-batch=64",
                  "--min-req-points=200"])


@pytest.mark.slow
@pytest.mark.durability
def test_cli_chaos_bench_crash_restart_smoke(capsys, tmp_path):
    """ISSUE 8: chaos_bench --crash-restart end to end — durable keys
    survive a mid-stage kill, restore with zero re-keygen and preserved
    generations, and the post-restart two-party parity gate vs the C++
    core passes (the harness raises SystemExit otherwise)."""
    recs = run_cli(
        capsys,
        ["chaos_bench", "--backend=numpy", "--crash-restart",
         "--duration=2", "--max-batch=64", "--concurrency=2",
         "--fault-window=6", "--breaker-cooldown=0.05",
         f"--store-dir={tmp_path / 'store'}"],
    )
    assert recs[0]["bench"] == "chaos_bench"
    assert recs[0]["scenario"] == "crash-restart"
    assert recs[0]["assertions_failed"] == []
    assert recs[0]["regen_count"] == 0
    assert recs[0]["restored"] == recs[0]["bundles"]
    assert recs[0]["quarantined"] == 0
    # an operator-chosen --store-dir is kept around for forensics
    assert (tmp_path / "store" / "MANIFEST.dcfm").exists()


@pytest.mark.durability
def test_cli_crash_restart_validates_flags_fast(tmp_path):
    """The --crash-restart scenario applies the same fail-fast flag
    discipline as the other serve benches: bad ranges/windows die
    loudly before the bundle gen and warmup ladder spend real time."""
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="request-size range"):
        cli.main(["chaos_bench", "--backend=bitsliced",
                  "--crash-restart", "--max-batch=64",
                  "--min-req-points=200"])
    with pytest.raises(SystemExit, match="fault-window"):
        cli.main(["chaos_bench", "--backend=bitsliced",
                  "--crash-restart", "--fault-window=0"])
    with pytest.raises(SystemExit, match="chaos_bench"):
        cli.main(["chaos_bench", "--backend=cpu", "--crash-restart"])


def test_cli_chaos_bench_validates_range_and_window_fast():
    """A bad request-size range or fault window dies loudly BEFORE the
    bundle gen / warmup ladder spend real time — a min_req > max_req
    range would otherwise kill every loadgen client at rng.integers
    (outside the client's try) and report 'breaker never opened'."""
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="request-size range"):
        cli.main(["chaos_bench", "--backend=bitsliced", "--max-batch=64",
                  "--min-req-points=200"])
    with pytest.raises(SystemExit, match="fault-window"):
        cli.main(["chaos_bench", "--backend=bitsliced",
                  "--fault-window=0"])


def test_cli_skew_flag_validated_before_warmup():
    """ISSUE 7 satellite: serve_bench / mic_bench / chaos_bench share
    the --skew edge validation — a negative, NaN or unparseable Zipf
    exponent dies with SystemExit naming the flag BEFORE the bundle gen
    and warmup ladder spend real time (inside the clients it would die
    in rng.choice, silently zeroing the offered load)."""
    from dcf_tpu import cli

    for bench in ("serve_bench", "mic_bench", "chaos_bench"):
        for bad in ("-1", "nan", "zipf"):
            with pytest.raises(SystemExit, match="--skew"):
                cli.main([bench, "--backend=bitsliced",
                          f"--skew={bad}"])


def test_cli_parse_priority_mix_validation():
    """Malformed --priority-mix entries fail loudly naming the flag and
    the expected shape — not with a bare float('') traceback — and
    duplicates are rejected instead of silently overwritten."""
    from dcf_tpu.cli import _parse_priority_mix

    assert _parse_priority_mix("critical=0.2,batch=0.8") == {
        "critical": 0.2, "batch": 0.8}
    for bad in ("critical,normal=1", "critical=", "critical=x",
                "urgent=1"):
        with pytest.raises(SystemExit, match="priority-mix"):
            _parse_priority_mix(bad)
    with pytest.raises(SystemExit, match="duplicate"):
        _parse_priority_mix("batch=0.2,batch=0.3")
    # Negative / NaN / inf weights and an all-zero mix must die HERE,
    # before the warmup ladder — NaN in particular compares false to 0
    # and would otherwise reach rng.choice inside every client thread,
    # silently zeroing the offered load.
    for bad in ("critical=-1,normal=2", "critical=nan,normal=1",
                "critical=inf"):
        with pytest.raises(SystemExit, match="finite non-negative"):
            _parse_priority_mix(bad)
    with pytest.raises(SystemExit, match="sum to zero"):
        _parse_priority_mix("critical=0,normal=0")


@pytest.mark.pod
def test_cli_pod_bench_validates_flags_fast():
    """pod_bench/serve_host apply the fail-fast flag discipline: a bad
    shard count, backend, or request-size range (and a serve_host with
    nowhere to restore keys from) dies loudly before any subprocess is
    spawned or a warmup ladder runs."""
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="shards"):
        cli.main(["pod_bench", "--shards=1"])
    with pytest.raises(SystemExit, match="facade backends"):
        cli.main(["pod_bench", "--backend=sharded"])
    with pytest.raises(SystemExit, match="request-size range"):
        cli.main(["pod_bench", "--max-batch=64",
                  "--min-req-points=200"])
    with pytest.raises(SystemExit, match="store-dir"):
        cli.main(["serve_host"])


@pytest.mark.selfheal
def test_cli_pod_bench_selfheal_validates_flags_fast():
    """ISSUE 14: the partition/flap scenario applies the same
    fail-fast flag discipline — bad probe cadence, live-key count or
    shard count die loudly before any subprocess is spawned."""
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="probe-interval"):
        cli.main(["pod_bench", "--partition", "--probe-interval=0"])
    with pytest.raises(SystemExit, match="live-bundles"):
        cli.main(["pod_bench", "--partition", "--live-bundles=-1"])
    with pytest.raises(SystemExit, match="shards"):
        cli.main(["pod_bench", "--flap", "--shards=1"])
    with pytest.raises(SystemExit, match="probe-interval"):
        cli.main(["pod_bench", "--probe-interval=-1"])
    with pytest.raises(SystemExit, match="live-bundles"):
        cli.main(["pod_bench", "--live-bundles=-1"])


@pytest.mark.slow
@pytest.mark.pod
def test_cli_pod_bench_smoke(capsys):
    """ISSUE 13: pod_bench end to end — 3 serve_host shard PROCESSES
    (+ the solo leg's) warm-restored from ring-placed replicated
    stores behind the DCFE router, interleaved solo/pod closed-loop
    legs, the open-loop pod-rollup reconciliation, and the
    kill-a-shard failover soak with every request accounted (the
    harness raises SystemExit if any gate fails).  The >= 2.2x
    throughput gate applies only where the host offers the pod
    parallelism; on smaller hosts the emitted line records it
    environment-gated — asserted either way."""
    recs = run_cli(
        capsys,
        ["pod_bench", "--shards=3", "--duration=6", "--bundles=6",
         "--max-batch=256", "--concurrency=3"],
    )
    assert recs[0]["bench"] == "pod_bench"
    assert recs[0]["shards"] == 3
    assert recs[0]["soak_mismatches"] == 0
    assert recs[0]["soak_unaccounted"] == 0
    assert recs[0]["soak_refused_unhinted"] == 0
    assert recs[0]["failover_parity"] is True
    assert recs[0]["generations_held"] is True
    assert recs[0]["pod_quarantined"] == 0
    assert recs[0]["open_loop_pod_reconciled"] is True
    assert recs[0]["router_failovers"] >= 1
    gate = recs[0]["throughput_gate"]
    assert gate.startswith("applies") or \
        gate.startswith("environment-gated")
    if gate.startswith("applies"):
        assert recs[0]["pod_vs_single"] >= 2.2
    # ISSUE 14: the kill soak's live (non-durable) keys served from
    # the promoted replica — generations preserved, zero re-keygen.
    assert recs[0]["live_bundles"] >= 1
    if recs[0]["victim_live_keys"]:
        assert recs[0]["critical_within_s"] is not None
        assert recs[0]["down_observed"] is True


@pytest.mark.slow
@pytest.mark.membership
def test_cli_pod_bench_churn_smoke(capsys):
    """ISSUE 15: ``pod_bench --churn`` end to end — SIGKILL one shard,
    the membership controller auto-ejects it after the grace with
    every frame re-replicated to the new placement (verified over the
    DIGEST verb and the stores), the healed shard re-joins through
    the anti-entropy warm-up, a second shard is gracefully drained
    (its SIGTERM drains and exits 0), and a doctored stale-epoch
    frame is refused E_EPOCH.  The harness raises SystemExit unless
    the ledger is clean, generations never regress, zero keys are
    lost, all four membership events committed under strictly-
    increasing epochs, and zero frames quarantined."""
    recs = run_cli(
        capsys,
        ["pod_bench", "--churn", "--shards=3", "--bundles=3",
         "--live-bundles=3", "--max-batch=256", "--eject-grace=1.5",
         "--probe-interval=0.2"],
    )
    assert recs[0]["bench"] == "pod_bench"
    assert recs[0]["mode"] == "churn"
    assert recs[0]["soak_mismatches"] == 0
    assert recs[0]["soak_unaccounted"] == 0
    assert recs[0]["soak_refused_unhinted"] == 0
    assert recs[0]["digest_regressions"] == 0
    assert recs[0]["lost_keys"] == 0
    assert recs[0]["fence_held"] is True
    assert recs[0]["post_fence_parity"] is True
    assert recs[0]["drained_exit_rc"] == 0
    assert recs[0]["pod_quarantined"] == 0
    e1, e2, e3 = recs[0]["epochs"]
    assert 1 <= e1 < e2 < e3
    for kind in ("eject", "join", "drain", "drain-complete"):
        assert kind in recs[0]["membership_events"]
    assert recs[0]["migrated_frames"] >= 1


@pytest.mark.autoscale
def test_cli_pod_bench_surge_validates_flags_fast():
    """ISSUE 16: the surge scenario applies the same fail-fast flag
    discipline — a solo ring, an empty standby pool, a dead probe
    cadence, a non-positive reaction bound or a mixed scenario die
    loudly before any subprocess is spawned."""
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="--shards >= 2"):
        cli.main(["pod_bench", "--surge", "--shards=1"])
    with pytest.raises(SystemExit, match="standby-hosts"):
        cli.main(["pod_bench", "--surge", "--standby-hosts=0"])
    with pytest.raises(SystemExit, match="probe-interval"):
        cli.main(["pod_bench", "--surge", "--probe-interval=0"])
    with pytest.raises(SystemExit, match="reaction-bound"):
        cli.main(["pod_bench", "--surge", "--reaction-bound=-1"])
    with pytest.raises(SystemExit, match="separate"):
        cli.main(["pod_bench", "--surge", "--churn"])
    with pytest.raises(SystemExit, match="separate"):
        cli.main(["pod_bench", "--surge", "--flap"])


@pytest.mark.slow
@pytest.mark.autoscale
def test_cli_pod_bench_surge_smoke(capsys):
    """ISSUE 16: ``pod_bench --surge`` end to end — a calibrated
    open-loop Zipf ramp overloads a 2-shard ring, the capacity
    controller admits the standby host within the reaction bound and
    drains the least-loaded host on the idle tail, a scripted
    oscillating-verdict leg pins zero churn, and the harness raises
    SystemExit unless every gate holds: zero lost keys, zero
    generation regressions, zero CRITICAL sheds, the heartbeat
    bit-exact, strictly-increasing epochs, post-shrink parity."""
    recs = run_cli(
        capsys,
        ["pod_bench", "--surge", "--shards=2", "--standby-hosts=1",
         "--bundles=2", "--duration=8", "--max-batch=256"],
    )
    assert recs[0]["bench"] == "pod_bench"
    assert recs[0]["mode"] == "surge"
    assert recs[0]["reaction_s"] is not None
    assert recs[0]["reaction_s"] <= recs[0]["reaction_bound_s"]
    kinds = [k for k, _h, _e in recs[0]["capacity_events"]]
    assert kinds.count("scale-out") >= 1
    assert kinds.count("scale-in") >= 1
    epochs = recs[0]["epochs"]
    assert all(b > a for a, b in zip(epochs, epochs[1:]))
    assert recs[0]["lost_keys"] == 0
    assert recs[0]["digest_regressions"] == 0
    assert recs[0]["pod_critical_shed"] == 0
    assert recs[0]["critical_hb_ok"] >= 1
    assert recs[0]["critical_hb_refused_unhinted"] == 0
    assert recs[0]["critical_hb_unaccounted"] == 0
    assert recs[0]["osc_events"] == 0
    assert recs[0]["osc_epoch_moved"] is False
    assert recs[0]["post_shrink_parity"] is True
    assert len(recs[0]["final_ring"]) == recs[0]["shards"]
    assert len(recs[0]["standby_after"]) == recs[0]["standby_hosts"]


@pytest.mark.mesh
def test_cli_pod_bench_mesh_validates_flags_fast():
    """ISSUE 18: the mesh scenario applies the same fail-fast flag
    discipline — a solo "mesh" (co-evaluating over one worker IS
    route-mode), a mixed scenario, or a bad ladder range die loudly
    before any subprocess is spawned."""
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="shards >= 2"):
        cli.main(["pod_bench", "--mesh", "--shards=1"])
    with pytest.raises(SystemExit, match="separate"):
        cli.main(["pod_bench", "--mesh", "--surge"])
    with pytest.raises(SystemExit, match="separate"):
        cli.main(["pod_bench", "--mesh", "--churn"])
    with pytest.raises(SystemExit, match="separate"):
        cli.main(["pod_bench", "--mesh", "--partition"])
    with pytest.raises(SystemExit, match="ladder range"):
        cli.main(["pod_bench", "--mesh", "--min-req-points=4096",
                  "--max-req-points=128"])


@pytest.mark.slow
@pytest.mark.mesh
def test_cli_pod_bench_mesh_smoke(capsys):
    """ISSUE 18: ``pod_bench --mesh`` end to end — 2 serve_host shard
    processes warm-restore the mesh-wide-replicated keys, a route-only
    and a co-evaluate router form over the identical pod, the two-party
    parity gate pins the scattered/gathered reconstruction bit-exact vs
    route-mode AND the numpy oracle, and the crossover ladder runs with
    every co-evaluation accounted and zero degrades (the harness raises
    SystemExit if any gate fails).  The crossover gate itself applies
    only where the host offers >= shards + 1 CPUs; on smaller hosts the
    emitted line records it environment-gated — asserted either way."""
    recs = run_cli(
        capsys,
        ["pod_bench", "--mesh", "--shards=2", "--bundles=2",
         "--reps=3", "--max-batch=512", "--min-req-points=128",
         "--max-req-points=512"],
    )
    assert recs[0]["bench"] == "pod_bench"
    assert recs[0]["mode"] == "mesh"
    assert recs[0]["shards"] == 2
    assert recs[0]["mesh_workers"] == 2
    assert recs[0]["mesh_degraded"] == 0
    # parity gate (2 keys x 2 parties) + ladder legs, warmup on top
    assert recs[0]["co_evals"] >= 2 * 2 + 2 * 3
    ladder = recs[0]["ladder"]
    assert [r["points"] for r in ladder] == [128, 512]
    for rung in ladder:
        assert rung["route_evals_per_sec"] > 0
        assert rung["coeval_evals_per_sec"] > 0
    gate = recs[0]["crossover_gate"]
    assert gate.startswith("applies") or \
        gate.startswith("environment-gated")
    if gate.startswith("applies"):
        assert recs[0]["crossover_points"] is not None
        assert recs[0]["crossover_points"] <= 512
    assert "crossover_points" in recs[0]
    assert recs[0]["repro"].startswith(
        "python -m dcf_tpu.cli pod_bench --mesh")


@pytest.mark.slow
@pytest.mark.selfheal
def test_cli_pod_bench_partition_smoke(capsys):
    """ISSUE 14: ``pod_bench --partition`` end to end — a
    ``net.partition`` window cuts the router<->victim link under
    mixed load while the health prober runs; the harness raises
    SystemExit unless the ledger is clean, the victim walks DOWN and
    back UP through the anti-entropy gate, the mid-cut registration
    converges with zero generation regressions, promotion serves
    NORMAL traffic from the replica, and the doctored old-generation
    frame is fenced typed."""
    recs = run_cli(
        capsys,
        ["pod_bench", "--partition", "--shards=3", "--duration=8",
         "--bundles=4", "--live-bundles=3", "--max-batch=256"],
    )
    assert recs[0]["bench"] == "pod_bench"
    assert recs[0]["mode"] == "partition"
    assert recs[0]["soak_mismatches"] == 0
    assert recs[0]["soak_unaccounted"] == 0
    assert recs[0]["soak_refused_unhinted"] == 0
    assert recs[0]["down_seen"] == 1
    assert recs[0]["up_recovered"] == 1
    assert recs[0]["digest_converged"] is True
    assert recs[0]["digest_regressions"] == 0
    assert recs[0]["fence_held"] is True
    assert recs[0]["post_heal_parity"] is True
    assert recs[0]["anti_entropy_runs"] >= 1
    assert recs[0]["anti_entropy_frames"] >= 1
    assert len(recs[0]["promoted_serve_s"]) == 1


def test_cli_pir_bench_validates_flags_fast():
    """pir_bench's domain and batch contracts die loudly BEFORE any
    database packing or kernel compile work."""
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="5 <= n <= 24"):
        cli.main(["pir_bench", "--n-bits=3"])
    with pytest.raises(SystemExit, match="5 <= n <= 24"):
        cli.main(["pir_bench", "--n-bits=25"])
    with pytest.raises(SystemExit, match="queries-per-batch"):
        cli.main(["pir_bench", "--keys=-1"])


@pytest.mark.pir
def test_dpf_pinned_ratio_shapes(tmp_path):
    """_dpf_pinned_ratio: the pir_bench denominator comes from the
    dpf.evalall_n16 pin, rescaled by leaf count for other domains,
    interpret runs keep the ratio but disclose the numerator, and a
    missing/corrupt pin yields {} (no silent in-run fallback)."""
    import json

    from dcf_tpu.cli import _dpf_pinned_ratio

    pin = tmp_path / "cpu_baseline.json"
    pin.write_text(json.dumps(
        {"dpf": {"evalall_n16": {"queries_per_sec": 2.0}}}))
    rec = _dpf_pinned_ratio(16, 4.0, baseline_path=str(pin))
    assert rec["vs_baseline"] == 2.0
    assert "dpf.evalall_n16" in rec["baseline"]
    assert "interpret" not in rec["baseline"]
    # n=14 has 4x fewer leaves -> the denominator scales up 4x
    rec14 = _dpf_pinned_ratio(14, 4.0, baseline_path=str(pin))
    assert rec14["vs_baseline"] == 0.5
    assert "rescaled x 2^16/2^14" in rec14["baseline"]
    rec_i = _dpf_pinned_ratio(16, 4.0, interpreted=True,
                              baseline_path=str(pin))
    assert "interpret-mode numerator" in rec_i["baseline"]
    other = tmp_path / "other.json"
    other.write_text(json.dumps({"keygen": {}}))
    assert _dpf_pinned_ratio(16, 4.0, baseline_path=str(other)) == {}
    corrupt = tmp_path / "corrupt.json"
    corrupt.write_text("{nope")
    assert _dpf_pinned_ratio(16, 4.0, baseline_path=str(corrupt)) == {}
    assert _dpf_pinned_ratio(
        16, 4.0, baseline_path=str(tmp_path / "absent.json")) == {}
