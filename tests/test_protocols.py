"""dcf_tpu.protocols: IC / MIC / piecewise over batched DCF (ISSUE 5).

Covers the acceptance contract — MIC over >= 8 intervals, K-packed,
reconstructing bit-exactly vs the numpy oracle on every facade-reachable
backend (auto, bitsliced, prefix, the sharded 2x2 virtual mesh, both
parties), including under injected ``protocols.combine`` and
``serve.eval`` faults with retries — plus the IC edge-case property
sweep (``x = p``, ``x = q-1``, ``x = q``, empty ``p == q``, full-domain,
wraparound ``p > q``, adjacent MIC partitions, GT_BETA), the DCFK v3
wire format (round-trip, corruption, version gating against v2), the
staged ``MicEvaluator``'s on-device combine parity with the facade
path, piecewise-constant lookup, and the serve-layer protocol
registration.
"""

import numpy as np
import pytest

from dcf_tpu import Dcf
from dcf_tpu.errors import KeyFormatError, ShapeError, StaleStateError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.protocols import (
    MicEvaluator,
    ProtocolBundle,
    eval_interval,
    eval_mic,
    gen_interval_bundle,
    ic_oracle,
    interval_bound_alphas,
    mic_oracle,
    partition_intervals,
    piecewise_oracle,
)
from dcf_tpu.spec import Bound
from dcf_tpu.testing import faults

pytestmark = pytest.mark.protocols

NB, LAM = 2, 16
N = 1 << 16


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0x1C5)


@pytest.fixture(scope="module")
def ck(rng):
    return [rng.bytes(32), rng.bytes(32)]


@pytest.fixture(scope="module")
def dcf(ck):
    return Dcf(NB, LAM, ck, backend="numpy")


#: The acceptance MIC shape: 8 disjoint intervals exercising every edge
#: class at once — plain, adjacent (shared bound 300), wraparound
#: (60000, 300 wraps past the domain top... kept disjoint from the rest
#: by construction), empty, full-ish suffix, single-point, and the
#: N-as-upper-bound suffix form.
MIC_INTERVALS = [
    (10, 200),        # plain
    (200, 300),       # adjacent to the previous (shares bound 200)
    (300, 1000),      # adjacent again
    (5000, 5000),     # empty
    (6000, 6001),     # single point
    (40000, 50000),   # plain, high
    (60000, 2000),    # wraparound p > q
    (65000, N),       # suffix via q = N = 2^16
]


def edge_points(intervals):
    """Every bound's neighborhood: x = p, q-1, q (mod N) per interval,
    plus the domain corners."""
    pts = {0, N - 1, 1}
    for p, q in intervals:
        for b in (p, q):
            for x in (b - 1, b, b + 1):
                pts.add(x % N)
    xs = sorted(pts)
    return np.array([[x >> 8, x & 0xFF] for x in xs], dtype=np.uint8)


def mixed_points(rng, intervals, extra=64):
    return np.vstack([
        edge_points(intervals),
        rng.integers(0, 256, (extra, NB), dtype=np.uint8)])


def make_mic(dcf, rng, intervals=MIC_INTERVALS, bound=Bound.LT_BETA):
    betas = rng.integers(0, 256, (len(intervals), LAM), dtype=np.uint8)
    return dcf.mic(intervals, betas, bound=bound, rng=rng), betas


# ----------------------------------------------------- oracle self-checks


def test_oracle_edges():
    beta = np.arange(1, LAM + 1, dtype=np.uint8)
    xs = np.array([[0, 9], [0, 10], [0, 199], [0, 200]], dtype=np.uint8)
    y = ic_oracle(xs, 10, 200, beta)
    assert not y[0].any()            # x = p - 1
    assert np.array_equal(y[1], beta)  # x = p (inclusive)
    assert np.array_equal(y[2], beta)  # x = q - 1
    assert not y[3].any()            # x = q (exclusive)
    # empty / full / wraparound
    assert not ic_oracle(xs, 7, 7, beta).any()
    assert np.array_equal(ic_oracle(xs, 0, N, beta),
                          np.broadcast_to(beta, (4, LAM)))
    yw = ic_oracle(np.array([[0xFF, 0xFF], [0, 5], [0, 100]],
                            dtype=np.uint8), 60000, 6, beta)
    assert np.array_equal(yw[0], beta)   # in [60000, N)
    assert np.array_equal(yw[1], beta)   # in [0, 6)
    assert not yw[2].any()               # in the gap


def test_oracle_bounds_validated():
    beta = np.zeros(LAM, dtype=np.uint8)
    with pytest.raises(ValueError):
        ic_oracle(np.zeros((1, NB), dtype=np.uint8), 0, N + 1, beta)


# -------------------------------------------- IC edge-case property sweep


@pytest.mark.parametrize("bound", [Bound.LT_BETA, Bound.GT_BETA])
@pytest.mark.parametrize("p,q", [
    (10, 200),        # plain interior
    (0, 1),           # single point at the origin
    (123, 124),       # single interior point
    (57, 57),         # empty
    (0, N),           # full domain
    (0, 0),           # empty at the origin
    (N, N),           # empty at the top
    (60000, 300),     # wraparound
    (N - 1, N),       # last point only
    (0, 32768),       # exact half
])
def test_ic_edge_cases_both_parties(dcf, rng, p, q, bound):
    """x = p, q-1, q and the corners, every edge interval class, both
    parties, both DCF bound families — bit-exact vs the oracle."""
    beta = rng.integers(1, 256, LAM, dtype=np.uint8)
    pb = dcf.interval(p, q, beta, bound=bound, rng=rng)
    assert pb.num_intervals == 1 and pb.keys.num_keys == 2
    xs = mixed_points(rng, [(p, q)], extra=32)
    y0 = dcf.eval_interval(0, pb, xs)
    y1 = dcf.eval_interval(1, pb, xs)
    assert np.array_equal(y0 ^ y1, ic_oracle(xs, p, q, beta))


def test_interval_bound_alphas_decomposition():
    """The public-correction algebra: pub bit per interval class, and
    GT alphas shifted by one (the 1_{x >= b} decomposition)."""
    iv = [(10, 200), (200, 10), (0, N), (5, 5), (N, N), (0, 0)]
    _, pub = interval_bound_alphas(iv, NB, Bound.LT_BETA)
    assert pub.tolist() == [0, 1, 1, 0, 0, 0]
    al, pubg = interval_bound_alphas(iv, NB, Bound.GT_BETA)
    assert pubg.tolist() == [0, 1, 1, 0, 0, 0]
    assert al[0].tolist() == [0, 9] and al[1].tolist() == [0, 199]
    with pytest.raises(ValueError):
        interval_bound_alphas([(0, N + 1)], NB)


# --------------------------------------------------- MIC acceptance sweep


def reconstruct_facade(dcf_like, pb, xs):
    return dcf_like.eval_mic(0, pb, xs) ^ dcf_like.eval_mic(1, pb, xs)


def test_mic_8_intervals_numpy_oracle(dcf, rng):
    pb, betas = make_mic(dcf, rng)
    assert pb.keys.num_keys == 16  # 2m keys K-packed in ONE bundle
    xs = mixed_points(rng, MIC_INTERVALS)
    got = reconstruct_facade(dcf, pb, xs)
    assert np.array_equal(got, mic_oracle(xs, MIC_INTERVALS, betas))


def test_mic_gt_beta(dcf, rng):
    pb, betas = make_mic(dcf, rng, bound=Bound.GT_BETA)
    xs = mixed_points(rng, MIC_INTERVALS)
    assert np.array_equal(
        reconstruct_facade(dcf, pb, xs),
        mic_oracle(xs, MIC_INTERVALS, betas))


@pytest.mark.parametrize("backend", ["auto", "bitsliced", "prefix"])
def test_mic_facade_backends(ck, rng, backend):
    """The acceptance matrix, single-device half: MIC over 8 intervals
    on every CPU-reachable facade backend, both parties, vs the
    oracle."""
    d = Dcf(NB, LAM, ck, backend=backend)
    pb, betas = make_mic(d, rng)
    xs = mixed_points(rng, MIC_INTERVALS, extra=32)
    assert np.array_equal(
        reconstruct_facade(d, pb, xs),
        mic_oracle(xs, MIC_INTERVALS, betas))


def test_mic_sharded_2x2_mesh(ck, rng):
    """The acceptance matrix, mesh half: the 2m = 16 K-packed keys
    shard over a 2x2 virtual mesh (keys axis 2 | points axis 2)."""
    from dcf_tpu.parallel import make_mesh

    d = Dcf(NB, LAM, ck, backend="bitsliced", mesh=make_mesh(shape=(2, 2)))
    pb, betas = make_mic(d, rng)
    xs = mixed_points(rng, MIC_INTERVALS, extra=32)
    assert np.array_equal(
        reconstruct_facade(d, pb, xs),
        mic_oracle(xs, MIC_INTERVALS, betas))


def test_mic_evaluator_staged_matches_facade(ck, rng):
    """The staged MicEvaluator (put_bundle/stage/eval_staged once +
    ON-DEVICE pair combine) is bit-identical to the facade path on the
    staged backends; prefix exercises the bit-major layout branch of
    the key-axis table, bitsliced the byte-major one."""
    for backend in ("bitsliced", "prefix"):
        d = Dcf(NB, LAM, ck, backend=backend)
        pb, betas = make_mic(d, rng)
        xs = mixed_points(rng, MIC_INTERVALS, extra=32)
        ev0, ev1 = MicEvaluator(d, pb, 0), MicEvaluator(d, pb, 1)
        want = mic_oracle(xs, MIC_INTERVALS, betas)
        assert np.array_equal(ev0.reconstruct_with(ev1, xs), want)
        assert np.array_equal(ev0.eval(xs), d.eval_mic(0, pb, xs))


def test_adjacent_partition_covers_domain(dcf, rng):
    """Adjacent-interval MIC partition: every point lands in exactly
    one interval, so the rows XOR-reduce to the piecewise lookup."""
    cuts = [0, 100, 5000, 60000]
    intervals = partition_intervals(cuts, 8 * NB)
    assert intervals == [(0, 100), (100, 5000), (5000, 60000), (60000, 0)]
    betas = rng.integers(0, 256, (4, LAM), dtype=np.uint8)
    pb = dcf.mic(intervals, betas, rng=rng)
    xs = mixed_points(rng, intervals)
    rows = reconstruct_facade(dcf, pb, xs)
    # at most one row fires per point (a partition; == 1 unless beta=0)
    assert (np.count_nonzero((rows != 0).any(axis=2), axis=0) <= 1).all()
    assert np.array_equal(rows, mic_oracle(xs, intervals, betas))


# ------------------------------------------------------------- piecewise


def test_piecewise_lookup(dcf, rng):
    cuts = [0, 100, 5000, 60000]
    vals = rng.integers(0, 256, (4, LAM), dtype=np.uint8)
    pb = dcf.piecewise(cuts, vals, rng=rng)
    xs = mixed_points(rng, partition_intervals(cuts, 8 * NB))
    y = dcf.eval_piecewise(0, pb, xs) ^ dcf.eval_piecewise(1, pb, xs)
    assert np.array_equal(y, piecewise_oracle(xs, cuts, vals))
    # spot-check the semantics directly: x = 4999 -> piece 1's value
    xq = np.array([[0x13, 0x87]], dtype=np.uint8)  # 0x1387 = 4999
    yq = dcf.eval_piecewise(0, pb, xq) ^ dcf.eval_piecewise(1, pb, xq)
    assert np.array_equal(yq[0], vals[1])


def test_piecewise_single_piece_is_constant(dcf, rng):
    vals = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
    pb = dcf.piecewise([42], vals, rng=rng)
    xs = rng.integers(0, 256, (16, NB), dtype=np.uint8)
    y = dcf.eval_piecewise(0, pb, xs) ^ dcf.eval_piecewise(1, pb, xs)
    assert np.array_equal(y, np.broadcast_to(vals[0], (16, LAM)))


def test_partition_validation():
    with pytest.raises(ValueError):
        partition_intervals([], 16)
    with pytest.raises(ValueError):
        partition_intervals([5, 5], 16)
    with pytest.raises(ValueError):
        partition_intervals([0, N], 16)


# ------------------------------------------------------------ wire format


def test_wire_roundtrip_and_version_gate(dcf, rng):
    pb, betas = make_mic(dcf, rng)
    data = pb.to_bytes()
    pb2 = ProtocolBundle.from_bytes(data)
    assert pb2.bound is pb.bound
    assert np.array_equal(pb2.combine_masks, pb.combine_masks)
    for a, b in zip(
            (pb2.keys.s0s, pb2.keys.cw_s, pb2.keys.cw_v, pb2.keys.cw_t,
             pb2.keys.cw_np1),
            (pb.keys.s0s, pb.keys.cw_s, pb.keys.cw_v, pb.keys.cw_t,
             pb.keys.cw_np1)):
        assert np.array_equal(a, b)
    # the per-party restriction round-trips too
    r = ProtocolBundle.from_bytes(pb.for_party(1).to_bytes())
    assert r.keys.s0s.shape[1] == 1 and r.combine_masks.shape[0] == 1
    # a plain-bundle reader must refuse the protocol frame loudly
    with pytest.raises(KeyFormatError, match="protocol section"):
        KeyBundle.from_bytes(data)
    # ...and the protocol reader refuses plain v2 frames with a pointer
    with pytest.raises(KeyFormatError, match="KeyBundle.from_bytes"):
        ProtocolBundle.from_bytes(pb.keys.to_bytes())
    # v2 plain bundles still read (the version gate's other half)
    kb = KeyBundle.from_bytes(pb.keys.to_bytes())
    assert kb.num_keys == pb.keys.num_keys


def test_wire_corruption_detected(dcf, rng):
    pb, _ = make_mic(dcf, rng)
    data = pb.to_bytes()
    # flip one byte mid-frame: the CRC trailer must catch it
    with pytest.raises(KeyFormatError, match="crc32"):
        ProtocolBundle.from_bytes(faults.corrupt(data, len(data) // 2))
    # truncation names the field that ran out
    with pytest.raises(KeyFormatError, match="truncated"):
        ProtocolBundle.from_bytes(data[: len(data) // 2])
    with pytest.raises(KeyFormatError, match="magic"):
        ProtocolBundle.from_bytes(b"XXXX" + data[4:])


def test_protocol_bundle_repr_redacted(dcf, rng):
    pb, betas = make_mic(dcf, rng)
    r = repr(pb)
    assert "redacted" in r and "m=8" in r
    assert betas.tobytes().hex()[:16] not in r


def test_protocol_bundle_shape_contracts(dcf, rng):
    pb, _ = make_mic(dcf, rng)
    with pytest.raises(ShapeError):
        ProtocolBundle(keys=pb.keys,
                       combine_masks=np.zeros((2, 3, LAM), np.uint8))
    odd = KeyBundle(
        s0s=pb.keys.s0s[:3], cw_s=pb.keys.cw_s[:3],
        cw_v=pb.keys.cw_v[:3], cw_t=pb.keys.cw_t[:3],
        cw_np1=pb.keys.cw_np1[:3])
    with pytest.raises(ShapeError):
        ProtocolBundle(keys=odd,
                       combine_masks=np.zeros((2, 1, LAM), np.uint8))


# ------------------------------------------------------------- faults


def test_combine_fault_seam_fires(dcf, rng):
    pb, _ = make_mic(dcf, rng)
    xs = rng.integers(0, 256, (8, NB), dtype=np.uint8)
    with faults.inject("protocols.combine"):
        with pytest.raises(faults.InjectedFault):
            dcf.eval_mic(0, pb, xs)
    # disarmed again afterwards
    dcf.eval_mic(0, pb, xs)


def test_combine_fault_seam_args(dcf, rng):
    pb, _ = make_mic(dcf, rng)
    xs = rng.integers(0, 256, (8, NB), dtype=np.uint8)
    seen = []
    with faults.inject("protocols.combine",
                       handler=lambda m, pts: seen.append((m, pts))):
        dcf.eval_mic(1, pb, xs)
    assert seen == [(8, 8)]  # m intervals, batch points


# ---------------------------------------------------------------- serve


def make_service(d, pb, **knobs):
    knobs.setdefault("max_batch", 32)
    svc = d.serve(**knobs)
    svc.register_key("mic-0", pb)
    return svc


def test_serve_mic_bit_exact(ck, rng):
    """Protocol bundles registered in DcfService serve combined
    [m, M, lam] shares with plain-DCF semantics otherwise."""
    d = Dcf(NB, LAM, ck, backend="bitsliced")
    pb, betas = make_mic(d, rng)
    svc = make_service(d, pb)
    xs = mixed_points(rng, MIC_INTERVALS, extra=16)
    f0 = svc.submit("mic-0", xs, b=0)
    f1 = svc.submit("mic-0", xs, b=1)
    svc.pump()
    got = f0.result() ^ f1.result()
    assert got.shape == (8, xs.shape[0], LAM)
    assert np.array_equal(got, mic_oracle(xs, MIC_INTERVALS, betas))


def test_serve_mic_under_faults_with_retries(ck, rng):
    """The acceptance fault clause: protocols.combine AND serve.eval
    faults injected mid-serve; retries reconstruct bit-exactly."""
    d = Dcf(NB, LAM, ck, backend="bitsliced")
    pb, betas = make_mic(d, rng)
    svc = make_service(d, pb, retries=1)
    xs = mixed_points(rng, MIC_INTERVALS, extra=16)
    want = mic_oracle(xs, MIC_INTERVALS, betas)

    for point in ("protocols.combine", "serve.eval"):
        calls = {"n": 0}

        def fail_first(*_a):
            calls["n"] += 1
            if calls["n"] == 1:
                raise faults.InjectedFault(f"injected at {point}")

        with faults.inject(point, handler=fail_first):
            f0 = svc.submit("mic-0", xs, b=0)
            f1 = svc.submit("mic-0", xs, b=1)
            svc.pump()
            assert np.array_equal(f0.result() ^ f1.result(), want), point
        assert calls["n"] >= 2  # the retry actually re-entered the seam


def test_serve_mic_retries_exhausted_fail_future(ck, rng):
    d = Dcf(NB, LAM, ck, backend="bitsliced")
    pb, _ = make_mic(d, rng)
    svc = make_service(d, pb, retries=1)
    xs = rng.integers(0, 256, (8, NB), dtype=np.uint8)
    with faults.inject("protocols.combine"):
        f = svc.submit("mic-0", xs, b=0)
        svc.pump()
        with pytest.raises(faults.InjectedFault):
            f.result()


def test_serve_mixed_plain_and_protocol_keys(ck, rng):
    """One service, one plain DCF key and one MIC key: shapes and
    values both correct (the registry's protocol record is per-key)."""
    from dcf_tpu.backends.numpy_backend import eval_batch_np
    from dcf_tpu.ops.prg import HirosePrgNp

    d = Dcf(NB, LAM, ck, backend="bitsliced")
    pb, betas = make_mic(d, rng)
    alphas = rng.integers(0, 256, (1, NB), dtype=np.uint8)
    plain_betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
    plain = d.gen(alphas, plain_betas, rng=rng)
    svc = make_service(d, pb)
    svc.register_key("plain-0", plain)
    xs = rng.integers(0, 256, (9, NB), dtype=np.uint8)
    fm = svc.submit("mic-0", xs, b=0)
    fp0 = svc.submit("plain-0", xs, b=0)
    fp1 = svc.submit("plain-0", xs, b=1)
    fm1 = svc.submit("mic-0", xs, b=1)
    svc.pump()
    assert fm.result().shape == (8, 9, LAM)
    assert fp0.result().shape == (1, 9, LAM)
    prg = HirosePrgNp(LAM, ck)
    want_plain = (eval_batch_np(prg, 0, plain.for_party(0), xs)
                  ^ eval_batch_np(prg, 1, plain.for_party(1), xs))
    assert np.array_equal(fp0.result() ^ fp1.result(), want_plain)
    assert np.array_equal(fm.result() ^ fm1.result(),
                          mic_oracle(xs, MIC_INTERVALS, betas))


def test_serve_rejects_mismatched_protocol_bundle(ck, rng):
    d = Dcf(NB, LAM, ck, backend="bitsliced")
    d4 = Dcf(4, LAM, ck, backend="numpy")
    pb4, _ = make_mic_any(d4, rng)
    svc = d.serve(max_batch=32)
    with pytest.raises(ShapeError):
        svc.register_key("mic-bad", pb4)


def test_registry_generation_guard_on_hot_swap(ck, rng):
    """The snapshot consistency guard: a key hot-swapped after a group
    snapshot was taken must not lazily re-stage under that snapshot's
    combine masks — ``resident()`` with the stale generation refuses
    (the group fails typed instead of resolving silently wrong shares),
    while fresh submissions snapshot the new entry and serve it."""
    d = Dcf(NB, LAM, ck, backend="bitsliced")
    pb, _ = make_mic(d, rng)
    svc = make_service(d, pb)
    _, _, gen = svc.registry.snapshot("mic-0")
    pb2, betas2 = make_mic(d, rng)
    svc.register_key("mic-0", pb2)  # hot-swap: same geometry, new betas
    with pytest.raises(StaleStateError):
        svc.registry.resident("mic-0", 0, gen)
    xs = mixed_points(rng, MIC_INTERVALS, extra=8)
    f0 = svc.submit("mic-0", xs, b=0)
    f1 = svc.submit("mic-0", xs, b=1)
    svc.pump()
    assert np.array_equal(f0.result() ^ f1.result(),
                          mic_oracle(xs, MIC_INTERVALS, betas2))


def make_mic_any(d, rng):
    n = 1 << (8 * d.n_bytes)
    iv = [(1, n // 2), (n // 2, n - 1)]
    betas = rng.integers(0, 256, (2, LAM), dtype=np.uint8)
    return d.mic(iv, betas, rng=rng), betas


# ------------------------------------------------- keygen reuse contract


def test_gen_interval_bundle_custom_gen_fn(ck, rng):
    """The keygen hook: any K-batched gen (here gen.gen_batch directly,
    standing in for a DeviceKeyGen pipeline) produces an equivalent
    bundle — the protocol layer adds structure, not a new keygen."""
    from dcf_tpu.gen import gen_batch, random_s0s
    from dcf_tpu.ops.prg import HirosePrgNp

    prg = HirosePrgNp(LAM, ck)
    seeds = np.random.default_rng(3)

    def gen_fn(alphas, betas, bound):
        return gen_batch(prg, alphas, betas,
                         random_s0s(alphas.shape[0], LAM, seeds), bound)

    iv = [(100, 60000), (60001, 100)]
    betas = rng.integers(0, 256, (2, LAM), dtype=np.uint8)
    pb = gen_interval_bundle(gen_fn, iv, betas, NB)
    d = Dcf(NB, LAM, ck, backend="numpy")
    xs = mixed_points(rng, iv, extra=16)
    got = d.eval_mic(0, pb, xs) ^ d.eval_mic(1, pb, xs)
    assert np.array_equal(got, mic_oracle(xs, iv, betas))


def test_eval_interval_rejects_mic_bundle(dcf, rng):
    pb, _ = make_mic(dcf, rng)
    xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
    with pytest.raises(ShapeError):
        eval_interval(dcf, 0, pb, xs)
    assert eval_mic(dcf, 0, pb, xs).shape == (8, 4, LAM)
