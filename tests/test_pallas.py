"""Pallas kernel path: bit-major AES, full eval parity (interpret mode).

On CPU the kernel runs via the Pallas interpreter; on TPU the same code is
the fused VMEM walk kernel.  Parity target: the numpy oracle, which is
itself pinned to the reference's vectors (tests/test_spec.py).
"""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.utils.bits import (
    bitmajor_perm,
    byte_bits_lsb,
    pack_lanes,
    planes_to_bytes,
)


def rand_bytes(rng: random.Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


def test_bitmajor_perm_roundtrip():
    perm = bitmajor_perm(16)
    assert sorted(perm) == list(range(128))
    # plane 0 stays (byte 0, bit 0); bit-major plane 15 is byte 15, bit 0 —
    # the plane the PRG's 8*lam-1 masking clears.
    assert perm[0] == 0
    assert perm[15] == 15 * 8


def test_bitmajor_aes_matches_bytemajor():
    from dcf_tpu.ops.aes_bitsliced import (
        aes256_encrypt_planes,
        aes256_encrypt_planes_bitmajor,
        round_key_masks,
        round_key_masks_bitmajor,
    )

    rng = random.Random(61)
    key = rand_bytes(rng, 32)
    blocks = np.random.default_rng(5).integers(0, 256, (64, 16), dtype=np.uint8)
    planes = pack_lanes(np.ascontiguousarray(byte_bits_lsb(blocks).T))
    want = aes256_encrypt_planes(
        np, round_key_masks(key), planes, np.uint32(0xFFFFFFFF)
    )
    perm = bitmajor_perm(16)
    got_bm = aes256_encrypt_planes_bitmajor(
        np, round_key_masks_bitmajor(key), planes[perm].view(np.int32),
        np.int32(-1),
    )
    got = got_bm.view(np.uint32)[np.argsort(perm)]
    assert np.array_equal(got, want)
    assert np.array_equal(planes_to_bytes(got, 16),
                          planes_to_bytes(want, 16))


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_pallas_eval_matches_numpy(bound):
    from dcf_tpu.backends.pallas_backend import PallasBackend

    rng = random.Random(62)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(6)
    k_num, n_bytes, m = 2, 2, 45  # m forces lane padding
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(k_num, 16, nprng), bound)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs[:k_num] = alphas
    be = PallasBackend(16, ck, interpret=True)
    for b in (0, 1):
        want = eval_batch_np(prg, b, bundle.for_party(b), xs)
        got = be.eval(b, xs, bundle=bundle.for_party(b))
        assert np.array_equal(got, want), f"party {b}"


def test_pallas_eval_per_key_points_multi_tile():
    from dcf_tpu.backends.pallas_backend import PallasBackend

    rng = random.Random(63)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(7)
    k_num, n_bytes, m = 2, 2, 128  # tile_words=2 -> two grid steps per key
    bundle = gen_batch(
        prg,
        nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8),
        nprng.integers(0, 256, (k_num, 16), dtype=np.uint8),
        random_s0s(k_num, 16, nprng),
        spec.Bound.LT_BETA,
    )
    xs3 = nprng.integers(0, 256, (k_num, m, n_bytes), dtype=np.uint8)
    be = PallasBackend(16, ck, tile_words=2, interpret=True)
    for b in (0, 1):
        want = eval_batch_np(prg, b, bundle.for_party(b), xs3)
        got = be.eval(b, xs3, bundle=bundle.for_party(b))
        assert np.array_equal(got, want)


def test_pallas_two_party_reconstruction():
    from dcf_tpu.backends.pallas_backend import PallasBackend

    rng = random.Random(64)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(8)
    alpha = np.array([[0x41, 0x7F]], dtype=np.uint8)
    beta = nprng.integers(0, 256, (1, 16), dtype=np.uint8)
    bundle = gen_batch(prg, alpha, beta, random_s0s(1, 16, nprng),
                       spec.Bound.LT_BETA)
    xs = np.array(
        [[0x41, 0x7E], [0x41, 0x7F], [0x41, 0x80], [0x00, 0x00], [0xFF, 0xFF]],
        dtype=np.uint8,
    )
    be = PallasBackend(16, ck, interpret=True)
    y0 = be.eval(0, xs, bundle=bundle.for_party(0))
    y1 = be.eval(1, xs, bundle=bundle.for_party(1))
    recon = y0[0] ^ y1[0]
    want = np.stack(
        [beta[0], np.zeros(16, np.uint8), np.zeros(16, np.uint8),
         beta[0], np.zeros(16, np.uint8)]
    )
    assert np.array_equal(recon, want)


@pytest.mark.parametrize("gt", [False, True])
def test_points_mismatch_count_device(gt):
    """The full on-device random-points parity counter (the bench gate):
    zero for a correct two-party pair, nonzero under a corrupted share —
    both the bit-major (Pallas) and byte-major (bitsliced) variants."""
    from dcf_tpu.backends.jax_bitsliced import BitslicedBackend
    from dcf_tpu.backends.pallas_backend import PallasBackend

    rng = random.Random(65)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(9)
    bound = spec.Bound.GT_BETA if gt else spec.Bound.LT_BETA
    alphas = nprng.integers(0, 256, (1, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (1, 16), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(1, 16, nprng), bound)
    xs = nprng.integers(0, 256, (43, 2), dtype=np.uint8)
    xs[0] = alphas[0]

    for cls, kwargs in ((PallasBackend, dict(interpret=True)),
                        (BitslicedBackend, dict())):
        be0 = cls(16, ck, **kwargs)
        be1 = cls(16, ck, **kwargs)
        be0.put_bundle(bundle.for_party(0))
        be1.put_bundle(bundle.for_party(1))
        st = be0.stage(xs)
        y0 = be0.eval_staged(0, st)
        y1 = be1.eval_staged(1, st)
        a, b = alphas[0].tobytes(), betas[0].tobytes()
        assert int(be0.points_mismatch_count(y0, y1, a, b, st, gt=gt)) == 0, \
            cls.__name__
        # Negative control: corrupt one lane of party 1's share.
        import jax.numpy as jnp

        y1_bad = jnp.asarray(np.asarray(y1)).at[..., 0].set(
            np.asarray(y1)[..., 0] ^ 1)
        assert int(be0.points_mismatch_count(y0, y1_bad, a, b, st,
                                             gt=gt)) > 0, cls.__name__


def test_pallas_rejects_other_lambda():
    from dcf_tpu.backends.pallas_backend import PallasBackend

    with pytest.raises(ValueError, match="lam=16"):
        PallasBackend(144, [b"\0" * 32] * 18)
