"""lockwatch: the TSan-lite lock-order watchdog (ISSUE 17).

The static half of the concurrency suite (guarded-by,
blocking-under-lock) proves lexical discipline; this harness proves
the one property no lexical pass can — that no two locks are ever
taken in opposite orders by different threads.  The tests here are
the detector's own detection-power fixtures:

* a SEEDED inversion — thread 1 completes ``A then B`` and hands off
  deterministically before the main thread tries ``B then A`` — must
  raise ``LockOrderError`` BEFORE the closing acquire blocks (the
  test would deadlock, not fail, if the detector ever regressed into
  needing the lucky interleave);
* consistent orders, reentrant RLocks, per-instance identity,
  try-locks and bounded waits must all stay silent — the watchdog
  rides the chaos/serve soaks, so a false positive there is a broken
  CI leg.

Tests carrying the ``lockwatch`` marker are armed by the autouse
conftest fixture (patched ``threading.Lock``/``RLock`` factories);
the unmarked tests pin the disarm/restore contract.
"""

from __future__ import annotations

import threading

import pytest

from dcf_tpu.errors import DcfError, LockOrderError
from dcf_tpu.testing import lockwatch


@pytest.mark.lockwatch
def test_seeded_inversion_detected():
    """The canonical two-lock inversion, deterministically interleaved:
    thread 1 takes A then B and fully exits before the main thread
    takes B and tries A.  No timing window — the graph remembers the
    A->B edge, so the closing B->A acquire raises instead of
    deadlocking."""
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    t1_done = threading.Event()

    def t1():
        with lock_a:
            with lock_b:  # records the edge A -> B
                pass
        t1_done.set()

    worker = threading.Thread(target=t1, name="t1-a-then-b")
    worker.start()
    worker.join(10.0)
    assert t1_done.is_set(), "seed thread did not complete"

    with lock_b:
        with pytest.raises(LockOrderError) as ei:
            lock_a.acquire()  # would close the cycle: refused pre-block
    err = ei.value
    # Typed and taxonomy-rooted, with the evidence attached.
    assert isinstance(err, DcfError) and isinstance(err, RuntimeError)
    assert len(err.cycle) == 3  # A -> B -> A (names carry file:line#seq)
    assert err.cycle[0] == err.cycle[-1]
    assert all("#" in name for name in err.cycle)
    assert err.stacks and "closing acquire" in err.stacks[-1]
    assert "first observed" in err.stacks[0]
    # The refused acquire never took the lock: A is still free.
    assert lock_a.acquire(blocking=False)
    lock_a.release()


@pytest.mark.lockwatch
def test_consistent_order_stays_silent():
    """Two threads hammering the SAME order never trip the detector —
    the property that lets the watchdog ride the soaks."""
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    errors = []

    def worker():
        try:
            for _ in range(50):
                with lock_a:
                    with lock_b:
                        pass
        except LockOrderError as e:  # pragma: no cover - the failure
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    assert errors == []


@pytest.mark.lockwatch
def test_per_instance_identity_no_alias():
    """Identity is per lock INSTANCE, not per allocation site: two
    independent pairs born at the same lines may be taken in opposite
    orders without a (false) cycle."""

    def make_pair():
        return threading.Lock(), threading.Lock()

    a1, b1 = make_pair()
    a2, b2 = make_pair()
    with a1:
        with b1:
            pass
    with b2:  # the reverse order, but on distinct instances
        with a2:
            pass


@pytest.mark.lockwatch
def test_trylock_and_bounded_acquire_skip_the_check():
    """Non-blocking and timeout-bounded acquires cannot deadlock, so
    they are allowed to run against the recorded order — but they still
    maintain the held stack (a blocking acquire under them is checked
    with them counted as held)."""
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    with lock_a:
        with lock_b:
            pass
    with lock_b:
        assert lock_a.acquire(timeout=0.2)  # against the order: allowed
        lock_a.release()
        assert lock_a.acquire(blocking=False)
        lock_a.release()
        with pytest.raises(LockOrderError):
            lock_a.acquire()  # the blocking spelling is still refused


@pytest.mark.lockwatch
def test_rlock_reentrancy_and_condition_protocol():
    """Reentrant re-acquires are depth-counted, never self-edges; a
    ``Condition`` built on a watched RLock completes a real
    wait/notify round trip through the ``_release_save`` /
    ``_acquire_restore`` protocol."""
    rlock = threading.RLock()
    with rlock:
        with rlock:  # reentrant: no edge, no error
            pass

    cond = threading.Condition(threading.RLock())
    log = []

    def waiter():
        with cond:
            while not log:
                cond.wait(1.0)
            log.append("woke")

    worker = threading.Thread(target=waiter)
    worker.start()
    # The waiter's timed wait re-checks the predicate, so a notify
    # that lands before it parks is merely unobserved, never lost.
    with cond:
        log.append("go")
        cond.notify()
    worker.join(10.0)
    assert log == ["go", "woke"]


@pytest.mark.lockwatch
def test_queue_and_event_survive_armed_window():
    """stdlib synchronization built while armed (queue.Queue's
    mutex+Conditions, Event's Condition-on-Lock) works unmodified —
    the soaks construct whole serve stacks inside the armed window."""
    import queue

    q = queue.Queue()
    ev = threading.Event()

    def producer():
        q.put("payload")
        ev.set()

    worker = threading.Thread(target=producer)
    worker.start()
    assert q.get(timeout=5.0) == "payload"
    assert ev.wait(5.0)
    worker.join(5.0)


@pytest.mark.lockwatch
def test_double_arm_rejected():
    """One armed session at a time: the marker fixture already armed,
    so a second arm is a usage error (ValueError, not a lock-order
    finding)."""
    with pytest.raises(ValueError):
        lockwatch.arm()


def test_unarmed_locks_are_native():
    """Without the marker the factories are untouched — production
    code never pays the wrapper, and the fixture's disarm restored
    the world after the armed tests above."""
    assert not isinstance(threading.Lock(), lockwatch.WatchedLock)
    assert "lock" in type(threading.Lock()).__name__.lower()


def test_disarm_restores_and_watched_locks_keep_working():
    """Explicit arm/disarm round trip: locks created while armed keep
    functioning after disarm (they wrap real locks; only the graph
    stops growing)."""
    watch = lockwatch.arm()
    try:
        survivor = threading.Lock()
        assert isinstance(survivor, lockwatch.WatchedLock)
    finally:
        lockwatch.disarm(watch)
    assert not isinstance(threading.Lock(), lockwatch.WatchedLock)
    with survivor:
        assert survivor.locked()
    assert not survivor.locked()
