"""dcflint: the real package is clean, and every pass has detection power.

Two halves, both load-bearing:

* ``test_package_clean`` pins the repo-wide contract the CI lint job
  enforces (``python -m tools.dcflint dcf_tpu`` exits 0) — a regression
  here means a PR introduced an unmarked violation of one of the nine
  machine-checked invariants.
* the seeded-violation fixtures prove each pass actually FIRES on the
  exact defect class it exists for (a checker nobody has seen fire is a
  checker nobody can trust), and that the scoping/exemption and
  suppression grammar behave as documented.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys

import pytest

from tools.dcflint import run_path
from tools.dcflint.passes.typed_error import DCF_ERRORS

REPO = pathlib.Path(__file__).resolve().parent.parent


def names(violations):
    return sorted({v.pass_name for v in violations})


def write(root: pathlib.Path, rel: str, src: str) -> pathlib.Path:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src)
    return p


# ---------------------------------------------------------------- repo-wide


def test_package_clean():
    violations = run_path(REPO / "dcf_tpu")
    assert violations == [], "\n".join(str(v) for v in violations)


def test_taxonomy_list_in_sync():
    """The typed-error pass hardcodes the DcfError subclass names (it
    must work on un-importable fixture trees); this pins the list to the
    live module so adding an error class updates both or fails here."""
    from dcf_tpu import errors

    live = {errors.DcfError.__name__} | {
        c.__name__ for c in vars(errors).values()
        if isinstance(c, type) and issubclass(c, errors.DcfError)}
    assert live == set(DCF_ERRORS)


# ---------------------------------------------------- per-pass detection


def test_compat_shim_detects(tmp_path):
    write(tmp_path, "backend.py", (
        "from jax.experimental.shard_map import shard_map\n"
        "import jax\n"
        "def f(pltpu, kernel, mesh):\n"
        "    params = pltpu.CompilerParams()\n"
        "    old = pltpu.TPUCompilerParams()\n"
        "    jax.shard_map(kernel, mesh=mesh, in_specs=(), out_specs=(),\n"
        "                  check_rep=False)\n"
        "    return params, old\n"))
    got = run_path(tmp_path)
    assert names(got) == ["compat-shim"]
    assert len(got) == 5  # import, 2 attrs, jax.shard_map, check_rep=
    # the canonical old-jax spellings are caught too
    write(tmp_path, "oldjax.py", (
        "from jax.experimental import shard_map\n"
        "from jax.experimental.pallas.tpu import TPUCompilerParams\n"))
    old = [v for v in run_path(tmp_path, ["compat-shim"])
           if v.path.endswith("oldjax.py")]
    assert [v.line for v in old] == [1, 2]
    # the shim modules themselves are the allowed resolution site
    write(tmp_path, "_compat.py",
          "from jax.experimental.shard_map import shard_map  # noqa\n")
    assert not [v for v in run_path(tmp_path)
                if v.path.endswith("_compat.py")]


def test_exception_hygiene_detects(tmp_path):
    write(tmp_path, "mod.py", (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # fallback-ok: probe may be absent\n"
        "        pass\n"))
    got = run_path(tmp_path)
    assert names(got) == ["exception-hygiene"]
    assert [v.line for v in got] == [4]  # the marked handler is allowed


def test_crypto_dtype_detects_and_scopes(tmp_path):
    bad = ("import jax.numpy as jnp\n"
           "def f(m):\n"
           "    a = jnp.zeros((4, m))\n"
           "    b = jnp.arange(8)\n"
           "    c = a.astype(jnp.float32)\n"
           "    d = jnp.ones((2,), jnp.uint8)  # positional dtype: fine\n"
           "    return a, b, c, d\n")
    write(tmp_path, "ops/kernel.py", bad)
    write(tmp_path, "backends/be.py", bad)
    write(tmp_path, "util.py", bad)  # outside the crypto scope
    got = run_path(tmp_path, ["crypto-dtype"])
    assert names(got) == ["crypto-dtype"]
    flagged = {(pathlib.Path(v.path).parent.name, v.line) for v in got}
    assert flagged == {("ops", 3), ("ops", 4), ("ops", 5),
                       ("backends", 3), ("backends", 4), ("backends", 5)}


def test_typed_error_detects(tmp_path):
    write(tmp_path, "mod.py", (
        "from dcf_tpu.errors import ShapeError\n"
        "def f(x):\n"
        "    if x == 1:\n"
        "        raise RuntimeError('untyped')\n"
        "    if x == 2:\n"
        "        raise ValueError('unmarked')\n"
        "    if x == 3:\n"
        "        raise ValueError('marked')  # api-edge: argument contract\n"
        "    if x == 4:\n"
        "        raise ShapeError('typed')\n"
        "    if x == 5:\n"
        "        raise NotImplementedError\n"))
    got = run_path(tmp_path, ["typed-error"])
    assert [v.line for v in got] == [4, 6]
    # cli.py may SystemExit; testing/ is the fault-injection harness
    write(tmp_path, "cli.py", "def f():\n    raise SystemExit('usage')\n")
    write(tmp_path, "testing/faults.py",
          "def f():\n    raise InjectedFault('seeded')\n")
    assert [v.line for v in run_path(tmp_path, ["typed-error"])] == [4, 6]


def test_secret_hygiene_detects(tmp_path):
    write(tmp_path, "mod.py", (
        "def f(seed, cw_s, count):\n"
        "    print('building', count)\n"        # no secret names: fine
        "    print('seed is', seed)\n"          # positional leak
        "    log(f'cw: {cw_s}')\n"              # f-string leak
        "    logger.info('s0s=%r', bundle.s0s)\n"))  # attribute leak
    got = run_path(tmp_path, ["secret-hygiene"])
    assert [v.line for v in got] == [3, 4, 5]
    write(tmp_path, "klass.py", (
        "from dataclasses import dataclass\n"
        "@dataclass\n"
        "class Leaky:\n"
        "    s0s: bytes\n"
        "    cw_np1: bytes\n"
        "@dataclass\n"
        "class Redacted:\n"
        "    s0s: bytes\n"
        "    def __repr__(self):\n"
        "        return 'Redacted(...)'\n"))
    got = [v for v in run_path(tmp_path, ["secret-hygiene"])
           if v.path.endswith("klass.py")]
    assert len(got) == 1 and "Leaky" in got[0].message


def test_secret_hygiene_covers_metric_sinks(tmp_path):
    """PR 4 rule 2: metric recording calls and label builders are output
    sinks — key-material names in their arguments are flagged exactly
    like print/log arguments (serve metrics end up in dashboards and
    committed RESULTS JSONL lines)."""
    write(tmp_path, "serve_mod.py", (
        "def f(metrics, seen, bundle, cw_s, n):\n"
        "    metrics.counter('serve_requests_total').inc(n)\n"   # fine
        "    seen.add(n)\n"                                      # fine
        "    gauge.set(len(bundle.s0s))\n"                       # leak-adj
        "    hist.observe(cw_s)\n"                               # leak
        "    name = labeled('serve_evals', key=bundle)\n"        # label leak
        "    return name\n"))
    got = run_path(tmp_path, ["secret-hygiene"])
    assert [v.line for v in got] == [4, 5, 6]
    # the serve metrics module itself stays clean under the rule
    assert run_path(REPO / "dcf_tpu" / "serve", ["secret-hygiene"]) == []


def test_secret_hygiene_covers_protocol_masks(tmp_path):
    """PR 5: a protocol bundle's ``combine_masks`` is key material
    (``pub * beta`` — the secret function value in the clear for
    wraparound intervals): leaking it through any output sink from a
    protocols-style module is flagged, and a mask-holding class without
    a redacting __repr__ is flagged too."""
    write(tmp_path, "protocols/mic.py", (
        "def f(combine_masks, bundle, m):\n"
        "    log(f'combining {m} intervals')\n"       # no secrets: fine
        "    log(f'masks: {combine_masks}')\n"        # f-string leak
        "    print('corr', bundle.combine_masks)\n"   # attribute leak
        "    hist.observe(combine_masks)\n"))         # metric-sink leak
    got = [v for v in run_path(tmp_path, ["secret-hygiene"])
           if v.path.endswith("mic.py")]
    assert [v.line for v in got] == [3, 4, 5]
    write(tmp_path, "protocols/keygen.py", (
        "from dataclasses import dataclass\n"
        "@dataclass\n"
        "class LeakyProtocolBundle:\n"
        "    combine_masks: object\n"))
    got = [v for v in run_path(tmp_path, ["secret-hygiene"])
           if v.path.endswith("keygen.py")]
    assert len(got) == 1 and "LeakyProtocolBundle" in got[0].message


def test_protocols_layer_lint_clean():
    """The ISSUE-5 satellite pin: dcf_tpu/protocols/ sweeps clean under
    ALL six passes (the package-wide test_package_clean already covers
    it; this pin keeps the guarantee legible if the sweep scope ever
    changes)."""
    assert run_path(REPO / "dcf_tpu" / "protocols") == []


def test_serve_layer_lint_clean(tmp_path):
    """The ISSUE-4 CI satellite: the whole dcflint sweep over
    dcf_tpu/serve/ reports zero findings — in particular determinism
    (the batcher/admission clock comes through the injectable
    utils.benchtime.monotonic seam, never time.* directly)."""
    assert run_path(REPO / "dcf_tpu" / "serve") == []
    # Detection power for the seam rule: the exact violation the seam
    # exists to prevent — a serve-shaped module reading the wall clock
    # directly instead of taking the injectable clock — IS caught.
    write(tmp_path, "serve/batchy.py", (
        "import time\n"
        "def too_old(req, max_delay):\n"
        "    return time.monotonic() - req.enq_t > max_delay\n"))
    got = run_path(tmp_path, ["determinism"])
    assert [v.line for v in got] == [3]
    assert "benchtime" in got[0].message


def test_frontier_cache_layer_lint_clean():
    """The ISSUE-7 CI satellite: the frontier-cache module pair —
    ``serve/frontier_cache.py`` (the LRU + TickSource) and
    ``backends/frontier.py`` (the consumer mixin) — sweeps clean under
    ALL six passes.  Determinism is the load-bearing one here: LRU
    stamps come from the shared TickSource, never a clock, so eviction
    order is a pure function of the request sequence (the orders
    tests/test_frontier_cache.py pins exactly)."""
    assert run_path(REPO / "dcf_tpu" / "serve" / "frontier_cache.py") == []
    assert run_path(REPO / "dcf_tpu" / "backends" / "frontier.py") == []


def test_fixedpoint_layer_lint_clean(tmp_path):
    """The ISSUE-20 CI satellite: the fixed-point gate pair —
    ``protocols/fixedpoint.py`` (gate keygen/eval/oracles) and
    ``workloads/gates.py`` (the served form) — sweeps clean under ALL
    passes.  Crypto-dtype is the load-bearing one: its scope now
    includes both files, so a float dtype creeping into an arithmetic
    share path (the classic probabilistic-truncation shortcut) is
    caught, exactly as it would be under ops/ or backends/."""
    assert run_path(REPO / "dcf_tpu" / "protocols"
                    / "fixedpoint.py") == []
    assert run_path(REPO / "dcf_tpu" / "workloads" / "gates.py") == []
    # Detection power for the scope extension: a fixedpoint-shaped
    # module quantizing through a float dtype IS caught...
    write(tmp_path, "protocols/fixedpoint.py", (
        "import numpy as np\n"
        "def quantize(x, f):\n"
        "    return (x * np.float32(2.0 ** f)).astype(np.int32)\n"))
    got = [v for v in run_path(tmp_path, ["crypto-dtype"])
           if v.path.endswith("fixedpoint.py")]
    assert [v.line for v in got] == [3]
    # ...and the same code OUTSIDE the scoped pair is not (the pass
    # stays a key/CW/value-path rule, not a repo-wide float ban).
    write(tmp_path, "protocols/other.py", (
        "import numpy as np\n"
        "def quantize(x, f):\n"
        "    return (x * np.float32(2.0 ** f)).astype(np.int32)\n"))
    assert [v for v in run_path(tmp_path, ["crypto-dtype"])
            if v.path.endswith("other.py")] == []
    # secret-hygiene learned the gate names: the truncation gate's
    # additive scalar shares and the signed per-key payloads.
    write(tmp_path, "protocols/gatey.py", (
        "def f(const_share, key_betas):\n"
        "    log(f'shares: {const_share}')\n"
        "    print('payloads', key_betas)\n"))
    got = [v for v in run_path(tmp_path, ["secret-hygiene"])
           if v.path.endswith("gatey.py")]
    assert [v.line for v in got] == [2, 3]


def test_secret_hygiene_covers_store_layer(tmp_path):
    """ISSUE 8 rule 4: the durable store layer.  ``frame`` joined the
    key-material name set (a serialized DCFK frame IS the key), and a
    ``serve/store.py`` creating files with builtin ``open`` in a write
    mode — umask-default permissions for bytes that must be 0o600 — is
    flagged; read-mode opens and the same write elsewhere are not."""
    write(tmp_path, "serve/store.py", (
        "def publish(path, frame, key_frame):\n"
        "    log(f'writing {frame}')\n"                   # name leak
        "    with open(path, 'wb') as fh:\n"              # write mode
        "        fh.write(frame)\n"
        "    with open(path, 'rb') as fh:\n"              # read: fine
        "        return fh.read()\n"
        "def publish_kw(path, data):\n"
        "    fh = open(path, mode='x+b')\n"               # kw write mode
        "    fh.write(data)\n"))
    got = [v for v in run_path(tmp_path, ["secret-hygiene"])
           if v.path.endswith("store.py")]
    assert [v.line for v in got] == [2, 3, 8]
    assert "0o600" in got[1].message
    # the same write-mode open OUTSIDE the store layer is not the
    # store rule's business (other passes own general file hygiene)
    write(tmp_path, "util.py", (
        "def save(path, data):\n"
        "    with open(path, 'wb') as fh:\n"
        "        fh.write(data)\n"))
    assert [v for v in run_path(tmp_path, ["secret-hygiene"])
            if v.path.endswith("util.py")] == []


def test_keygen_layer_lint_clean():
    """The ISSUE-10 CI satellite: the device-keygen layer —
    ``ops/pallas_keygen.py`` (the K-packed keygen kernel + wide tail),
    the refactored shared walk core in ``ops/pallas_narrow.py`` that
    gen and eval now both consume, and the ``gen.py`` router — sweeps
    clean under ALL six passes.  Crypto-dtype and secret-hygiene are
    the load-bearing ones: correction words and seeds are key material,
    and a float or a logged plane on the keygen path is a broken or
    leaked key."""
    assert run_path(REPO / "dcf_tpu" / "ops" / "pallas_keygen.py") == []
    assert run_path(REPO / "dcf_tpu" / "ops" / "pallas_narrow.py") == []
    assert run_path(REPO / "dcf_tpu" / "gen.py") == []


def test_keyfactory_layer_lint_clean():
    """The ISSUE-11 CI satellite: the key-factory layer —
    ``serve/keyfactory.py`` (pools, claims, batched refill) and the
    churn mode in ``serve/loadgen.py`` — sweeps clean under ALL six
    passes.  Secret-hygiene and determinism are the load-bearing ones:
    pool entries hold bundles (key material — redacting reprs, no
    sink leaks), and the ONE sanctioned entropy source (fresh mint
    seeds) carries its mandatory suppression reason while everything
    else runs on seeded rngs and the injectable clock."""
    assert run_path(REPO / "dcf_tpu" / "serve" / "keyfactory.py") == []
    assert run_path(REPO / "dcf_tpu" / "serve" / "loadgen.py") == []


def test_store_layer_lint_clean():
    """The ISSUE-8 CI satellite: the durable store module sweeps clean
    under ALL six passes — in particular secret-hygiene (no
    key-material names in log/print/metric sinks; store files created
    through the os.open 0o600 helper, pinned by rule 4's own scope)
    and determinism (no clocks, no RNG: on-disk bytes are a pure
    function of the store's logical state)."""
    assert run_path(REPO / "dcf_tpu" / "serve" / "store.py") == []


def test_edge_layer_lint_clean():
    """The ISSUE-12 CI satellite: the network edge —
    ``serve/edge.py`` (wire codecs, EdgeServer/EdgeClient, the tenant
    token buckets) — sweeps clean under ALL six passes.
    Secret-hygiene and determinism are the load-bearing ones: wire
    buffers hold evaluated SHARE bytes on their way to a party (the
    name set knows ``share*`` for exactly this layer), and every piece
    of admission math (buckets, deadlines) runs on the injectable
    clock, never ``time.*``."""
    assert run_path(REPO / "dcf_tpu" / "serve" / "edge.py") == []


def test_secret_hygiene_covers_share_buffers(tmp_path):
    """ISSUE 12: ``share*`` joined the key-material name set — a
    logged share next to the other party's reconstructs the function
    value, so edge-shaped code printing or metric-labelling a share
    buffer is flagged like a seed leak."""
    write(tmp_path, "serve/edgey.py", (
        "def respond(req_id, share_bytes, shares, m, shared):\n"
        "    log(f'sending {share_bytes}')\n"          # name leak
        "    counter.inc(len(shares))\n"               # metric sink
        "    counter.inc(m)\n"                         # scalar: fine
        "    log(f'state {shared}')\n"))  # 'shared' state: NOT a secret
    got = [v for v in run_path(tmp_path, ["secret-hygiene"])
           if v.path.endswith("edgey.py")]
    assert [v.line for v in got] == [2, 3]
    assert "share_bytes" in got[0].message


def test_determinism_detects_and_exempts(tmp_path):
    bad = ("import time, random\n"
           "import numpy as np\n"
           "def f():\n"
           "    t = time.time()\n"
           "    r = random.random()\n"
           "    g = np.random.default_rng()\n"
           "    ok = np.random.default_rng(42)\n"
           "    legacy = np.random.randint(4)\n"
           "    return t, r, g, ok, legacy\n")
    write(tmp_path, "mod.py", bad)
    write(tmp_path, "cli.py", bad)                 # bench layer: exempt
    write(tmp_path, "utils/benchtime.py", bad)     # bench layer: exempt
    write(tmp_path, "testing/harness.py", bad)     # scaffolding: exempt
    got = run_path(tmp_path, ["determinism"])
    assert {pathlib.Path(v.path).name for v in got} == {"mod.py"}
    assert [v.line for v in got] == [4, 5, 6, 8]
    # single-FILE mode keeps directory scoping: scanning the exempt file
    # directly must still see its testing/ segment and stay clean
    assert run_path(tmp_path / "testing" / "harness.py",
                    ["determinism"]) == []
    assert len(run_path(tmp_path / "mod.py", ["determinism"])) == 4


# ------------------------------------------------------------ suppression


def test_suppression_needs_reason(tmp_path):
    write(tmp_path, "mod.py", (
        "import time\n"
        "def f():\n"
        "    a = time.time()  # dcflint: disable=determinism\n"
        "    b = time.time()  # dcflint: disable=determinism boot stamp\n"
        "    return a, b\n"))
    got = run_path(tmp_path)
    # the reasoned suppression hides line 4; the reasonless one does NOT
    # hide line 3 and is itself flagged
    assert sorted((v.pass_name, v.line) for v in got) == [
        ("determinism", 3), ("suppression", 3)]


def test_suppression_block_above_and_unknown_pass(tmp_path):
    write(tmp_path, "mod.py", (
        "import time\n"
        "def f():\n"
        "    # dcflint: disable=determinism cold-start stamp, logged\n"
        "    # only, never reaches control flow\n"
        "    t = time.time()\n"
        "    u = time.time()  # dcflint: disable=no-such-pass why\n"
        "    return t, u\n"))
    got = run_path(tmp_path)
    assert sorted((v.pass_name, v.line) for v in got) == [
        ("determinism", 6), ("suppression", 6)]


# -------------------------------------------------------------- CLI contract


def run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.dcflint", *args],
        capture_output=True, text=True, cwd=REPO)


@pytest.mark.slow
def test_cli_contract(tmp_path):
    write(tmp_path, "clean.py", "X = 1\n")
    proc = run_cli(str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "dcflint OK" in proc.stdout
    write(tmp_path, "dirty.py", "import time\nT = time.time()\n")
    proc = run_cli(str(tmp_path), "--json")
    assert proc.returncode == 1
    rep = json.loads(proc.stdout)
    assert rep["count"] == 1
    assert rep["violations"][0]["pass_name"] == "determinism"
    assert len(rep["passes"]) == 9
    assert run_cli(str(tmp_path), "--pass", "bogus").returncode == 2
    assert run_cli(str(tmp_path / "absent")).returncode == 2
    # ISSUE 17 satellite: SARIF + output file + changed-only + the
    # --json/--format conflict are all part of the CLI contract.
    sarif_path = tmp_path / "report.sarif"
    proc = run_cli(str(tmp_path), "--format", "sarif",
                   "--output", str(sarif_path))
    assert proc.returncode == 1
    sarif = json.loads(sarif_path.read_text())
    assert sarif["version"] == "2.1.0"
    assert sarif["runs"][0]["results"][0]["ruleId"] == "determinism"
    assert run_cli(str(tmp_path), "--json", "--format",
                   "sarif").returncode == 2
    assert run_cli(str(tmp_path), "--changed-only",
                   "no-such-ref").returncode == 2
    # Changed-only vs HEAD: the fixture files are outside the repo, so
    # the narrowed sweep scans nothing and exits clean even though the
    # full sweep of the same path exits 1 — the exact miss CI's
    # unconditional full sweep exists to cover.
    assert run_cli(str(tmp_path), "--changed-only", "HEAD").returncode == 0


def test_exception_hygiene_shim_removed():
    """PR 4 deleted the deprecated ``tools/check_exception_hygiene.py``
    shim (superseded by the dcflint exception-hygiene pass in PR 2);
    callers use ``python -m tools.dcflint <dir> --pass
    exception-hygiene``.  This pins the removal so the shim does not
    quietly resurrect."""
    assert not (REPO / "tools" / "check_exception_hygiene.py").exists()


def test_pod_layer_lint_clean():
    """The ISSUE-13 CI satellite: the pod tier — ``serve/router.py``
    (the DCFE forwarding/failover core) and ``serve/shardmap.py`` (the
    rendezvous ring) — sweeps clean under ALL six passes.  Determinism
    is the load-bearing one: suspicion cooldowns run on the injectable
    clock and placement on a keyed blake2b digest, never a process-
    salted hash or ``time.*``; secret-hygiene matters because the
    router relays SHARE bytes and replication moves whole DCFK
    frames."""
    assert run_path(REPO / "dcf_tpu" / "serve" / "router.py") == []
    assert run_path(REPO / "dcf_tpu" / "serve" / "shardmap.py") == []


def test_secret_hygiene_covers_replication_frames(tmp_path):
    """ISSUE 13: ``repl_frame``/``replica_frame`` joined the
    key-material name set — a replication buffer is the same DCFK
    frame on its way to another host's store, so pod-tier code
    printing or metric-labelling one is flagged like logging the key
    itself."""
    write(tmp_path, "serve/podding.py", (
        "def replicate(key_id, repl_frame, replica_frames, n,"
        " replicated):\n"
        "    log(f'shipping {repl_frame}')\n"       # name leak
        "    counter.inc(len(replica_frames))\n"    # metric sink
        "    counter.inc(n)\n"                      # scalar: fine
        "    log(f'state {replicated}')\n"))  # ordinary state name
    got = [v for v in run_path(tmp_path, ["secret-hygiene"])
           if v.path.endswith("podding.py")]
    assert [v.line for v in got] == [2, 3]
    assert "repl_frame" in got[0].message


# ------------------------------------------- ISSUE 17: concurrency suite


def test_guarded_by_detects(tmp_path):
    """The guarded-by contract fires on exactly the access shapes the
    serve-tier review rounds kept catching by hand: unguarded writes,
    unguarded reads outside __init__, and the closure trap (a nested
    def/lambda body does NOT inherit the enclosing ``with`` — it runs
    after the critical section is gone)."""
    write(tmp_path, "mod.py", (
        "import threading\n"
        "class Pool:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        # guarded-by: _lock\n"
        "        self._items = []\n"
        "        self._items.append('warm')\n"   # __init__: pre-publication
        "    def good(self):\n"
        "        with self._lock:\n"
        "            self._items.append(2)\n"
        "    def bad_write(self):\n"
        "        self._items = []\n"                       # line 12
        "    def bad_read(self):\n"
        "        return len(self._items)\n"                # line 14
        "    # holds-lock: _lock\n"
        "    def evict_locked(self):\n"
        "        return self._items.pop()\n"               # marked: fine
        "    def closure_trap(self):\n"
        "        with self._lock:\n"
        "            return lambda: self._items.count(0)\n"  # line 20
        "    def suppressed(self):\n"
        "        # dcflint: disable=guarded-by snapshot read, len is atomic\n"
        "        return len(self._items)\n"))
    got = run_path(tmp_path, ["guarded-by"])
    assert names(got) == ["guarded-by"]
    assert [v.line for v in got] == [12, 14, 20]
    assert "written" in got[0].message
    assert "read" in got[1].message and "read" in got[2].message


def test_guarded_by_annotation_hygiene(tmp_path):
    """A contract that silently fails to bind is worse than none: a
    guard naming a lock __init__ never assigns, a malformed name, and
    an orphaned marker are all findings in their own right."""
    write(tmp_path, "mod.py", (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"                        # line 3
        "        # guarded-by: _ghost\n"
        "        self._x = 0\n"
        "        # guarded-by: _lock (plus prose that breaks the name)\n"
        "        self._y = 0\n"
        "# guarded-by: _lock\n"                            # line 8: orphan
        "Z = 1\n"))
    got = run_path(tmp_path, ["guarded-by"])
    msgs = {v.line: v.message for v in got}
    assert sorted(msgs) == [3, 6, 8]
    assert "never assigns self._ghost" in msgs[3]
    assert "malformed" in msgs[6]
    assert "orphaned" in msgs[8]


def test_blocking_under_lock_detects(tmp_path):
    """Every blocking family fires inside a ``with <lock>`` body; the
    deliberate non-findings (timed waits, str.join, nested defs,
    non-lock with-subjects, code outside the with) stay silent."""
    write(tmp_path, "mod.py", (
        "import subprocess, time\n"
        "def f(self, sock, ev, t, parts, path):\n"
        "    with self._lock:\n"
        "        sock.sendall(b'x')\n"                     # line 4
        "        subprocess.run(['ls'])\n"                 # line 5
        "        time.sleep(0.1)\n"                        # line 6
        "        ev.wait()\n"                              # line 7
        "        t.join()\n"                               # line 8
        "        ev.wait(1.0)\n"                           # timed: fine
        "        t.join(timeout=1.0)\n"                    # timed: fine
        "        s = ', '.join(parts)\n"                   # str.join: fine
        "        fn = lambda: time.sleep(1)\n"             # later: fine
        "    with open(path) as fh:\n"                     # not a lock
        "        time.sleep(0.1)\n"
        "        fh.read()\n"
        "    time.sleep(0.1)\n"                            # outside: fine
        "    return s, fn\n"))
    got = run_path(tmp_path, ["blocking-under-lock"])
    assert names(got) == ["blocking-under-lock"]
    assert [v.line for v in got] == [4, 5, 6, 7, 8]
    assert all("with _lock" in v.message for v in got)
    # testing/ holds locks around arbitrary seams by design: exempt.
    write(tmp_path, "testing/h.py", (
        "import time\n"
        "def g(self):\n"
        "    with self._lock:\n"
        "        time.sleep(0.1)\n"))
    assert [v for v in run_path(tmp_path, ["blocking-under-lock"])
            if v.path.endswith("h.py")] == []
    # the mandatory-reason suppression grammar applies as everywhere
    write(tmp_path, "mod2.py", (
        "def h(self, sock, wire):\n"
        "    with self._send_lock:\n"
        "        # dcflint: disable=blocking-under-lock the send lock\n"
        "        # exists precisely to serialize whole-frame writes\n"
        "        sock.sendall(wire)\n"))
    assert [v for v in run_path(tmp_path, ["blocking-under-lock"])
            if v.path.endswith("mod2.py")] == []


def test_wire_taxonomy_sync_detects_errors_drift(tmp_path):
    """An errors.py whose DcfError closure disagrees with DCF_ERRORS
    is flagged in both directions (new class missing from the list;
    listed class missing from the module)."""
    write(tmp_path, "errors.py", (
        "class DcfError(Exception):\n"
        "    pass\n"
        "class RogueError(DcfError):\n"                    # line 3
        "    pass\n"))
    got = run_path(tmp_path, ["wire-taxonomy-sync"])
    by_msg = "\n".join(v.message for v in got)
    assert any(v.line == 3 and "RogueError is missing from DCF_ERRORS"
               in v.message for v in got)
    assert "DCF_ERRORS names ShapeError" in by_msg  # dead-entry side
    # basename scoping: the same content elsewhere is not the taxonomy
    write(tmp_path, "other.py", (tmp_path / "errors.py").read_text())
    assert [v for v in run_path(tmp_path, ["wire-taxonomy-sync"])
            if v.path.endswith("other.py")] == []


def test_wire_taxonomy_sync_detects_edge_drift(tmp_path):
    """The edge.py side: orphan codes, duplicate wire bytes, unnamed
    keys, missing WIRE_INTERNAL_ONLY, uncovered taxonomy classes, and
    an encode/decode table that does not round-trip are each their own
    finding."""
    write(tmp_path, "edge.py", (
        "E_SHAPE = 2\n"
        "E_ORPHAN = 3\n"                       # no WIRE_CODES entry
        "E_DUP = 2\n"                          # same byte as E_SHAPE
        "WIRE_CODES = {\n"
        "    E_SHAPE: ShapeError,\n"
        "    99: BackendUnavailableError,\n"   # unnamed key
        "}\n"
        "_EXC_CODES = (\n"
        "    (BackendUnavailableError, E_SHAPE),\n"  # broken round trip
        ")\n"))
    msgs = "\n".join(v.message for v in
                     run_path(tmp_path, ["wire-taxonomy-sync"]))
    assert "E_ORPHAN has no WIRE_CODES entry" in msgs
    assert "duplicate E_* code value(s) [2]" in msgs
    assert "key is not a module-level E_*" in msgs
    assert "defines no WIRE_INTERNAL_ONLY" in msgs
    assert "BackendUnavailableError has no wire code" in msgs  # coverage
    assert "decodes to ShapeError but _EXC_CODES never encodes" in msgs
    assert "encodes BackendUnavailableError but no WIRE_CODES entry" \
        in msgs
    assert "round trip changes the exception type" in msgs


def test_wire_taxonomy_sync_internal_only_rules(tmp_path):
    """WIRE_INTERNAL_ONLY is a checked declaration, not a dumping
    ground: a coded class may not also be declared internal-only, and
    only taxonomy classes belong in the set."""
    write(tmp_path, "edge.py", (
        "E_SHAPE = 2\n"
        "WIRE_CODES = {E_SHAPE: ShapeError}\n"
        "WIRE_INTERNAL_ONLY = frozenset({ShapeError, NotAnError})\n"
        "_EXC_CODES = ((ShapeError, E_SHAPE),)\n"))
    msgs = "\n".join(v.message for v in
                     run_path(tmp_path, ["wire-taxonomy-sync"]))
    assert "ShapeError is declared WIRE_INTERNAL_ONLY but has a wire" \
        in msgs
    assert "names NotAnError, which is not in the DCF_ERRORS taxonomy" \
        in msgs


# ---------------------------------------- ISSUE 17: repo-wide clean pins


def test_guardedby_repo_clean():
    """The tentpole pin — and the regression test for the three real
    races the annotation sweep surfaced and fixed:

    * ``EdgeServer`` accept loop: the open-connection gauge read
      ``self._conns`` outside ``_lock`` (now: snapshot under the lock,
      publish outside);
    * ``EdgeClient._read_loop``: ``self._pending.pop`` raced
      ``_fail_pending``'s swap-and-fail (now: popped under ``_lock``);
    * ``CapacityController._maybe_scale_out``: standby emptiness check
      and pop were two separate lock acquisitions (now: one atomic
      check-and-claim).

    Reverting any of them reintroduces an unguarded access to an
    annotated attribute, and this pin fails."""
    assert run_path(REPO / "dcf_tpu", ["guarded-by"]) == []
    # The pin has teeth only while the annotations exist: the serving
    # tier's contract surface must stay annotated.
    for mod in ["edge.py", "capacity.py", "registry.py", "breaker.py",
                "admission.py", "health.py", "membership.py"]:
        src = (REPO / "dcf_tpu" / "serve" / mod).read_text()
        assert "# guarded-by:" in src, f"{mod} lost its annotations"


def test_blocking_under_lock_repo_clean():
    assert run_path(REPO / "dcf_tpu", ["blocking-under-lock"]) == []


def test_wire_taxonomy_sync():
    """The triangle — errors.py classes, edge.py wire tables,
    DCF_ERRORS — holds on the real tree, and the declaration that
    makes coverage checkable (WIRE_INTERNAL_ONLY) is present."""
    assert run_path(REPO / "dcf_tpu", ["wire-taxonomy-sync"]) == []
    from dcf_tpu.serve import edge
    assert edge.WIRE_INTERNAL_ONLY  # the declaration itself exists


# ------------------------------------- ISSUE 17: SARIF and changed-only


def test_sarif_render(tmp_path):
    """SARIF 2.1.0 shape: one rule per pass (plus the synthetic
    parse/suppression rules), results referencing rules by id and
    index, 1-based regions, srcroot-relative URIs."""
    from tools.dcflint import all_passes, render_sarif

    write(tmp_path, "dirty.py", "import time\nT = time.time()\n")
    violations = run_path(tmp_path)
    sarif = json.loads(render_sarif(violations, str(tmp_path)))
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    rules = run["tool"]["driver"]["rules"]
    assert {r["id"] for r in rules} == \
        set(all_passes()) | {"parse", "suppression"}
    (res,) = run["results"]
    assert res["ruleId"] == "determinism"
    assert rules[res["ruleIndex"]]["id"] == "determinism"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 2
    assert loc["artifactLocation"]["uri"].endswith("dirty.py")


def test_changed_only_narrowing_vs_full_sweep(tmp_path):
    """The ISSUE 17 pin: ``only`` narrows the walk, so a violation in
    a file OUTSIDE the changed set is invisible to the narrowed run —
    and therefore the full sweep next to it in CI is load-bearing,
    not belt-and-braces."""
    changed = write(tmp_path, "changed.py",
                    "import time\nT = time.time()\n")
    write(tmp_path, "untouched.py", "import time\nU = time.time()\n")
    narrowed = run_path(tmp_path, ["determinism"], only=[changed])
    assert {pathlib.Path(v.path).name for v in narrowed} == {"changed.py"}
    full = run_path(tmp_path, ["determinism"])
    assert {pathlib.Path(v.path).name for v in full} == \
        {"changed.py", "untouched.py"}


def test_mesh_layer_lint_clean(tmp_path):
    """The ISSUE 18 compat satellite: every file of the mesh
    co-evaluation layer sweeps clean under ALL passes — in particular
    compat-shim, now that it also flags ``jax.distributed`` /
    ``jax.experimental.multihost_utils`` imports outside
    ``parallel/_compat.py`` (the one allowed resolution site)."""
    for rel in (("dcf_tpu", "parallel", "_compat.py"),
                ("dcf_tpu", "parallel", "mesh.py"),
                ("dcf_tpu", "parallel", "mesh_eval.py"),
                ("dcf_tpu", "serve", "meshgroup.py"),
                ("dcf_tpu", "serve", "router.py")):
        assert run_path(REPO.joinpath(*rel)) == [], "/".join(rel)
    # Detection power for the extension: a multi-process touchpoint
    # outside the shim is flagged, with the shim hint in the message.
    write(tmp_path, "rogue.py", (
        "import jax.distributed\n"
        "from jax.experimental import multihost_utils\n"
        "from jax.distributed import initialize\n"))
    got = [v for v in run_path(tmp_path, ["compat-shim"])
           if v.path.endswith("rogue.py")]
    assert [v.line for v in got] == [1, 2, 3]
    assert all("parallel._compat" in v.message for v in got)
    # ...and the shim module itself stays the allowed site.
    write(tmp_path, "_compat.py",
          "import jax.distributed\n"
          "from jax.experimental import multihost_utils  # noqa\n")
    assert not [v for v in run_path(tmp_path)
                if v.path.endswith("_compat.py")]


# ------------------------------------------------ ISSUE 19: DPF + PIR


def test_dpf_layer_lint_clean():
    """The ISSUE-19 CI satellite: the whole DPF/PIR column —
    ``protocols/dpf.py`` (keygen + wire), ``ops/pallas_evalall.py``
    (the level-order kernel), ``backends/evalall.py`` (host walk +
    device driver) and ``workloads/pir.py`` (the served retrieval) —
    sweeps clean under ALL nine passes.  Crypto-dtype and
    secret-hygiene are the load-bearing ones: DPF seeds/correction
    words are key material and the leaf t-planes are selection-vector
    shares, so a float on the walk or a logged plane is a broken key
    or a leaked query."""
    for rel in (("dcf_tpu", "protocols", "dpf.py"),
                ("dcf_tpu", "ops", "pallas_evalall.py"),
                ("dcf_tpu", "backends", "evalall.py"),
                ("dcf_tpu", "workloads", "pir.py")):
        assert run_path(REPO.joinpath(*rel)) == [], "/".join(rel)


def test_secret_hygiene_covers_selection_shares(tmp_path):
    """ISSUE 19: ``t_word(s)``/``sel_vec``/``selection_vec`` joined
    the key-material name set — one party's leaf t-bit lane words are
    its share of the PIR selection vector, and two logged shares
    reconstruct WHICH record the client asked for."""
    write(tmp_path, "workloads/piry.py", (
        "def serve(key_id, t_words, sel_vec, n, selected):\n"
        "    log(f'leaves {t_words}')\n"           # name leak
        "    counter.inc(len(sel_vec))\n"          # metric sink
        "    counter.inc(n)\n"                     # scalar: fine
        "    log(f'state {selected}')\n"))  # ordinary state name
    got = [v for v in run_path(tmp_path, ["secret-hygiene"])
           if v.path.endswith("piry.py")]
    assert [v.line for v in got] == [2, 3]
    assert "t_words" in got[0].message
