"""Keys-in-lanes Pallas kernel: parity vs the numpy oracle + device-gen
pipeline (interpret mode on CPU; the same code is the Mosaic kernel on TPU).
"""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.device_gen import DeviceKeyGen
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.backends.pallas_keylanes import KeyLanesPallasBackend
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp


def rand_bytes(rng, n):
    return bytes(rng.getrandbits(8) for _ in range(n))


def _setup(seed, k, nb, m):
    rng = random.Random(seed)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(seed)
    alphas = nprng.integers(0, 256, (k, nb), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, 16), dtype=np.uint8)
    s0s = random_s0s(k, 16, nprng)
    bundle = gen_batch(prg, alphas, betas, s0s, spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, nb), dtype=np.uint8)
    xs[0] = alphas[0]  # exact-alpha point
    return ck, prg, alphas, betas, s0s, bundle, xs


@pytest.mark.parametrize("b", [0, 1])
def test_keylanes_pallas_matches_numpy(b):
    ck, prg, alphas, betas, s0s, bundle, xs = _setup(81, k=5, nb=2, m=6)
    be = KeyLanesPallasBackend(
        16, ck, m_tile=2, kw_tile=1, level_chunk=8, interpret=True)
    got = be.eval(b, xs, bundle=bundle)
    xs_k = np.broadcast_to(xs[None], (5, *xs.shape))
    want = eval_batch_np(prg, b, bundle.for_party(b), xs_k)
    assert np.array_equal(got, want)


def test_keylanes_pallas_device_gen_pipeline():
    """DeviceKeyGen -> put_bundle_device -> kernel eval -> device verify:
    the full config-5 pipeline, plus a negative control."""
    ck, prg, alphas, betas, s0s, bundle, xs = _setup(82, k=7, nb=2, m=4)
    gen = DeviceKeyGen(16, ck)
    dev = gen.gen(alphas, betas, s0s, spec.Bound.LT_BETA)
    be = KeyLanesPallasBackend(
        16, ck, m_tile=2, kw_tile=1, level_chunk=16, interpret=True)
    be.put_bundle_device(dev)
    staged = be.stage(xs)
    y0 = be.eval_staged(0, staged)
    y1 = be.eval_staged(1, staged)
    assert int(be.relu_mismatch_count(y0, y1, alphas, betas, xs)) == 0
    # negative control: flip one beta byte -> that key mismatches wherever
    # x < alpha (at least the exact-alpha-minus... count must be > 0 only
    # if some xs fall below alpha; xs[0] == alphas[0] gives f=0 there, so
    # perturb alpha instead: claim alpha+1 for key 0 flips point xs[0]).
    alphas_wrong = alphas.copy()
    a0 = int.from_bytes(alphas[0].tobytes(), "big")
    alphas_wrong[0] = np.frombuffer(
        (a0 + 1).to_bytes(2, "big"), dtype=np.uint8)
    assert int(be.relu_mismatch_count(y0, y1, alphas_wrong, betas, xs)) == 1


def test_secure_relu_check_device_chunks():
    """The streaming config-5 driver: ragged key chunks, zero-pad keys, one
    device-summed mismatch counter."""
    from dcf_tpu.workloads import secure_relu_check_device

    ck, prg, alphas, betas, s0s, bundle, xs = _setup(84, k=40, nb=2, m=4)
    assert secure_relu_check_device(
        16, ck, alphas, betas, s0s, xs,
        key_chunk=32, kw_tile=1, interpret=True) == 0
    # (The driver regenerates keys from its inputs, so gen and verify are
    # self-consistent by construction; the detection power of the device
    # comparison itself is proven by the shifted-alpha negative control in
    # test_keylanes_pallas_device_gen_pipeline.)


def test_keylanes_pallas_matches_xla_keylanes():
    """Same bundle through the XLA keylanes path and the Pallas kernel."""
    from dcf_tpu.backends.jax_bitsliced import KeyLanesBackend

    ck, prg, alphas, betas, s0s, bundle, xs = _setup(83, k=33, nb=2, m=4)
    pb = KeyLanesPallasBackend(
        16, ck, m_tile=4, kw_tile=2, level_chunk=16, interpret=True)
    xb = KeyLanesBackend(16, ck)
    for b in (0, 1):
        got = pb.eval(b, xs, bundle=bundle)
        want = xb.eval(b, xs, bundle=bundle.for_party(b))
        assert np.array_equal(got, want)
