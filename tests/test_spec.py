"""Spec-model tests: ports of the reference's four unit tests plus AES checks.

Reference tests ported (SURVEY.md §4):
- test_dcf_gen_then_eval_ok            (src/lib.rs:372-395)
- test_dcf_gen_gt_beta_then_eval_ok    (src/lib.rs:397-420)
- test_dcf_gen_then_eval_not_zeros     (src/lib.rs:422-442)
- test_prg_gen_not_zeros               (src/prg.rs:86-96)
"""

import random

import pytest

from dcf_tpu import spec
from tests.vectors import ALPHAS, BETA, KEYS, PRG_SEED


def rand_bytes(rng: random.Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


# ---------------------------------------------------------------------------
# AES-256 primitives
# ---------------------------------------------------------------------------


def test_aes_sbox_known_entries():
    # FIPS-197 figure 7 spot checks.
    assert spec.AES_SBOX[0x00] == 0x63
    assert spec.AES_SBOX[0x01] == 0x7C
    assert spec.AES_SBOX[0x53] == 0xED
    assert spec.AES_SBOX[0xFF] == 0x16


def test_aes256_fips197_vector():
    # FIPS-197 appendix C.3: AES-256 of 00112233..ff under key 000102..1f.
    key = bytes(range(32))
    block = bytes.fromhex("00112233445566778899aabbccddeeff")
    rk = spec.aes256_expand_key(key)
    out = spec.aes256_encrypt_block(rk, block)
    assert out == bytes.fromhex("8ea2b7ca516745bfeafc49904b496089")


def test_aes256_matches_cryptography_lib():
    cryptography = pytest.importorskip("cryptography")  # noqa: F841
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    rng = random.Random(7)
    for _ in range(8):
        key = rand_bytes(rng, 32)
        block = rand_bytes(rng, 16)
        enc = Cipher(algorithms.AES(key), modes.ECB()).encryptor()
        expect = enc.update(block) + enc.finalize()
        got = spec.aes256_encrypt_block(spec.aes256_expand_key(key), block)
        assert got == expect


# ---------------------------------------------------------------------------
# PRG
# ---------------------------------------------------------------------------


def test_prg_gen_not_zeros():
    prg = spec.HirosePrgSpec(16, KEYS)
    out = prg.gen(PRG_SEED)
    zero = bytes(16)
    for s, v, _t in out:
        assert s != zero
        assert v != zero
        assert spec.xor_bytes(s, PRG_SEED) != zero
        assert spec.xor_bytes(v, PRG_SEED) != zero


def test_prg_right_child_is_seed_copy():
    # The zip-truncation quirk (SURVEY.md §2.1): for lam=16 the right child's
    # s is the (masked) seed and its v is the (masked) seed ^ 0xff...
    prg = spec.HirosePrgSpec(16, KEYS)
    (s_l, v_l, t_l), (s_r, v_r, t_r) = prg.gen(PRG_SEED)
    seed_p = bytes(b ^ 0xFF for b in PRG_SEED)
    mask = PRG_SEED[:15] + bytes([PRG_SEED[15] & 0xFE])
    mask_p = seed_p[:15] + bytes([seed_p[15] & 0xFE])
    assert s_r == mask
    assert v_r == mask_p


def test_prg_t_bit_sourcing():
    # Both t-bits come from byte 0 of the *half-0* buffers (src/prg.rs:63-64):
    # t_l from buf0[0] (= s_l) and t_r from buf1[0] (= v_l) — NOT from the
    # right child's buffers.  Byte 0 is untouched by the last-byte masking,
    # so the returned s_l/v_l expose the exact source bits.
    prg = spec.HirosePrgSpec(16, KEYS)
    rng = random.Random(9)
    for _ in range(32):
        seed = rand_bytes(rng, 16)
        (s_l, v_l, t_l), (_s_r, _v_r, t_r) = prg.gen(seed)
        assert t_l == bool(s_l[0] & 1)
        assert t_r == bool(v_l[0] & 1)


def test_prg_key_count_contract():
    # lam=32 under the reference's own key-count contract (2*(lam/16) = 4
    # keys) would index ciphers[17] and panic; the framework refuses it.
    rng = random.Random(10)
    with pytest.raises(ValueError):
        spec.HirosePrgSpec(32, [rand_bytes(rng, 32) for _ in range(4)])


def test_prg_last_bit_cleared():
    prg = spec.HirosePrgSpec(16, KEYS)
    rng = random.Random(1)
    for _ in range(4):
        seed = rand_bytes(rng, 16)
        for s, v, _t in prg.gen(seed):
            assert s[15] & 1 == 0
            assert v[15] & 1 == 0


def test_prg_large_lambda_shape():
    # lam=32 exercises both loop iterations (ciphers 0 and 17).
    rng = random.Random(2)
    keys = [rand_bytes(rng, 32) for _ in range(4 * 16 + 2)]
    prg = spec.HirosePrgSpec(32, keys)
    seed = rand_bytes(rng, 32)
    (s_l, v_l, _), (s_r, v_r, _) = prg.gen(seed)
    seed_p = bytes(b ^ 0xFF for b in seed)
    # Half 0 block 0 encrypted, block 1 of half 0 is seed copy (feed-forward of
    # zeros); half 1 block 1 encrypted, block 0 is seed copy.
    assert s_l[:16] != seed[:16]
    assert s_l[16:] == seed[16:31] + bytes([seed[31] & 0xFE])
    assert s_r[:16] == seed[:16]
    assert v_l[16:] == seed_p[16:31] + bytes([seed_p[31] & 0xFE])
    assert v_r[:16] == seed_p[:16]


# ---------------------------------------------------------------------------
# DCF end-to-end (ported reference tests)
# ---------------------------------------------------------------------------


def _keypair(bound: spec.Bound, seed: int = 42):
    rng = random.Random(seed)
    prg = spec.HirosePrgSpec(16, KEYS)
    s0s = [rand_bytes(rng, 16), rand_bytes(rng, 16)]
    f = spec.CmpFn(alpha=ALPHAS[2], beta=BETA)
    k = spec.gen(prg, f, s0s, bound)
    return prg, k.for_party(0), k.for_party(1)


def test_dcf_gen_then_eval_ok():
    prg, k0, k1 = _keypair(spec.Bound.LT_BETA)
    ys0 = spec.eval_batch(prg, False, k0, ALPHAS)
    ys1 = spec.eval_batch(prg, True, k1, ALPHAS)
    recon = [spec.xor_bytes(a, b) for a, b in zip(ys0, ys1)]
    assert recon == [BETA, BETA, bytes(16), bytes(16), bytes(16)]


def test_dcf_gen_gt_beta_then_eval_ok():
    prg, k0, k1 = _keypair(spec.Bound.GT_BETA)
    ys0 = spec.eval_batch(prg, False, k0, ALPHAS)
    ys1 = spec.eval_batch(prg, True, k1, ALPHAS)
    recon = [spec.xor_bytes(a, b) for a, b in zip(ys0, ys1)]
    assert recon == [bytes(16), bytes(16), bytes(16), BETA, BETA]


def test_dcf_gen_then_eval_not_zeros():
    prg, k0, k1 = _keypair(spec.Bound.LT_BETA)
    y0 = spec.eval_point(prg, False, k0, ALPHAS[2])
    y1 = spec.eval_point(prg, True, k1, ALPHAS[2])
    assert y0 != bytes(16)
    assert y1 != bytes(16)


def test_dcf_full_domain_small_n():
    # Full-domain eval at n_bytes=1 (256 points): output must be exactly
    # [beta]*alpha + [0]*(256-alpha) for LT, and the complement (minus x=alpha)
    # for GT.
    rng = random.Random(3)
    keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = spec.HirosePrgSpec(16, keys)
    alpha = bytes([0x5A])
    beta = rand_bytes(rng, 16)
    s0s = [rand_bytes(rng, 16), rand_bytes(rng, 16)]
    k = spec.gen(prg, spec.CmpFn(alpha, beta), s0s, spec.Bound.LT_BETA)
    xs = [bytes([i]) for i in range(256)]
    ys0 = spec.eval_batch(prg, False, k.for_party(0), xs)
    ys1 = spec.eval_batch(prg, True, k.for_party(1), xs)
    for i, (y0, y1) in enumerate(zip(ys0, ys1)):
        expect = beta if i < 0x5A else bytes(16)
        assert spec.xor_bytes(y0, y1) == expect, f"x={i}"


def test_dcf_random_property():
    # Property test: XOR of party evals equals f(x) for random alpha/beta/x.
    rng = random.Random(4)
    for trial in range(3):
        keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
        prg = spec.HirosePrgSpec(16, keys)
        n_bytes = 2
        alpha = rand_bytes(rng, n_bytes)
        beta = rand_bytes(rng, 16)
        s0s = [rand_bytes(rng, 16), rand_bytes(rng, 16)]
        for bound in (spec.Bound.LT_BETA, spec.Bound.GT_BETA):
            k = spec.gen(prg, spec.CmpFn(alpha, beta), s0s, bound)
            xs = [rand_bytes(rng, n_bytes) for _ in range(16)] + [alpha]
            ys0 = spec.eval_batch(prg, False, k.for_party(0), xs)
            ys1 = spec.eval_batch(prg, True, k.for_party(1), xs)
            for x, y0, y1 in zip(xs, ys0, ys1):
                if bound is spec.Bound.LT_BETA:
                    expect = beta if x < alpha else bytes(16)
                else:
                    expect = beta if x > alpha else bytes(16)
                assert spec.xor_bytes(y0, y1) == expect
