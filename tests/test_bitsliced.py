"""Bitsliced path: S-box circuit, plane packing, bitsliced AES, full eval."""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.utils.bits import (
    byte_bits_lsb,
    byte_bits_msb,
    pack_lanes,
    planes_to_bytes,
    unpack_lanes,
)
from tests.vectors import KEYS


def rand_bytes(rng: random.Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


def test_sbox_circuit_exhaustive_and_gate_count():
    # Import runs the exhaustive 256-input verification; re-run explicitly
    # and document the nonlinear gate budget.
    from dcf_tpu.ops import sbox_circuit as sc

    sc._verify()
    assert sc.SBOX_NONLINEAR_GATES <= 80  # tower-field budget; table-free


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    bits = rng.integers(0, 2, (5, 3, 96), dtype=np.uint8)
    assert np.array_equal(unpack_lanes(pack_lanes(bits)), bits)
    with pytest.raises(ValueError):
        pack_lanes(bits[..., :50])


def test_byte_bits_orders():
    a = np.array([[0b10000001, 0b00000010]], dtype=np.uint8)
    lsb = byte_bits_lsb(a)
    assert list(lsb[0, :8]) == [1, 0, 0, 0, 0, 0, 0, 1]  # byte 0, LSB-first
    msb = byte_bits_msb(a)
    assert list(msb[0, :8]) == [1, 0, 0, 0, 0, 0, 0, 1]  # MSB-first walk order
    assert list(msb[0, 8:]) == [0, 0, 0, 0, 0, 0, 1, 0]


def test_xs_mask_dev_matches_host_msb():
    """The device-side walk-order mask equals host byte_bits_msb + pack."""
    from dcf_tpu.backends.jax_bitsliced import _xs_to_mask_dev

    rng = np.random.default_rng(7)
    xs = rng.integers(0, 256, (3, 64, 2), dtype=np.uint8)  # [Kx, M, n_bytes]
    got = np.asarray(_xs_to_mask_dev(xs))  # [n, Kx, M/32]
    bits = byte_bits_msb(xs.reshape(-1, 2)).reshape(3, 64, 16)  # [Kx, M, n]
    want = pack_lanes(np.ascontiguousarray(bits.transpose(2, 0, 1)))
    assert np.array_equal(got, want)


def test_bitsliced_aes_matches_table():
    from dcf_tpu.ops.aes import aes256_encrypt_np, expand_key_np
    from dcf_tpu.ops.aes_bitsliced import aes256_encrypt_planes, round_key_masks

    rng = random.Random(51)
    key = rand_bytes(rng, 32)
    blocks = np.random.default_rng(1).integers(0, 256, (96, 16), dtype=np.uint8)
    planes = pack_lanes(np.ascontiguousarray(byte_bits_lsb(blocks).T))
    out = aes256_encrypt_planes(
        np, round_key_masks(key), planes, np.uint32(0xFFFFFFFF)
    )
    got = planes_to_bytes(out, 16)
    want = aes256_encrypt_np(expand_key_np(key), blocks)
    assert np.array_equal(got, want)


@pytest.mark.parametrize("variant", ["v2", "v3"])
@pytest.mark.parametrize("use_jnp", [False, True])
def test_permutation_aes_variants_match_v1(variant, use_jnp):
    """The Mosaic-fast cipher variants (v2 block-permutation, v3
    conjugated-ShiftRows) are bit-identical to the reshape/concat
    formulation the interpreter tests run (v1).  Covers both _perm_rows
    branches: numpy fancy indexing and the jnp slice-concat decomposition
    the compiled kernel actually uses."""
    from dcf_tpu.ops import aes_bitsliced as ab

    enc = {"v2": ab.aes256_encrypt_planes_bitmajor_v2,
           "v3": ab.aes256_encrypt_planes_bitmajor_v3}[variant]
    if use_jnp:
        import jax.numpy as jnp
    rng = np.random.default_rng(7)
    for trial in range(3):
        rk = ab.round_key_masks_bitmajor(rng.bytes(32))
        state = rng.integers(
            -(2**31), 2**31, (128, 5 + trial), dtype=np.int64
        ).astype(np.int32)
        v1 = ab.aes256_encrypt_planes_bitmajor(np, rk, state, np.int32(-1))
        if use_jnp:
            got = np.asarray(enc(
                jnp, jnp.asarray(rk), jnp.asarray(state), jnp.int32(-1)))
        else:
            got = enc(np, rk, state, np.int32(-1))
        assert np.array_equal(v1, got)


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_bitsliced_eval_matches_numpy(bound):
    from dcf_tpu.backends.jax_bitsliced import BitslicedBackend

    rng = random.Random(52)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(2)
    k_num, n_bytes, m = 3, 2, 45  # m forces lane padding
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(k_num, 16, nprng), bound)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs[:k_num] = alphas
    be = BitslicedBackend(16, ck)
    for b in (0, 1):
        want = eval_batch_np(prg, b, bundle.for_party(b), xs)
        got = be.eval(b, xs, bundle=bundle.for_party(b))
        assert np.array_equal(got, want), f"party {b}"


def test_bitsliced_eval_per_key_points_and_reference_keys():
    from dcf_tpu.backends.jax_bitsliced import BitslicedBackend

    prg = HirosePrgNp(16, KEYS)
    nprng = np.random.default_rng(3)
    k_num, n_bytes, m = 2, 2, 33
    bundle = gen_batch(
        prg,
        nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8),
        nprng.integers(0, 256, (k_num, 16), dtype=np.uint8),
        random_s0s(k_num, 16, nprng),
        spec.Bound.LT_BETA,
    )
    xs3 = nprng.integers(0, 256, (k_num, m, n_bytes), dtype=np.uint8)
    be = BitslicedBackend(16, KEYS)
    for b in (0, 1):
        want = eval_batch_np(prg, b, bundle.for_party(b), xs3)
        got = be.eval(b, xs3, bundle=bundle.for_party(b))
        assert np.array_equal(got, want)


@pytest.mark.slow
def test_bitsliced_lambda_2048():
    """lam=2048 (256 AES keys, 16384 planes): the multi-block plane assembly
    well beyond the lam=144 regime — 1022 of 1024 half-blocks are the
    never-encrypted Miyaguchi copies (reference src/prg.rs:48-62 zip quirk
    at scale).  Slow-marked: ~1 min on one CPU core."""
    from dcf_tpu.backends.jax_bitsliced import BitslicedBackend

    rng = random.Random(54)
    lam = 2048
    ck = [rand_bytes(rng, 32) for _ in range(2 * (lam // 16))]
    prg = HirosePrgNp(lam, ck)
    nprng = np.random.default_rng(9)
    bundle = gen_batch(
        prg,
        nprng.integers(0, 256, (1, 1), dtype=np.uint8),
        nprng.integers(0, 256, (1, lam), dtype=np.uint8),
        random_s0s(1, lam, nprng),
        spec.Bound.LT_BETA,
    )
    xs = nprng.integers(0, 256, (4, 1), dtype=np.uint8)
    be = BitslicedBackend(lam, ck)
    y = {}
    for b in (0, 1):
        want = eval_batch_np(prg, b, bundle.for_party(b), xs)
        y[b] = be.eval(b, xs, bundle=bundle.for_party(b))
        assert np.array_equal(y[b], want), f"party {b}"


def test_bitsliced_large_lambda():
    # lam=144: two encrypted block positions, plane assembly across blocks.
    from dcf_tpu.backends.jax_bitsliced import BitslicedBackend

    rng = random.Random(53)
    lam = 144
    ck = [rand_bytes(rng, 32) for _ in range(18)]
    prg = HirosePrgNp(lam, ck)
    nprng = np.random.default_rng(4)
    bundle = gen_batch(
        prg,
        nprng.integers(0, 256, (1, 1), dtype=np.uint8),
        nprng.integers(0, 256, (1, lam), dtype=np.uint8),
        random_s0s(1, lam, nprng),
        spec.Bound.LT_BETA,
    )
    xs = nprng.integers(0, 256, (32, 1), dtype=np.uint8)
    be = BitslicedBackend(lam, ck)
    for b in (0, 1):
        want = eval_batch_np(prg, b, bundle.for_party(b), xs)
        got = be.eval(b, xs, bundle=bundle.for_party(b))
        assert np.array_equal(got, want)
