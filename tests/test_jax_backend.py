"""Parity: JAX backend vs numpy backend/spec (runs on CPU JAX, 8 virt devices)."""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp
from tests.vectors import ALPHAS, BETA, KEYS


def rand_bytes(rng: random.Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


def test_aes_jax_matches_np():
    from dcf_tpu.ops.aes import aes256_encrypt_np, expand_key_np
    from dcf_tpu.ops.aes_jax import aes256_encrypt_jax
    import jax.numpy as jnp

    rng = random.Random(21)
    key = rand_bytes(rng, 32)
    rk = expand_key_np(key)
    blocks = np.random.default_rng(0).integers(0, 256, (5, 7, 16), dtype=np.uint8)
    out_np = aes256_encrypt_np(rk, blocks)
    out_j = np.asarray(aes256_encrypt_jax(jnp.asarray(rk), jnp.asarray(blocks)))
    assert np.array_equal(out_np, out_j)


@pytest.mark.parametrize("lam,nkeys", [(16, 2), (32, 18)])
def test_prg_jax_matches_np(lam, nkeys):
    import jax.numpy as jnp
    from dcf_tpu.backends.jax_backend import prg_gen_jax
    from dcf_tpu.ops.aes import expand_key_np
    from dcf_tpu.spec import hirose_used_cipher_indices

    rng = random.Random(22)
    keys = [rand_bytes(rng, 32) for _ in range(nkeys)]
    prg_np = HirosePrgNp(lam, keys)
    used = hirose_used_cipher_indices(lam, len(keys))
    rks = tuple(jnp.asarray(expand_key_np(keys[i])) for i in used)
    seeds = np.random.default_rng(1).integers(0, 256, (11, lam), dtype=np.uint8)
    got = prg_gen_jax(rks, lam, jnp.asarray(seeds))
    want = prg_np.gen(seeds)
    for g, w in zip(got, (want.s_l, want.v_l, want.t_l, want.s_r, want.v_r, want.t_r)):
        assert np.array_equal(np.asarray(g), w)


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_jax_eval_matches_numpy(bound):
    from dcf_tpu.backends.jax_backend import JaxBackend

    rng = random.Random(23)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(2)
    k_num, n_bytes, m = 3, 2, 33
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(prg_np, alphas, betas, random_s0s(k_num, 16, nprng), bound)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs[:k_num] = alphas

    backend = JaxBackend(16, cipher_keys)
    for b in (0, 1):
        want = eval_batch_np(prg_np, b, bundle.for_party(b), xs)
        got = backend.eval(b, xs, bundle=bundle.for_party(b))
        assert np.array_equal(got, want), f"party {b} mismatch"


def test_jax_eval_reference_vectors_and_reconstruction():
    from dcf_tpu.backends.jax_backend import JaxBackend

    nprng = np.random.default_rng(3)
    alphas = np.frombuffer(ALPHAS[2], dtype=np.uint8)[None, :]
    betas = np.frombuffer(BETA, dtype=np.uint8)[None, :]
    prg_np = HirosePrgNp(16, KEYS)
    bundle = gen_batch(prg_np, alphas, betas, random_s0s(1, 16, nprng), spec.Bound.LT_BETA)
    xs = np.stack([np.frombuffer(a, dtype=np.uint8) for a in ALPHAS])
    backend = JaxBackend(16, KEYS)
    y0 = backend.eval(0, xs, bundle=bundle.for_party(0))
    y1 = backend.eval(1, xs, bundle=bundle.for_party(1))
    recon = y0 ^ y1
    expect = [BETA, BETA, bytes(16), bytes(16), bytes(16)]
    assert [recon[0, j].tobytes() for j in range(5)] == expect


def test_jax_eval_large_lambda_extension():
    # lam=144 is the smallest reference-executable multi-block shape.
    from dcf_tpu.backends.jax_backend import JaxBackend

    rng = random.Random(24)
    lam = 144
    cipher_keys = [rand_bytes(rng, 32) for _ in range(2 * (lam // 16))]
    prg_np = HirosePrgNp(lam, cipher_keys)
    nprng = np.random.default_rng(4)
    alphas = nprng.integers(0, 256, (1, 1), dtype=np.uint8)
    betas = nprng.integers(0, 256, (1, lam), dtype=np.uint8)
    bundle = gen_batch(prg_np, alphas, betas, random_s0s(1, lam, nprng), spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (9, 1), dtype=np.uint8)
    backend = JaxBackend(lam, cipher_keys)
    for b in (0, 1):
        want = eval_batch_np(prg_np, b, bundle.for_party(b), xs)
        got = backend.eval(b, xs, bundle=bundle.for_party(b))
        assert np.array_equal(got, want)
