"""Device keygen parity: the on-device keys-in-lanes generator must produce
bit-identical keys to the host numpy gen_batch (which is itself pinned to
the reference vectors via tests/test_spec.py / test_numpy_backend.py)."""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.device_gen import DeviceKeyGen
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp


def rand_bytes(rng, n):
    return bytes(rng.getrandbits(8) for _ in range(n))


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_device_gen_matches_numpy(bound):
    rng = random.Random(71)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(71)
    k, nb = 37, 2  # non-multiple of 32: exercises key padding
    alphas = nprng.integers(0, 256, (k, nb), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, 16), dtype=np.uint8)
    s0s = random_s0s(k, 16, nprng)
    want = gen_batch(prg, alphas, betas, s0s, bound)

    gen = DeviceKeyGen(16, ck)
    dev = gen.gen(alphas, betas, s0s, bound)
    got = gen.to_host_bundle(dev)
    assert np.array_equal(got.s0s, want.s0s)
    assert np.array_equal(got.cw_s, want.cw_s)
    assert np.array_equal(got.cw_v, want.cw_v)
    assert np.array_equal(got.cw_t, want.cw_t)
    assert np.array_equal(got.cw_np1, want.cw_np1)


def test_device_gen_feeds_keylanes_eval():
    """The device bundle plugs straight into the keylanes evaluator and the
    two-party XOR reconstruction is correct."""
    from dcf_tpu.backends.jax_bitsliced import KeyLanesBackend

    rng = random.Random(72)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    nprng = np.random.default_rng(72)
    k, nb, m = 33, 2, 12
    alphas = nprng.integers(0, 256, (k, nb), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, 16), dtype=np.uint8)
    s0s = random_s0s(k, 16, nprng)
    gen = DeviceKeyGen(16, ck)
    dev = gen.gen(alphas, betas, s0s, spec.Bound.LT_BETA)
    bundle = gen.to_host_bundle(dev)
    xs = nprng.integers(0, 256, (m, nb), dtype=np.uint8)
    xs[0] = alphas[0]
    be0 = KeyLanesBackend(16, ck)
    be1 = KeyLanesBackend(16, ck)
    y0 = be0.eval(0, xs, bundle=bundle.for_party(0))
    y1 = be1.eval(1, xs, bundle=bundle.for_party(1))
    recon = y0 ^ y1
    for i in range(k):
        a = alphas[i].tobytes()
        for j in range(m):
            want = betas[i].tobytes() if xs[j].tobytes() < a else bytes(16)
            assert recon[i, j].tobytes() == want
