"""Slow crash/restore soak for the durable key store (ISSUE 8).

Serial-CI-leg material (``-m "durability and slow"``): repeated
kill/restore cycles under 3-thread closed-loop load with an every-9th
``serve.eval`` fault armed the whole time.  Each cycle the service is
closed WITHOUT draining mid-load (the in-process kill), a fresh service
restores from the same store directory, and the soak asserts that every
cycle restored the full key set with generations preserved, nothing was
ever quarantined (atomic publish: a kill cannot tear a visible frame),
and EVERY delivered result across all cycles was bit-exact vs the numpy
oracle (the clients verify inline — a wrong share anywhere fails the
soak, not just at the end).
"""

import threading

import numpy as np
import pytest

from dcf_tpu import Dcf
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.errors import DcfError
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.serve import DcfService, ServeConfig
from dcf_tpu.testing import faults

pytestmark = [pytest.mark.durability, pytest.mark.slow]

NB, LAM = 2, 16


def test_crash_restore_soak_under_faults(tmp_path):
    rng = np.random.default_rng(0xD0_50AC)
    ck = [rng.bytes(32), rng.bytes(32)]
    dcf = Dcf(NB, LAM, ck, backend="bitsliced")
    prg = HirosePrgNp(LAM, ck)
    bundles = {}
    for name in ("d0", "d1", "d2"):
        alphas = rng.integers(0, 256, (1, NB), dtype=np.uint8)
        betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
        bundles[name] = dcf.gen(alphas, betas, rng=rng)
    names = sorted(bundles)

    calls = {"n": 0}

    def every_ninth(*_args):
        calls["n"] += 1
        if calls["n"] % 9 == 0:
            raise faults.InjectedFault("intermittent eval failure")

    mismatches: list[str] = []
    ok_counts = {"n": 0}

    def client(svc, stop, seed):
        crng = np.random.default_rng(seed)
        while not stop.is_set():
            name = names[int(crng.integers(0, len(names)))]
            b = int(crng.integers(0, 2))
            m = int(crng.integers(1, 25))
            xs = crng.integers(0, 256, (m, NB), dtype=np.uint8)
            try:
                y = svc.evaluate(name, xs, b=b, timeout=60)
            except (DcfError, faults.InjectedFault):
                continue  # typed shed/retry-exhausted failures are fine
            want = eval_batch_np(prg, b, bundles[name].for_party(b), xs)
            if not np.array_equal(y, want):
                mismatches.append(f"{name} party {b} m={m}")
                return
            ok_counts["n"] += 1

    def make_svc():
        return DcfService(dcf, ServeConfig(
            max_batch=64, max_delay_ms=2.0, retries=1,
            max_queued_points=4096, store_dir=str(tmp_path)))

    gens = None
    with faults.inject("serve.eval", handler=every_ninth):
        for cycle in range(3):
            svc = make_svc()
            if cycle == 0:
                for name in names:
                    svc.register_key(name, bundles[name], durable=True)
                gens = {k: svc.registry.snapshot(k)[2] for k in names}
            else:
                report = svc.restore_keys()
                assert sorted(report.restored) == names, cycle
                assert report.quarantined == {}, cycle
                assert report.restored == gens, cycle  # gens preserved
            svc.start()
            stop = threading.Event()
            threads = [threading.Thread(
                target=client, args=(svc, stop, 31 * cycle + i),
                daemon=True) for i in range(3)]
            for t in threads:
                t.start()
            stop.wait(1.5)
            stop.set()
            # The kill: close mid-load without draining, clients still
            # submitting — queued futures fail typed, nothing drains.
            svc.close(drain=False)
            for t in threads:
                t.join(60)
            assert not any(t.is_alive() for t in threads)
    assert mismatches == [], mismatches
    assert ok_counts["n"] > 0  # the soak actually delivered results

    # Final restart, faults disarmed: full two-party parity per key.
    svc = make_svc()
    report = svc.restore_keys()
    assert sorted(report.restored) == names
    assert report.restored == gens
    xs = rng.integers(0, 256, (9, NB), dtype=np.uint8)
    for name in names:
        f0 = svc.submit(name, xs, b=0)
        f1 = svc.submit(name, xs, b=1)
        svc.pump()
        want = eval_batch_np(prg, 0, bundles[name].for_party(0), xs) ^ \
            eval_batch_np(prg, 1, bundles[name].for_party(1), xs)
        assert np.array_equal(f0.result(5) ^ f1.result(5), want), name
    svc.close()
