"""Full-domain DPF EvalAll (ISSUE 19): host walk, device kernel, mesh.

Parity discipline, innermost out: the host breadth-first expansion
(``dpf_tree_expand_np``) must agree with the per-point reference walk
AND the ``dpf_oracle`` golden model over an ENTIRE domain; the Pallas
kernel must be byte-identical to that host expansion at the device
width; the mesh-sharded kernel must reconstruct the point function over
the whole domain on every shard; and a depth-d prefix evaluation of a
deeper key must hand back exactly the depth-d one-hot t-planes — the
contract 2-server PIR rides for non-byte-granular database domains.
"""

import warnings

import numpy as np
import pytest

from dcf_tpu.backends.evalall import (
    DpfEvalAll,
    dpf_finalize_np,
    dpf_tree_expand_np,
    leaf_planes_to_bytes,
)
from dcf_tpu.errors import ShapeError
from dcf_tpu.gen import random_s0s
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.parallel import ShardedDpfEvalAll, make_mesh
from dcf_tpu.protocols.dpf import DPF_DEVICE_LAM, dpf_gen_batch
from dcf_tpu.protocols.dpf import dpf_eval_points
from dcf_tpu.protocols.oracle import dpf_oracle
from dcf_tpu.utils.bits import unpack_lanes

pytestmark = pytest.mark.dpf

LAM = DPF_DEVICE_LAM  # 32: the two-block device width


def _cipher_keys(rng, lam: int) -> list:
    n = 18 if lam >= 32 else max(2, 2 * (lam // 16))
    return [bytes(rng.integers(0, 256, 32, dtype=np.uint8))
            for _ in range(n)]


def _prg(lam, ck):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return HirosePrgNp(lam, ck)


def _bitrev_values(n_bits: int) -> np.ndarray:
    pos = np.arange(1 << n_bits, dtype=np.uint32)
    value = np.zeros(1 << n_bits, dtype=np.uint32)
    for k in range(n_bits):
        value |= ((pos >> k) & 1) << (n_bits - 1 - k)
    return value


def _bundle(rng, prg, alpha_vals, n_bits, lam):
    nb = n_bits // 8
    alphas = np.array([list(int(a).to_bytes(nb, "big"))
                       for a in alpha_vals], dtype=np.uint8)
    betas = rng.integers(0, 256, (len(alpha_vals), lam), dtype=np.uint8)
    s0s = random_s0s(len(alpha_vals), lam, rng)
    return dpf_gen_batch(prg, alphas, betas, s0s), betas


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0xEA11)


@pytest.fixture(scope="module")
def ck(rng):
    return _cipher_keys(rng, LAM)


@pytest.fixture(scope="module")
def evaluator(ck):
    return DpfEvalAll(LAM, ck, interpret=True)


def test_host_evalall_vs_per_point_walk_and_oracle_full_domain(rng):
    """The breadth-first expansion over the WHOLE 2^8 domain is
    bit-exact against the per-point reference walk at every point, and
    the two parties' leaves XOR to the golden point function."""
    lam, n = 16, 8
    prg = _prg(lam, _cipher_keys(rng, lam))
    alpha_vals = [0, 137, 255]
    bundle, betas = _bundle(rng, prg, alpha_vals, n, lam)
    values = _bitrev_values(n)  # domain value at each leaf position
    xs = values.astype(np.uint8)[:, None]  # [N, 1] bytes, leaf order
    leaves = {}
    for b in (0, 1):
        part = bundle.for_party(b)
        s, t = dpf_tree_expand_np(prg, part, b, n)
        y = dpf_finalize_np(bundle, s, t)
        np.testing.assert_array_equal(y, dpf_eval_points(prg, part, b, xs))
        leaves[b] = y
    recon = leaves[0] ^ leaves[1]
    for i, a in enumerate(alpha_vals):
        np.testing.assert_array_equal(recon[i], dpf_oracle(xs, a, betas[i]))


def test_device_evalall_byte_identical_to_host_n16(rng, ck, evaluator):
    """The Pallas kernel's leaf planes, unpacked back to bytes, equal
    the host expansion exactly — payload AND t column, both parties,
    K-packed — over the full 2^16 domain."""
    n = 16
    prg = _prg(LAM, ck)
    alpha_vals = [0, 0xBEEF]
    bundle, _betas = _bundle(rng, prg, alpha_vals, n, LAM)
    staged_cw, fronts, parts = evaluator._staged_for(bundle, n)
    for b in (0, 1):
        y0, y1, t = evaluator.eval_party(b, parts[b], n, staged_cw,
                                         fronts[b])
        y_dev, t_dev = leaf_planes_to_bytes(y0, y1, t)
        s, t_host = dpf_tree_expand_np(prg, parts[b], b, n)
        np.testing.assert_array_equal(y_dev, dpf_finalize_np(
            bundle, s, t_host))
        np.testing.assert_array_equal(t_dev, t_host)


def test_device_check_clean_and_tamper_detected(rng, ck, evaluator):
    """The on-device verifier sees zero mismatching leaves for honest
    keys and a nonzero count once a payload byte is cooked (n=8 keeps
    this fast; depth coverage rides the n=16 parity tests)."""
    n = 8
    prg = _prg(LAM, ck)
    alpha_vals = [3, 129]
    bundle, betas = _bundle(rng, prg, alpha_vals, n, LAM)
    assert evaluator.check(bundle, alpha_vals, betas, n) == 0
    bad = betas.copy()
    bad[1, 0] ^= 0x40
    evaluator.invalidate()
    assert evaluator.check(bundle, alpha_vals, bad, n) > 0
    evaluator.invalidate()


def test_prefix_depth_t_planes_are_the_selection_vector(rng, ck,
                                                        evaluator):
    """A 9-level evaluation of a 16-level key stops mid-tree: the t
    lane words must equal the host walk's depth-9 t column, and the
    XOR of the parties must be one-hot at alpha's 9-bit prefix — the
    non-byte-granular PIR contract (y planes deliberately unread)."""
    n_key, d = 16, 9
    prg = _prg(LAM, ck)
    idx = [0, 411]  # 9-bit prefixes
    bundle, _betas = _bundle(rng, prg, [i << (n_key - d) for i in idx],
                             n_key, LAM)
    staged_cw, fronts, parts = evaluator._staged_for(bundle, d)
    t_both = {}
    for b in (0, 1):
        _y0, _y1, t = evaluator.eval_party(b, parts[b], d, staged_cw,
                                           fronts[b])
        _s, t_host = dpf_tree_expand_np(prg, parts[b], b, d)
        t_dev = unpack_lanes(
            np.asarray(t).view(np.uint32))[:, 0, :].astype(np.uint8)
        np.testing.assert_array_equal(t_dev, t_host)
        t_both[b] = t_host
    onehot = t_both[0] ^ t_both[1]
    values = _bitrev_values(d)
    for i, a in enumerate(idx):
        np.testing.assert_array_equal(onehot[i], (values == a)
                                      .astype(np.uint8))
    evaluator.invalidate()


def test_eval_party_depth_and_restriction_contracts(rng, ck, evaluator):
    prg = _prg(LAM, ck)
    bundle, _ = _bundle(rng, prg, [1], 8, LAM)
    with pytest.raises(ShapeError, match="cannot evaluate"):
        evaluator.eval_party(0, bundle.for_party(0), 16)
    with pytest.raises(ShapeError, match="party-restricted"):
        evaluator.eval_party(0, bundle, 8)


def test_sharded_evalall_2x2_mesh(rng, ck):
    """Whole-domain reconstruction on a 2x2 (keys, points) mesh — the
    conftest pins 8 virtual CPU devices, so a real 4-device sharding —
    plus the host_levels floor the frontier split demands."""
    mesh = make_mesh(shape=(2, 2))
    ev = ShardedDpfEvalAll(LAM, ck, mesh, interpret=True)
    prg = _prg(LAM, ck)
    alpha_vals = [7, 200]
    bundle, betas = _bundle(rng, prg, alpha_vals, 8, LAM)
    assert ev.check(bundle, alpha_vals, betas, 8) == 0
    bad = betas.copy()
    bad[0, 5] ^= 0x01
    ev.invalidate()
    assert ev.check(bundle, alpha_vals, bad, 8) > 0
    with pytest.raises(ValueError, match="need >= 7 for 4 devices"):
        ShardedDpfEvalAll(LAM, ck, mesh, host_levels=6, interpret=True)


@pytest.mark.slow
def test_per_point_cross_check_full_n16_domain(rng, ck, evaluator):
    """The serial-leg anchor: every one of the 65536 domain points,
    walked individually by the reference evaluator, agrees with the
    device EvalAll leaves AND the oracle."""
    n = 16
    prg = _prg(LAM, ck)
    alpha_vals = [0xC0DE]
    bundle, betas = _bundle(rng, prg, alpha_vals, n, LAM)
    values = _bitrev_values(n)
    xs = np.array([list(int(v).to_bytes(2, "big")) for v in values],
                  dtype=np.uint8)
    staged_cw, fronts, parts = evaluator._staged_for(bundle, n)
    recon_pp = None
    for b in (0, 1):
        y0, y1, t = evaluator.eval_party(b, parts[b], n, staged_cw,
                                         fronts[b])
        y_dev, _t_dev = leaf_planes_to_bytes(y0, y1, t)
        y_pp = dpf_eval_points(prg, parts[b], b, xs)
        np.testing.assert_array_equal(y_dev, y_pp)
        recon_pp = y_pp if recon_pp is None else recon_pp ^ y_pp
    np.testing.assert_array_equal(
        recon_pp[0], dpf_oracle(xs, alpha_vals[0], betas[0]))
    evaluator.invalidate()
