"""dcf_tpu.serve.health + serve.replicate: pod self-healing (ISSUE 14).

Covers the active health prober (UP -> SUSPECT -> DOWN -> UP hysteresis
with typed events, the recovery gate keeping an unconverged shard DOWN,
bounded cardinality under target churn), the DCFE control verbs (PING
round trips, REGISTER fan-out with the owner's generation preserved,
DIGEST/SYNC anti-entropy pulls), the monotonic-generation fence (a
doctored old-generation frame dies typed ``StaleStateError`` /
``E_STALE``, counted, never served — in-process and across the wire),
DOWN-promotion routing (NORMAL traffic serves from the replica once
the prober marks the owner DOWN; the suspect-state and health-state
planes stay distinguishable in the metrics), the ``net.partition``
fault seam, the pool dial-backoff clamp on probe-confirmed recovery,
and the router's bounded state under ring membership churn.  The
partition and flap soaks ride the serial slow leg.
"""

import pathlib
import socket
import struct
import threading
import time

import numpy as np
import pytest

from dcf_tpu import Dcf
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.errors import (
    BackendUnavailableError,
    CircuitOpenError,
    StaleStateError,
)
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.serve import (
    DcfRouter,
    EdgeClient,
    EdgeClientPool,
    EdgeServer,
    HealthProber,
    ShardMap,
    ShardSpec,
)
from dcf_tpu.serve.edge import E_STALE, decode_response, encode_register
from dcf_tpu.serve.health import DOWN, SUSPECT, UP
from dcf_tpu.serve.metrics import Metrics
from dcf_tpu.testing import faults
from dcf_tpu.testing.faults import FakeClock

pytestmark = pytest.mark.selfheal

NB, LAM = 2, 16


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0x5E1F)


@pytest.fixture(scope="module")
def ck(rng):
    return [rng.bytes(32), rng.bytes(32)]


@pytest.fixture(scope="module")
def dcf(ck):
    return Dcf(NB, LAM, ck, backend="bitsliced")


@pytest.fixture(scope="module")
def prg(ck):
    return HirosePrgNp(LAM, ck)


def mk_bundle(dcf, rng):
    alphas = rng.integers(0, 256, (1, NB), dtype=np.uint8)
    betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
    return dcf.gen(alphas, betas, rng=rng)


def recon_oracle(prg, kb, xs):
    return eval_batch_np(prg, 0, kb.for_party(0), xs) ^ \
        eval_batch_np(prg, 1, kb.for_party(1), xs)


class SelfHealPod:
    """N in-process shard hosts (real DcfService + EdgeServer over
    real TCP) behind one router with fast probe/backoff knobs — the
    tier-1 stand-in for pod_bench's subprocesses."""

    def __init__(self, dcf, n=3, router_kw=None):
        self.svcs, self.servers, specs = [], [], []
        for i in range(n):
            svc = dcf.serve(max_batch=32, max_delay_ms=1.0)
            svc.start()
            srv = EdgeServer(svc).start()
            self.svcs.append(svc)
            self.servers.append(srv)
            specs.append(ShardSpec(f"shard-{i}", *srv.address))
        self.map = ShardMap(specs)
        self._index = {s.host_id: i for i, s in enumerate(specs)}
        kw = dict(probe_fail_n=2, probe_recover_m=2,
                  reconnect_backoff_s=0.01, max_backoff_s=0.05,
                  probe_interval_s=0.05)
        kw.update(router_kw or {})
        self.router = DcfRouter(self.map, n_bytes=NB, **kw)

    def svc_of(self, host_id):
        return self.svcs[self._index[host_id]]

    def key_owned_by(self, host_id, prefix="sh-key"):
        n = 0
        while True:
            name = f"{prefix}-{n}"
            if self.map.owner(name).host_id == host_id:
                return name
            n += 1

    def kill(self, host_id):
        i = self._index[host_id]
        self.servers[i].close()
        self.svcs[i].close(drain=False)

    def pump_until(self, host_id, state, rounds=120, sleep=0.05):
        for _ in range(rounds):
            if self.router.health.pump()[host_id] == state:
                return True
            time.sleep(sleep)
        return False

    def close(self):
        self.router.close()
        for srv in self.servers:
            srv.close()
        for svc in self.svcs:
            try:
                svc.close(drain=False)
            except Exception:  # fallback-ok: best-effort teardown of
                # an already-killed shard
                pass


# ------------------------------------------------- the state machine


class FakeTarget:
    """A pingable whose outcomes the test scripts."""

    def __init__(self):
        self.ok = True
        self.pings = 0

    def ping(self, timeout=None):
        self.pings += 1
        if not self.ok:
            raise BackendUnavailableError("scripted probe failure")
        return True


def test_health_prober_state_machine_events_and_gate():
    """The acceptance walk on a fake clock: first failure -> SUSPECT,
    fail_n consecutive -> DOWN, one success mid-SUSPECT -> UP (a blip
    is not an outage), recover_m successes while DOWN run the gate —
    a refusing gate keeps the shard DOWN (counted), a passing one
    re-admits.  Every transition is a typed event and a gauge write."""
    clk = FakeClock(100.0)
    t = FakeTarget()
    gate_calls = []
    gate_verdict = {"ok": False}

    def gate(host_id):
        gate_calls.append(host_id)
        return gate_verdict["ok"]

    m = Metrics()
    hp = HealthProber({"s0": t}, interval_s=0.5, fail_n=3, recover_m=2,
                      clock=clk, metrics=m, recover_gate=gate)
    assert hp.pump() == {"s0": UP}
    # One failed probe: a blip -> SUSPECT; one success heals it.
    t.ok = False
    assert hp.pump() == {"s0": SUSPECT}
    t.ok = True
    assert hp.pump() == {"s0": UP}
    # fail_n consecutive failures -> DOWN.
    t.ok = False
    for want in (SUSPECT, SUSPECT, DOWN):
        assert hp.pump() == {"s0": want}
    snap = m.snapshot()
    assert snap["router_health_state{shard=s0}"] == 2
    assert snap["router_down_shards"] == 1
    assert snap["router_probe_failures_total{shard=s0}"] == 4
    # Recovery: recover_m successes run the gate; a refusing gate
    # keeps the shard DOWN and is counted.
    t.ok = True
    hp.pump()
    assert hp.state("s0") == DOWN and gate_calls == []
    hp.pump()
    assert gate_calls == ["s0"] and hp.state("s0") == DOWN
    assert m.snapshot()["router_recover_gate_failures_total"] == 1
    gate_verdict["ok"] = True
    hp.pump()
    hp.pump()
    assert hp.state("s0") == UP
    evs = [(e.frm, e.to) for e in hp.events()]
    assert evs == [(UP, SUSPECT), (SUSPECT, UP), (UP, SUSPECT),
                   (SUSPECT, DOWN), (DOWN, UP)]
    assert hp.events() == []  # events() drains
    assert m.snapshot()["router_down_shards"] == 0


def test_health_prober_validates_config_and_churn_is_bounded():
    with pytest.raises(ValueError):
        HealthProber({}, interval_s=0.0)
    with pytest.raises(ValueError):
        HealthProber({}, fail_n=0)
    with pytest.raises(ValueError):
        HealthProber({}, recover_m=0)
    # Target churn: removed targets leave state AND labeled series.
    m = Metrics()
    hp = HealthProber({}, interval_s=0.1, metrics=m)
    baseline = set(m.snapshot())
    for i in range(5):
        t = FakeTarget()
        t.ok = False
        hp.add_target(f"churn-{i}", t)
        hp.pump()
        assert hp.state(f"churn-{i}") == SUSPECT
        hp.remove_target(f"churn-{i}")
        assert hp.states() == {}
    leftovers = {k for k in m.snapshot() if "churn-" in k}
    assert leftovers == set(), leftovers
    assert set(m.snapshot()) == baseline | {
        "router_health_transitions_total",
        "router_health_transitions_total{to=suspect}"}


# ------------------------------------------------- wire control verbs


def test_ping_round_trip_and_dead_target_typed(dcf):
    svc = dcf.serve(max_batch=32, max_delay_ms=1.0)
    svc.start()
    server = EdgeServer(svc).start()
    host, port = server.address
    try:
        with EdgeClient(host, port, n_bytes=NB) as c:
            assert c.ping(timeout=30) is True
            assert c.ping(timeout=30) is True  # connection survives
        pool = EdgeClientPool(host, port, n_bytes=NB, size=1)
        try:
            assert pool.ping(timeout=30) is True
        finally:
            pool.close()
        server.close()
        with pytest.raises(BackendUnavailableError):
            EdgeClientPool(host, port, n_bytes=NB, size=1,
                           connect_timeout=2.0).ping(timeout=5)
    finally:
        server.close()
        svc.close(drain=False)


def test_live_registration_fans_out_generation_preserved(dcf, prg,
                                                         rng):
    """The tentpole's replication half: one router-door registration
    lands on the owner AND the replica with the SAME owner-minted
    generation (the wire round-trips it), serves bit-exact through
    the router, and the digests agree."""
    pod = SelfHealPod(dcf, n=3)
    try:
        kb = mk_bundle(dcf, rng)
        name = pod.key_owned_by("shard-0")
        gen = pod.router.register_key(name, kb)
        assert gen >= 1
        placed = pod.map.placement(name, replicas=1)
        assert len(placed) == 2
        for spec in placed:
            assert pod.svc_of(spec.host_id).replication_digest() \
                == {name: gen}
        others = [s for s in pod.map.hosts()
                  if s not in placed]
        for spec in others:
            assert name not in pod.svc_of(
                spec.host_id).replication_digest()
        xs = rng.integers(0, 256, (7, NB), dtype=np.uint8)
        got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
        snap = pod.router.metrics_snapshot()
        assert snap["router_registered_total"] == 1
        assert snap["router_replicated_total"] == 1
        # Hot-swap through the router: the new generation is strictly
        # newer everywhere the key lands.
        kb2 = mk_bundle(dcf, rng)
        gen2 = pod.router.register_key(name, kb2)
        assert gen2 > gen
        for spec in placed:
            assert pod.svc_of(spec.host_id).replication_digest() \
                == {name: gen2}
        got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb2, xs))
    finally:
        pod.close()


def test_generation_fence_typed_counted_in_process_and_wire(dcf, prg,
                                                            rng):
    """ISSUE 14 acceptance: a doctored old-generation frame is fenced
    typed (``StaleStateError`` / ``E_STALE``), counted
    (``serve_replica_fenced_total``), and NEVER served — the key keeps
    answering with the newer key's bits."""
    svc = dcf.serve(max_batch=32, max_delay_ms=1.0)
    svc.start()
    server = EdgeServer(svc).start()
    try:
        kb_new, kb_old = mk_bundle(dcf, rng), mk_bundle(dcf, rng)
        gen = svc.apply_replica_frame("fence-key", kb_new.to_bytes(), 7)
        assert gen == 7
        for doctored in (7, 3):  # equal AND strictly older both fence
            with pytest.raises(StaleStateError):
                svc.apply_replica_frame("fence-key", kb_old.to_bytes(),
                                        doctored)
        assert svc.metrics_snapshot()[
            "serve_replica_fenced_total"] == 2
        with EdgeClient(*server.address, n_bytes=NB) as c:
            with pytest.raises(StaleStateError) as ei:
                c.register_frame("fence-key", kb_old.to_bytes(),
                                 generation=7)
            assert ei.value.wire_code == E_STALE
            # ...and the connection survived the typed refusal; the
            # key still serves the NEW bits.
            xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
            y0 = c.evaluate("fence-key", xs, b=0, timeout=60)
            assert np.array_equal(
                y0, eval_batch_np(prg, 0, kb_new.for_party(0), xs))
        # A strictly newer generation passes the fence.
        assert svc.apply_replica_frame("fence-key", kb_old.to_bytes(),
                                       11) == 11
        # ...and a local hot-swap mints ABOVE everything applied.
        svc.register_key("fence-key", kb_new)
        assert svc.replication_digest()["fence-key"] > 11
    finally:
        server.close()
        svc.close(drain=False)


def test_sync_frames_chunked_and_suppressed(dcf, rng):
    """Review hardening pins: (a) a SYNC response is CAPPED — a heal
    with a large backlog streams in bounded chunks the puller
    iterates over (one unbounded frame would trip the client's frame
    bound and wedge recovery exactly when the backlog is largest);
    (b) the ``DIGEST_SUPPRESS`` sentinel keeps a key's frame from
    ever being serialized — sender-side placement filtering, so
    unplaced key material never crosses the wire."""
    from dcf_tpu.serve.replicate import DIGEST_SUPPRESS, sync_frames

    svc = dcf.serve(max_batch=32, max_delay_ms=1.0)
    try:
        frames = {}
        for i in range(6):
            kb = mk_bundle(dcf, rng)
            svc.register_key(f"chunk-{i}", kb)
            frames[f"chunk-{i}"] = len(kb.to_bytes())
        one = max(frames.values())
        # Cap below two frames: each call returns exactly one entry,
        # and advancing the digest walks the whole set.
        digest: dict = {}
        seen = []
        while True:
            entries = sync_frames(svc.registry, digest, max_bytes=one)
            if not entries:
                break
            assert len(entries) == 1
            key_id, gen, _proto, frame = entries[0]
            seen.append(key_id)
            digest[key_id] = gen
        assert seen == sorted(frames)
        # Suppression: a sentinel-marked key is never serialized.
        digest = {"chunk-0": DIGEST_SUPPRESS}
        got = {e[0] for e in sync_frames(svc.registry, digest)}
        assert got == set(sorted(frames)[1:])
    finally:
        svc.close(drain=False)


def test_register_at_contract():
    from dcf_tpu.serve.registry import KeyRegistry

    reg = KeyRegistry(lambda: None)
    with pytest.raises(ValueError):
        reg.register_at("k", None, 0)  # 0 is the wire's mint sentinel


# ------------------------------------------------- partition + heal


def test_partition_heals_via_anti_entropy(dcf, prg, rng):
    """The tentpole loop end to end: a registration during a router<->
    replica partition reaches only the owner (counted); probes walk
    the cut link UP -> SUSPECT -> DOWN; on heal, recover_m successes
    trigger ONE anti-entropy pass that pulls exactly the missed frame
    (generation preserved) before the shard is re-admitted UP."""
    pod = SelfHealPod(dcf, n=2)
    try:
        victim = "shard-1"
        owner = "shard-0"
        name = pod.key_owned_by(owner)
        assert pod.map.replica(name).host_id == victim
        kb = mk_bundle(dcf, rng)
        with faults.inject("net.partition",
                           handler=faults.partition(
                               {("router", victim)})):
            gen = pod.router.register_key(name, kb)
            assert pod.svc_of(owner).replication_digest() == {name: gen}
            assert pod.svc_of(victim).replication_digest() == {}
            assert pod.pump_until(victim, DOWN)
            snap = pod.router.metrics_snapshot()
            assert snap["router_replicate_failures_total"] == 1
            # While the replica is DOWN the owner serves everything.
            xs = rng.integers(0, 256, (5, NB), dtype=np.uint8)
            got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
                pod.router.evaluate(name, xs, b=1, timeout=60)
            assert np.array_equal(got, recon_oracle(prg, kb, xs))
        # Healed: the recovery gate converges the digest BEFORE UP.
        assert pod.pump_until(victim, UP)
        assert pod.svc_of(victim).replication_digest() == {name: gen}
        snap = pod.router.metrics_snapshot()
        assert snap["router_anti_entropy_runs_total"] >= 1
        assert snap["router_anti_entropy_frames_total"] == 1
        evs = [(e.host_id, e.frm, e.to)
               for e in pod.router.health.events()]
        assert (victim, SUSPECT, DOWN) in evs
        assert (victim, DOWN, UP) in evs
    finally:
        pod.close()


def test_down_promotion_serves_normal_from_replica(dcf, prg, rng):
    """Satellite: the prober says DOWN before any request failed —
    NORMAL (not just CRITICAL) traffic serves bit-exact from the
    promoted replica, counted on the PROMOTION metric (the health
    plane), with the request-suspicion plane untouched."""
    pod = SelfHealPod(dcf, n=3)
    try:
        victim = "shard-0"
        name = pod.key_owned_by(victim)
        kb = mk_bundle(dcf, rng)
        pod.router.register_key(name, kb)
        pod.kill(victim)
        # No request has failed: the DOWN verdict comes from probes.
        assert pod.pump_until(victim, DOWN)
        assert pod.router.suspect_remaining(victim) == 0.0
        xs = rng.integers(0, 256, (6, NB), dtype=np.uint8)
        got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
        snap = pod.router.metrics_snapshot()
        assert snap["router_promoted_forwards_total"] >= 2
        assert snap["router_failovers_total"] == 0
        assert snap.get(
            f"router_suspected_total{{shard={victim}}}", 0) == 0
        assert snap[f"router_health_state{{shard={victim}}}"] == 2
    finally:
        pod.close()


def test_every_holder_down_refused_typed_with_hint(dcf, rng):
    pod = SelfHealPod(dcf, n=2)
    try:
        name = pod.key_owned_by("shard-0")
        kb = mk_bundle(dcf, rng)
        pod.router.register_key(name, kb)
        for hid in ("shard-0", "shard-1"):
            pod.kill(hid)
        for hid in ("shard-0", "shard-1"):
            assert pod.pump_until(hid, DOWN)
        xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
        with pytest.raises(CircuitOpenError) as ei:
            pod.router.evaluate(name, xs, b=0, timeout=60)
        assert ei.value.retry_after_s is not None
        assert ei.value.retry_after_s > 0
        snap = pod.router.metrics_snapshot()
        assert snap["router_down_refusals_total"] >= 1
    finally:
        pod.close()


def test_request_suspect_while_prober_up_refuses_typed(dcf, prg, rng):
    """Satellite: the converse interaction — a shard marked suspect by
    an in-flight transport failure while the prober still says UP.
    NORMAL is refused typed with ``retry_after_s`` on the REQUEST
    plane (``router_suspected_total``), CRITICAL fails over, and the
    health plane shows zero probe evidence."""
    pod = SelfHealPod(dcf, n=3)
    try:
        victim = "shard-0"
        name = pod.key_owned_by(victim)
        kb = mk_bundle(dcf, rng)
        pod.router.register_key(name, kb)
        pod.kill(victim)
        # NO pump: the prober has never observed the death.
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        with pytest.raises(CircuitOpenError) as ei:
            pod.router.evaluate(name, xs, b=0, timeout=60)
        assert ei.value.retry_after_s is not None
        assert pod.router.health.state(victim) == UP
        assert pod.router.suspect_remaining(victim) > 0
        got = pod.router.evaluate(name, xs, b=0, timeout=60,
                                  priority="critical") ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60,
                                priority="critical")
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
        snap = pod.router.metrics_snapshot()
        assert snap[f"router_suspected_total{{shard={victim}}}"] >= 1
        assert snap["router_failovers_total"] >= 2
        assert snap["router_promoted_forwards_total"] == 0
        assert snap[
            f"router_probe_failures_total{{shard={victim}}}"] == 0
    finally:
        pod.close()


# ------------------------------------------------- satellites


def test_pool_backoff_clamped_on_probe_confirmed_recovery(monkeypatch):
    """Satellite: a pool whose target was dark long enough to reach
    its max exponential backoff must NOT wait it out once health says
    UP — ``reset_backoff`` (wired to the router's UP transition)
    makes the next lease dial immediately.  FakeClock-pinned."""
    import dcf_tpu.serve.edge as edge_mod

    clk = FakeClock(10.0)
    dialed = {"n": 0}

    def failing_connect(*a, **kw):
        dialed["n"] += 1
        raise OSError("injected dead target")

    monkeypatch.setattr(edge_mod.socket, "create_connection",
                        failing_connect)
    pool = EdgeClientPool("127.0.0.1", 1, n_bytes=NB, size=1,
                          clock=clk, reconnect_backoff_s=1.0,
                          max_backoff_s=64.0)
    try:
        # Drive the backoff to its 64s ceiling.
        for _ in range(8):
            with pytest.raises(BackendUnavailableError):
                pool.ping(timeout=1)
            clk.advance(pool._backoff)
        with pytest.raises(BackendUnavailableError):
            pool.ping(timeout=1)
        assert pool._backoff == 64.0
        before = dialed["n"]
        # Dark: leases fail fast WITHOUT dialing...
        clk.advance(1.0)
        with pytest.raises(BackendUnavailableError, match="dark"):
            pool.ping(timeout=1)
        assert dialed["n"] == before
        # ...until the probe-confirmed UP clamps the backoff: the next
        # lease dials with NO clock advance at all.
        pool.reset_backoff()
        with pytest.raises(BackendUnavailableError, match="connect"):
            pool.ping(timeout=1)
        assert dialed["n"] == before + 1
    finally:
        pool.close()


def test_router_up_transition_clamps_backoff_and_suspicion(dcf, rng):
    """The router half of the satellite: a DOWN -> UP health event
    resets the shard pool's dial backoff AND clears the stale
    request-suspicion cooldown (probe-confirmed recovery outranks
    both)."""
    pod = SelfHealPod(dcf, n=2)
    try:
        victim = "shard-1"
        pod.router.mark_suspect(victim, 3600.0)
        pool = pod.router._pools[victim]
        # A live pooled connection (the recovery gate's anti-entropy
        # leases it, bypassing the dark sentinel below — exactly how a
        # real recovery looks: the successful probes already dialed).
        assert pool.ping(timeout=30)
        pool._backoff, pool._dark_until = 64.0, 1e18
        # Anti-entropy is vacuous here (nothing registered): drive the
        # DOWN -> UP walk through the prober's own observe path.
        for _ in range(2):
            pod.router.health.observe(victim, False)
        pod.router.health.observe(victim, False)
        assert pod.router.health.state(victim) == DOWN
        for _ in range(2):
            pod.router.health.observe(victim, True)
        assert pod.router.health.state(victim) == UP
        assert pool._dark_until is None and pool._backoff == 0.0
        assert pod.router.suspect_remaining(victim) == 0.0
    finally:
        pod.close()


def test_router_state_bounded_under_ring_churn(dcf, rng):
    """Satellite: the ``BreakerBoard.forget`` discipline applied to
    the router — churning a host in and out of the ring (suspect
    state, probe failures, metric series and all) leaves the suspect
    map, the pool table and the metrics snapshot EXACTLY where they
    started, five cycles in a row."""
    pod = SelfHealPod(dcf, n=2)
    try:
        xs = rng.integers(0, 256, (2, NB), dtype=np.uint8)
        kb = mk_bundle(dcf, rng)
        baseline = None
        for cycle in range(5):
            ghost = f"ghost-{cycle}"
            grown = pod.map.with_host(ShardSpec(ghost, "127.0.0.1", 1))
            pod.router.set_ring(grown)
            assert ghost in pod.router._pools
            # Accumulate every kind of per-host state for the ghost:
            # request suspicion (a failed forward), probe failures,
            # health transitions, forward counters.
            name = grown.owner_key = next(
                f"ghost-key-{n}" for n in range(500)
                if grown.owner(f"ghost-key-{n}").host_id == ghost)
            with pytest.raises(CircuitOpenError):
                pod.router.evaluate(name, xs, b=0, timeout=30)
            pod.router.health.pump()
            assert pod.router.suspect_remaining(ghost) > 0
            snap = pod.router.metrics_snapshot()
            assert f"router_suspected_total{{shard={ghost}}}" in snap
            # ...and churn it back out: everything is forgotten.
            pod.router.set_ring(pod.map)
            assert ghost not in pod.router._pools
            assert pod.router.suspect_remaining(ghost) == 0.0
            assert pod.router.health.states() == {
                "shard-0": UP, "shard-1": UP}
            snap = pod.router.metrics_snapshot()
            leftovers = {k for k in snap if ghost in k}
            assert leftovers == set(), leftovers
            keys = set(snap)
            if baseline is None:
                baseline = keys
            else:
                assert keys == baseline
        # The surviving ring still serves.
        name = pod.key_owned_by("shard-0")
        pod.router.register_key(name, kb)
        pod.router.evaluate(name, xs, b=0, timeout=60)
    finally:
        pod.close()


def test_partition_handler_contract():
    calls = []

    h = faults.partition({("a", "b")})
    h("a", "c")  # not cut: passes
    with pytest.raises(OSError):
        h("a", "b")
    with pytest.raises(OSError):
        h("b", "a")  # symmetric
    clk = FakeClock(0.0)
    hw = faults.partition({("a", "b")}, clock=clk, window=(5.0, 10.0))
    hw("a", "b")  # before the window
    clk.advance(6.0)
    with pytest.raises(OSError):
        hw("a", "b")
    clk.advance(10.0)
    hw("a", "b")  # healed
    with pytest.raises(ValueError):
        faults.partition({("a",)})
    with pytest.raises(ValueError):
        faults.partition({("a", "b")}, clock=clk)  # window missing
    assert calls == []


def test_wire_fuzz_register_frames_die_typed(dcf, rng):
    """Control-frame fuzz: byte-flipped REGISTER frames at a shard
    door each die as a typed per-connection outcome, and a healthy
    connection keeps round-tripping pings throughout."""
    svc = dcf.serve(max_batch=32, max_delay_ms=1.0)
    svc.start()
    server = EdgeServer(svc).start()
    addr = server.address
    kb = mk_bundle(dcf, rng)
    frame = encode_register(5, "fuzz-key", kb.to_bytes(), 0, False)
    healthy = EdgeClient(*addr, n_bytes=NB)
    try:
        for off in rng.choice(len(frame) - 4, size=8, replace=False):
            buf = bytearray(frame)
            buf[4 + int(off)] ^= 0x41
            s = socket.create_connection(addr, timeout=30)
            try:
                s.sendall(bytes(buf))
                s.shutdown(socket.SHUT_WR)
                data = b""
                while True:
                    try:
                        chunk = s.recv(1 << 16)
                    except ConnectionResetError:
                        break
                    if not chunk:
                        break
                    data += chunk
            finally:
                s.close()
            off2 = 0
            while off2 < len(data):
                (body_len,) = struct.unpack_from("<I", data, off2)
                decoded = decode_response(
                    data[off2 + 4:off2 + 4 + body_len])
                assert decoded[0] == "error", decoded
                off2 += 4 + body_len
            assert healthy.ping(timeout=30)
            assert not healthy.closed
        assert "fuzz-key" not in svc.replication_digest()
    finally:
        healthy.close()
        server.close()
        svc.close(drain=False)


def test_selfheal_layer_lint_clean():
    """The ISSUE-14 CI satellite: the self-healing tier —
    ``serve/health.py`` (the probe state machine) and
    ``serve/replicate.py`` (live replication + anti-entropy) — sweeps
    clean under ALL six dcflint passes.  Determinism and secret
    hygiene are the load-bearing ones: probe cadence runs on the
    injectable clock, and replication moves whole DCFK frames whose
    buffer names (``frame``/``frame_bytes``) are in the key-material
    set."""
    from tools.dcflint import run_path

    repo = pathlib.Path(__file__).resolve().parent.parent
    assert run_path(repo / "dcf_tpu" / "serve" / "health.py") == []
    assert run_path(repo / "dcf_tpu" / "serve" / "replicate.py") == []


def test_secret_hygiene_learned_frame_bytes(tmp_path):
    """ISSUE 14: ``frame_bytes`` joined the key-material name set —
    the live-replication buffers hold serialized DCFK frames, so a
    sink referencing one is flagged like logging the key itself."""
    from tools.dcflint import run_path

    p = tmp_path / "serve" / "healing.py"
    p.parent.mkdir(parents=True)
    p.write_text(
        "def push(key_id, frame_bytes, n):\n"
        "    log(f'sync {frame_bytes}')\n"   # name leak: flagged
        "    counter.inc(n)\n")              # scalar: fine
    got = [v for v in run_path(tmp_path, ["secret-hygiene"])
           if v.path.endswith("healing.py")]
    assert [v.line for v in got] == [2]
    assert "frame_bytes" in got[0].message


# ------------------------------------------------- the slow soaks


def _soak_clients(pod, bundles, prg, stats, lock, stop, n_threads=3):
    names = sorted(bundles)

    def client(i):
        crng = np.random.default_rng(400 + i)
        while not stop.is_set():
            name = names[int(crng.integers(0, len(names)))]
            pr = "critical" if crng.random() < 0.4 else "normal"
            m = int(crng.integers(1, 17))
            xs = crng.integers(0, 256, (m, NB), dtype=np.uint8)
            try:
                f0 = pod.router.submit(name, xs, b=0, priority=pr)
                f1 = pod.router.submit(name, xs, b=1, priority=pr)
                got = f0.result(60) ^ f1.result(60)
            except Exception as e:  # fallback-ok: the soak's ledger —
                # every failure is classified, anything untyped or
                # unhinted fails the gate
                from dcf_tpu.errors import DcfError

                hinted = getattr(e, "retry_after_s", None) is not None
                with lock:
                    if isinstance(e, DcfError) and hinted:
                        stats["refused_hinted"] += 1
                    elif isinstance(e, DcfError):
                        stats["refused_unhinted"] += 1
                    else:
                        stats["unaccounted"] += 1
                continue
            ok = np.array_equal(got,
                                recon_oracle(prg, bundles[name], xs))
            with lock:
                stats["ok" if ok else "mismatch"] += 1

    return [threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(n_threads)]


@pytest.mark.slow
def test_partition_soak_every_request_accounted(dcf, prg, rng):
    """Serial-leg soak (ISSUE 14 acceptance): 3 shards under 3-thread
    mixed load with the health prober RUNNING, a ``net.partition``
    window isolating one shard mid-run.  Every request reconstructs
    bit-exact vs the numpy oracle or is refused typed with
    ``retry_after_s`` — zero mismatches, zero unaccounted, zero
    unhinted.  On heal, anti-entropy converges the victim's digest
    with zero generation regressions, and a doctored old-generation
    frame is fenced typed, never served."""
    pod = SelfHealPod(dcf, n=3)
    bundles, gens = {}, {}
    try:
        for i in range(6):
            name = f"soak-key-{i}"
            bundles[name] = mk_bundle(dcf, rng)
            gens[name] = pod.router.register_key(name, bundles[name])
        victim = pod.map.owner(sorted(bundles)[0]).host_id
        stats = {"ok": 0, "mismatch": 0, "refused_hinted": 0,
                 "refused_unhinted": 0, "unaccounted": 0}
        lock, stop = threading.Lock(), threading.Event()
        threads = _soak_clients(pod, bundles, prg, stats, lock, stop)
        pod.router.start_health()
        t0 = time.monotonic()
        cut = faults.partition({("router", victim)},
                               clock=time.monotonic,
                               window=(t0 + 1.0, t0 + 3.0))
        with faults.inject("net.partition", handler=cut):
            for t in threads:
                t.start()
            # Mid-window: a new registration reaches the reachable
            # holders; the victim converges post-heal.
            time.sleep(1.6)
            late = "soak-late-key"
            bundles[late] = mk_bundle(dcf, rng)
            gens[late] = pod.router.register_key(late, bundles[late])
            time.sleep(2.4)
            # Healed: wait for the prober to re-admit the victim.
            deadline = time.monotonic() + 30
            while pod.router.health.state(victim) != UP:
                assert time.monotonic() < deadline, \
                    pod.router.health.states()
                time.sleep(0.05)
            stop.set()
            for t in threads:
                t.join(30)
        assert stats["ok"] >= 5, stats
        assert stats["mismatch"] == 0, stats
        assert stats["unaccounted"] == 0, stats
        assert stats["refused_unhinted"] == 0, stats
        # Convergence: the victim holds exactly the generations the
        # ring placed on it — zero regressions.
        victim_digest = pod.svc_of(victim).replication_digest()
        for name, gen in gens.items():
            placed = {s.host_id
                      for s in pod.map.placement(name, replicas=1)}
            if victim in placed:
                assert victim_digest.get(name) == gen, (name, gen)
        snap = pod.router.metrics_snapshot()
        assert snap["router_anti_entropy_runs_total"] >= 1
        # The doctored frame: an old generation can never roll back.
        name = next(n for n, g in gens.items()
                    if victim in {s.host_id for s in
                                  pod.map.placement(n, replicas=1)})
        with pytest.raises(StaleStateError):
            pod.svc_of(victim).apply_replica_frame(
                name, mk_bundle(dcf, rng).to_bytes(), gens[name])
        xs = rng.integers(0, 256, (8, NB), dtype=np.uint8)
        got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, bundles[name],
                                                xs))
    finally:
        pod.close()


@pytest.mark.slow
def test_flap_soak_generations_never_regress(dcf, prg, rng):
    """Serial-leg flap soak: the victim link is cut and healed three
    times under load.  The ledger stays clean every cycle, the victim
    is re-admitted through the anti-entropy gate each heal, and its
    digest generations are MONOTONE across the whole run (the fence's
    global property: flapping cannot roll any key back)."""
    pod = SelfHealPod(dcf, n=3)
    bundles, gens = {}, {}
    try:
        for i in range(4):
            name = f"flap-key-{i}"
            bundles[name] = mk_bundle(dcf, rng)
            gens[name] = pod.router.register_key(name, bundles[name])
        # Cut the flapped key's REPLICA: its owner stays reachable, so
        # mid-cut re-registrations ack at the owner and the victim
        # converges through anti-entropy on every heal.  (Cutting the
        # OWNER would correctly fail the registration outright — no
        # ack without an owner.)
        victim = pod.map.replica(sorted(bundles)[0]).host_id
        stats = {"ok": 0, "mismatch": 0, "refused_hinted": 0,
                 "refused_unhinted": 0, "unaccounted": 0}
        lock, stop = threading.Lock(), threading.Event()
        threads = _soak_clients(pod, bundles, prg, stats, lock, stop)
        pod.router.start_health()
        for t in threads:
            t.start()
        # The mid-cut churn key is DEDICATED: the soak clients'
        # name list was snapshotted before it exists, so no client
        # ever oracles a key whose bundle the main thread is
        # swapping (that would race the test's own bookkeeping, not
        # the product).  Its owner stays reachable, its replica is
        # the flapped victim.
        midkey = next(
            f"flap-mid-{i}" for i in range(100000)
            if pod.map.placement(f"flap-mid-{i}", 1)[0]
            .host_id != victim
            and pod.map.placement(f"flap-mid-{i}", 1)[1]
            .host_id == victim)
        seen = {}
        try:
            for cycle in range(3):
                t0 = time.monotonic()
                cut = faults.partition({("router", victim)},
                                       clock=time.monotonic,
                                       window=(t0, t0 + 0.8))
                with faults.inject("net.partition", handler=cut):
                    # (Re-)register the churn key mid-cut: its
                    # generation climbs on the reachable side each
                    # cycle; the heal must converge it.
                    bundles[midkey] = mk_bundle(dcf, rng)
                    gens[midkey] = pod.router.register_key(
                        midkey, bundles[midkey])
                    time.sleep(1.0)
                deadline = time.monotonic() + 30
                while pod.router.health.state(victim) != UP:
                    assert time.monotonic() < deadline, \
                        (cycle, pod.router.health.states())
                    time.sleep(0.05)
                digest = pod.svc_of(victim).replication_digest()
                for k, g in digest.items():
                    assert g >= seen.get(k, 0), (cycle, k, g, seen)
                    seen[k] = g
        finally:
            stop.set()
            for t in threads:
                t.join(30)
        assert stats["mismatch"] == 0, stats
        assert stats["unaccounted"] == 0, stats
        assert stats["refused_unhinted"] == 0, stats
        assert stats["ok"] >= 3, stats
        # Post-flap: the churned key serves its NEWEST bits bit-exact
        # — including from the flapped replica's converged copy.
        assert pod.svc_of(victim).replication_digest()[midkey] \
            == gens[midkey]
        xs = rng.integers(0, 256, (8, NB), dtype=np.uint8)
        got = pod.router.evaluate(midkey, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(midkey, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, bundles[midkey],
                                                xs))
        snap = pod.router.metrics_snapshot()
        assert snap["router_anti_entropy_runs_total"] >= 3
    finally:
        pod.close()
