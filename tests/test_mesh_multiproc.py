"""Two-process device-mesh parity (ISSUE 18): the co-evaluate tentpole's
ground truth.

Spawns ``nproc=2`` real OS processes that rendezvous through
``parallel._compat.distributed_initialize`` (jax.distributed + gloo CPU
collectives), form ONE global pod mesh spanning both processes' devices,
and co-evaluate one batch: each process stages only its local point
slice, ``host_to_global`` concatenates the slices into the global sharded
batch, the walk runs as a pure map, and the two-party mismatch counter is
the end collective (a cross-process device psum that must read 0 on every
process).

The parent then gathers each process's locally-addressable share bytes
and pins them byte-identical against BOTH oracles computed single-process:
``eval_batch_np`` (host numpy) and ``ShardedLargeLambdaBackend`` (the
single-process sharded path the mesh backend subclasses) — the same
equivalence ``parallel/mesh_eval.py`` promises in its module contract.

Rides the serial CI leg (``mesh and slow``): two interpreter-mode JAX
processes on shared cores is not threaded-leg material.  Skips typed when
``jax.distributed`` cannot initialize in this container.
"""

import os
import random
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp

pytestmark = [pytest.mark.mesh, pytest.mark.slow]

REPO = Path(__file__).resolve().parents[1]

LAM = 64
NB2 = 2   # 16-bit domain
M = 70    # ragged: 35 local points per process, padded per shard

NPROC = 2
WORKER_TIMEOUT_S = 420


def material(k_num: int):
    """Deterministic key material + points, identical in every process
    (the SPMD contract: same bundle bytes everywhere, only the staged
    point slice differs per process)."""
    rng = random.Random(1804)
    ck = [bytes(rng.getrandbits(8) for _ in range(32)) for _ in range(18)]
    prg = HirosePrgNp(LAM, ck)
    nprng = np.random.default_rng(1805 + k_num)
    alphas = nprng.integers(0, 256, (k_num, NB2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, LAM), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(k_num, LAM, nprng),
                       spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (M, NB2), dtype=np.uint8)
    xs[0] = alphas[0]  # exercise the x == alpha boundary
    return ck, prg, alphas, betas, bundle, xs


# The worker half: written to disk by the parent, run once per process.
# argv: port nproc pid outdir k_num.  Exits 0 printing a typed marker if
# the distributed runtime is unavailable (parent skips), asserts the end
# collective reads zero, and leaves its local share bytes as .npy files.
WORKER = '''\
import os
import sys

port, nproc, pid, outdir, k_num = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
    int(sys.argv[5]))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # one real device per process

from dcf_tpu.errors import BackendUnavailableError
from dcf_tpu.parallel._compat import distributed_initialize

try:
    distributed_initialize("127.0.0.1:" + port, nproc, pid)
except BackendUnavailableError as e:
    print("DIST-INIT-UNAVAILABLE:", e, flush=True)
    sys.exit(0)

import numpy as np

from dcf_tpu.parallel import MeshLargeLambdaBackend, make_pod_mesh
from tests.test_mesh_multiproc import LAM, material

ck, prg, alphas, betas, bundle, xs = material(k_num)
mesh = make_pod_mesh()
be = {b: MeshLargeLambdaBackend(LAM, ck, mesh, interpret=True)
      for b in (0, 1)}
m_local = xs.shape[0] // nproc
xs_local = xs[pid * m_local:(pid + 1) * m_local]
ys = {}
staged = None
for b in (0, 1):
    be[b].put_bundle(bundle.for_party(b))
    if staged is None:
        staged = be[b].stage(xs_local)
    ys[b] = be[b].eval_staged(b, staged)
    local = be[b].staged_to_bytes(ys[b], staged["m"])
    np.save(os.path.join(outdir, "shares_K%d_b%d_p%d.npy"
                         % (k_num, b, pid)), local)
# The end collective: a device psum spanning every process's shard.
bad = int(be[0].points_mismatch_count(ys[0], ys[1], alphas, betas, staged))
assert bad == 0, "pid %d: %d mismatching (key, point) pairs" % (pid, bad)
print("PARITY-OK pid=%d" % pid, flush=True)
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(tmp_path: Path, k_num: int) -> list[str]:
    script = tmp_path / "mesh_worker.py"
    script.write_text(WORKER)
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(REPO) + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(NPROC), str(pid),
             str(tmp_path), str(k_num)],
            cwd=str(REPO), env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(NPROC)]
    outs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=WORKER_TIMEOUT_S)
            except subprocess.TimeoutExpired:
                pytest.fail(f"mesh worker hung past {WORKER_TIMEOUT_S}s "
                            "(a peer likely died before the collective)")
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any("DIST-INIT-UNAVAILABLE" in o for o in outs):
        pytest.skip("jax.distributed cannot initialize in this container: "
                    + "".join(outs)[:200])
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
        assert "PARITY-OK" in out, out
    return outs


@pytest.mark.parametrize("k_num", [1, 3])
def test_two_process_mesh_parity(tmp_path, k_num):
    """One batch, two OS processes, one mesh: the gathered shares are
    byte-identical to the numpy oracle AND the single-process sharded
    backend, both parties; the cross-process mismatch psum read 0 in
    every worker (asserted worker-side before this parent check)."""
    _run_workers(tmp_path, k_num)

    import jax

    from dcf_tpu.parallel import ShardedLargeLambdaBackend, make_mesh

    ck, prg, alphas, betas, bundle, xs = material(k_num)
    sp_mesh = make_mesh(shape=(1, len(jax.devices())))
    for b in (0, 1):
        parts = [np.load(tmp_path / f"shares_K{k_num}_b{b}_p{pid}.npy")
                 for pid in range(NPROC)]
        assert all(p.shape == (k_num, M // NPROC, LAM) for p in parts), \
            [p.shape for p in parts]
        got = np.concatenate(parts, axis=1)  # process order = points order
        want_np = eval_batch_np(prg, b, bundle.for_party(b), xs)
        assert np.array_equal(got, want_np), f"party {b} vs numpy oracle"
        sp = ShardedLargeLambdaBackend(LAM, ck, sp_mesh, interpret=True)
        sp.put_bundle(bundle.for_party(b))
        staged = sp.stage(xs)
        want_sp = sp.staged_to_bytes(sp.eval_staged(b, staged), staged["m"])
        assert np.array_equal(got, want_sp), \
            f"party {b} vs single-process sharded backend"
