"""Parity: C++ native core vs the numpy layer (and hence the spec).

Covers both compiled paths — AES-NI and the portable S-box fallback — and
both the serial and threaded eval, mirroring the reference's CI feature
matrix (multithread on/off, SURVEY.md §4).
"""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.native import NativeDcf
from dcf_tpu.ops.prg import HirosePrgNp


def rand_bytes(rng: random.Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


@pytest.fixture(scope="module", params=[False, True], ids=["aesni", "portable"])
def native(request):
    rng = random.Random(41)
    keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    return keys, NativeDcf(16, keys, portable=request.param)


def test_native_prg_matches_np(native):
    keys, d = native
    prg_np = HirosePrgNp(16, keys)
    seeds = np.random.default_rng(1).integers(0, 256, (13, 16), dtype=np.uint8)
    got = d.prg_gen(seeds)
    want = prg_np.gen(seeds)
    for g, w in zip(got, (want.s_l, want.v_l, want.t_l, want.s_r, want.v_r, want.t_r)):
        assert np.array_equal(g, w)


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_native_gen_matches_np(native, bound):
    keys, d = native
    prg_np = HirosePrgNp(16, keys)
    nprng = np.random.default_rng(2)
    alphas = nprng.integers(0, 256, (5, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (5, 16), dtype=np.uint8)
    s0s = random_s0s(5, 16, nprng)
    want = gen_batch(prg_np, alphas, betas, s0s, bound)
    got = d.gen_batch(alphas, betas, s0s, bound)
    for name in ("s0s", "cw_s", "cw_v", "cw_t", "cw_np1"):
        assert np.array_equal(getattr(got, name), getattr(want, name)), name


@pytest.mark.parametrize("threads", [1, 4])
def test_native_eval_matches_np(native, threads):
    keys, d = native
    prg_np = HirosePrgNp(16, keys)
    nprng = np.random.default_rng(3)
    alphas = nprng.integers(0, 256, (3, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (3, 16), dtype=np.uint8)
    bundle = d.gen_batch(alphas, betas, random_s0s(3, 16, nprng), spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (17, 2), dtype=np.uint8)
    xs[:3] = alphas
    for b in (0, 1):
        want = eval_batch_np(prg_np, b, bundle.for_party(b), xs)
        got = d.eval(b, bundle.for_party(b), xs, num_threads=threads)
        assert np.array_equal(got, want)
    # per-key xs layout
    xs3 = nprng.integers(0, 256, (3, 6, 2), dtype=np.uint8)
    for b in (0, 1):
        want = eval_batch_np(prg_np, b, bundle.for_party(b), xs3)
        got = d.eval(b, bundle.for_party(b), xs3, num_threads=threads)
        assert np.array_equal(got, want)


def test_native_large_lambda(native):
    # lam=144: both cipher indices (0, 17) exercised.
    rng = random.Random(42)
    lam = 144
    keys = [rand_bytes(rng, 32) for _ in range(18)]
    use_portable = not native[1].has_aesni
    d = NativeDcf(lam, keys, portable=use_portable)
    prg_np = HirosePrgNp(lam, keys)
    seeds = np.random.default_rng(4).integers(0, 256, (5, lam), dtype=np.uint8)
    got = d.prg_gen(seeds)
    want = prg_np.gen(seeds)
    for g, w in zip(got, (want.s_l, want.v_l, want.t_l, want.s_r, want.v_r, want.t_r)):
        assert np.array_equal(g, w)


def test_native_bad_config():
    rng = random.Random(43)
    with pytest.raises(ValueError):
        NativeDcf(32, [rand_bytes(rng, 32)] * 4)  # key-count contract violation
