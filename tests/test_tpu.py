"""On-hardware lane: the COMPILED Mosaic kernels vs the numpy oracle.

The CPU lane runs every Pallas program under the interpreter, which (for
the walk kernels) swaps in the compact v1 cipher graph — so the code that
produces every headline number (the v3-cipher Mosaic artifacts) is
otherwise untested.  This lane runs all four compiled kernels (walk,
keylanes, tree, narrow) plus DeviceKeyGen and the sharded wrappers on the
real chip against the same oracle, matching the reference's
tested-hot-path discipline (its tests run the real AES via ``-F prg``,
/root/reference/src/lib.rs:351-443).

Run with::

    DCF_TPU_TESTS=1 python -m pytest -m tpu -q

(bench.py runs this lane automatically and records the result.)
"""

import os
import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp


def _on_tpu() -> bool:
    if os.environ.get("DCF_TPU_TESTS") != "1":
        return False
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(
        not _on_tpu(),
        reason="on-hardware lane: set DCF_TPU_TESTS=1 on a TPU host"),
]


def rand_bytes(rng: random.Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


def _workload(seed: int, k_num: int, n_bytes: int, m: int,
              bound=spec.Bound.LT_BETA, lam: int = 16):
    rng = random.Random(seed)
    ck = [rand_bytes(rng, 32) for _ in range(max(2, 2 * (lam // 16)))]
    prg = HirosePrgNp(lam, ck)
    nprng = np.random.default_rng(seed)
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, lam), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(k_num, lam, nprng),
                       bound)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs[: min(k_num, m)] = alphas[: min(k_num, m), :]  # exact-alpha points
    return ck, prg, alphas, betas, bundle, xs


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_walk_kernel_compiled(bound):
    """The flagship walk kernel at full shipping depth (n=128): 3 keys,
    ragged 37-point batch (lane padding), both parties, vs the oracle."""
    from dcf_tpu.backends.pallas_backend import PallasBackend

    ck, prg, _a, _b, bundle, xs = _workload(70, 3, 16, 37, bound)
    be = PallasBackend(16, ck)
    assert not be.interpret
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = be.eval(b, xs, bundle=kb)
        want = eval_batch_np(prg, b, kb, xs)
        assert np.array_equal(got, want), f"party {b} {bound}"


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_prefix_kernel_compiled(bound):
    """The prefix-shared evaluator end to end on hardware: compiled tree
    frontier (k=12), t-stash in the masked plane, per-point gather,
    in-kernel butterfly transpose, and the 116 remaining walked levels —
    bit-exact vs the oracle at full n=128 depth, ragged 37-point batch,
    both parties and bounds, plus the staged device counter."""
    from dcf_tpu.backends.pallas_prefix import PrefixPallasBackend

    ck, prg, alphas, betas, bundle, xs = _workload(82, 1, 16, 37, bound)
    be = PrefixPallasBackend(16, ck, prefix_levels=12)
    assert not be.interpret
    be.put_bundle(bundle.for_party(0))
    be1 = PrefixPallasBackend(16, ck, prefix_levels=12)
    be1.put_bundle(bundle.for_party(1))
    staged = be.stage(xs)
    ys = {}
    for b, bk in ((0, be), (1, be1)):
        y = bk.eval_staged(b, staged)
        ys[b] = y
        got = bk.staged_to_bytes(y, staged["m"])
        want = eval_batch_np(prg, b, bundle.for_party(b), xs)
        assert np.array_equal(got, want), f"party {b} {bound}"
    assert int(be.points_mismatch_count(
        ys[0], ys[1], alphas[0].tobytes(), betas[0].tobytes(), staged,
        gt=bound is spec.Bound.GT_BETA)) == 0


def test_walk_kernel_compiled_multi_tile():
    """Multi-tile grid + per-key points at the 128-word Mosaic tiling
    granule (smaller tiles only exist under the interpreter): 8200 ragged
    points -> three 128-word tiles per key."""
    from dcf_tpu.backends.pallas_backend import PallasBackend

    ck, prg, _a, _b, bundle, xs = _workload(71, 2, 2, 0)
    nprng = np.random.default_rng(71)
    xs3 = nprng.integers(0, 256, (2, 8200, 2), dtype=np.uint8)
    be = PallasBackend(16, ck)
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = be.eval(b, xs3, bundle=kb)
        want = eval_batch_np(prg, b, kb, xs3)
        assert np.array_equal(got, want), f"party {b}"


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_keylanes_kernel_compiled(bound):
    """The many-keys kernel: ragged key count (40), odd point count (24),
    both parties, BOTH bounds (the reference tests them as peers,
    src/lib.rs:372-420), plus the on-device relu mismatch counter (whose
    semantics are the LT comparison)."""
    from dcf_tpu.backends.pallas_keylanes import KeyLanesPallasBackend

    ck, prg, alphas, betas, bundle, xs = _workload(72, 40, 2, 24, bound)
    be = KeyLanesPallasBackend(16, ck, level_chunk=4)
    assert not be.interpret
    be.put_bundle(bundle)
    staged = be.stage(xs)
    ys = {}
    for b in (0, 1):
        y = be.eval_staged(b, staged)
        ys[b] = y
        got = be.staged_to_bytes(y, staged["m"])
        want = eval_batch_np(prg, b, bundle.for_party(b), xs)
        assert np.array_equal(got, want), f"party {b} {bound}"
    if bound is spec.Bound.LT_BETA:
        assert int(be.relu_mismatch_count(
            ys[0], ys[1], alphas, betas, xs)) == 0


@pytest.mark.parametrize("gt", [False, True])
def test_tree_fulldomain_compiled(gt):
    """The GGM tree expand kernel over the whole 2^16 domain, on-device
    two-party reconstruction vs the plain comparison."""
    from dcf_tpu.backends.fulldomain import TreeFullDomain

    bound = spec.Bound.GT_BETA if gt else spec.Bound.LT_BETA
    ck, prg, alphas, betas, bundle, _xs = _workload(73, 1, 2, 1, bound)
    fd = TreeFullDomain(16, ck)
    assert not fd.interpret
    alpha = int.from_bytes(alphas[0].tobytes(), "big")
    assert fd.check(bundle, alpha, betas[0].tobytes(), 16, gt=gt) == 0


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_narrow_kernel_compiled(bound):
    """The large-lambda hybrid's Pallas narrow walk (lane-dependent round
    keys) at lam=144, both parties, BOTH bounds, vs the full-width oracle
    — K=3 keys (the kernel grids over keys; the wide part is a batched
    MXU matmul)."""
    from dcf_tpu.backends.large_lambda import LargeLambdaBackend

    ck, prg, _a, _b, bundle, xs = _workload(74, 3, 2, 9, bound, lam=144)
    be = LargeLambdaBackend(144, ck, narrow="pallas")
    assert not be.interpret
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = be.eval(b, xs, bundle=kb)
        want = eval_batch_np(prg, b, kb, xs)
        assert np.array_equal(got, want), f"party {b} {bound}"


def test_hybrid_multikey_lam16384_compiled():
    """The multi-key large-lambda regime on hardware: K=32 keys at
    lam=16384 (the reference bench's literal range,
    benches/dcf_large_lambda.rs:8-43) through the hybrid's gridded narrow
    walk + batched MXU wide part.  Oracle = the C++ core (the numpy PRG
    at 2048 ciphers x 32 keys would take minutes)."""
    import random as _random

    from dcf_tpu.backends.large_lambda import LargeLambdaBackend
    from dcf_tpu.native import NativeDcf

    lam, k_num, m = 16384, 32, 64
    rng = _random.Random(77)
    ck = [rand_bytes(rng, 32) for _ in range(2 * (lam // 16))]
    native = NativeDcf(lam, ck)
    nprng = np.random.default_rng(77)
    alphas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, lam), dtype=np.uint8)
    bundle = native.gen_batch(alphas, betas, random_s0s(k_num, lam, nprng),
                              spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, 16), dtype=np.uint8)
    xs[:k_num] = alphas[:, :]  # exact-alpha points
    be = LargeLambdaBackend(lam, ck)
    assert not be.interpret
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = be.eval(b, xs, bundle=kb)
        want = native.eval(b, bundle, xs)
        assert np.array_equal(got, want), f"party {b}"


def test_hybrid_lam128_compiled():
    """The extension band on hardware: lam=128 (the BASELINE headline's
    lam reading, reference-inexecutable — 16-key contract cannot cover
    cipher index 17) through the compiled hybrid, K=2 keys, both
    parties, C++ anchor + full on-device two-party reconstruction via
    the staged counter; then the same workload through the compiled
    PREFIX-shared hybrid (frontier state walk + 16-column gather +
    remaining-level walk), which must agree bit-for-bit."""
    import warnings as _warnings

    from dcf_tpu.backends.large_lambda import LargeLambdaBackend
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.spec import ReferenceContractWarning

    lam, k_num, m = 128, 2, 96
    rng = random.Random(84)
    ck = [rand_bytes(rng, 32) for _ in range(18)]  # index 17 needed
    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore", ReferenceContractWarning)
        native = NativeDcf(lam, ck)
    nprng = np.random.default_rng(84)
    alphas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, lam), dtype=np.uint8)
    bundle = native.gen_batch(alphas, betas, random_s0s(k_num, lam, nprng),
                              spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, 16), dtype=np.uint8)
    xs[:k_num] = alphas[:, :]  # exact-alpha points
    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore", ReferenceContractWarning)
        be0 = LargeLambdaBackend(lam, ck)
        be1 = LargeLambdaBackend(lam, ck)
        bep = {b: LargeLambdaBackend(lam, ck, prefix_levels=12)
               for b in (0, 1)}
    assert not be0.interpret
    be0.put_bundle(bundle.for_party(0))
    be1.put_bundle(bundle.for_party(1))
    staged = be0.stage(xs)
    ys = {0: be0.eval_staged(0, staged), 1: be1.eval_staged(1, staged)}
    for b, bk in ((0, be0), (1, be1)):
        got = bk.staged_to_bytes(ys[b], staged["m"])
        want = native.eval(b, bundle, xs)
        assert np.array_equal(got, want), f"party {b}"
    # Full on-device two-party reconstruction (device parity, not just
    # the host anchor).
    assert int(be0.points_mismatch_count(
        ys[0], ys[1], alphas, betas, staged)) == 0
    # The compiled prefix-shared hybrid agrees bit-for-bit.
    for b in (0, 1):
        bep[b].put_bundle(bundle.for_party(b))
    staged_p = bep[0].stage(xs)
    ysp = {b: bep[b].eval_staged(b, staged_p) for b in (0, 1)}
    for b in (0, 1):
        assert np.array_equal(
            bep[b].staged_to_bytes(ysp[b], staged_p["m"]),
            bep[b].staged_to_bytes(ys[b], staged["m"])), f"party {b}"
    assert int(bep[0].points_mismatch_count(
        ysp[0], ysp[1], alphas, betas, staged_p)) == 0


def test_device_gen_matches_host():
    """On-device keygen produces a bit-identical bundle to the host gen."""
    from dcf_tpu.backends.device_gen import DeviceKeyGen

    ck, prg, alphas, betas, bundle, _xs = _workload(75, 32, 2, 1)
    nprng = np.random.default_rng(75)
    # Same s0s the host bundle was generated with.
    s0s = bundle.s0s
    gen = DeviceKeyGen(16, ck)
    dev = gen.gen(alphas, betas, s0s, spec.Bound.LT_BETA)
    got = gen.to_host_bundle(dev)
    for field in ("s0s", "cw_s", "cw_v", "cw_t", "cw_np1"):
        assert np.array_equal(getattr(got, field), getattr(bundle, field)), \
            field
    del nprng


def test_sharded_pallas_1chip_mesh_compiled():
    """The shard_map-wrapped walk kernel compiles and matches the oracle
    on a real 1-device TPU mesh (the multi-chip plumbing proof)."""
    from dcf_tpu.parallel import ShardedPallasBackend, make_mesh

    ck, prg, _a, _b, bundle, xs = _workload(76, 2, 2, 45)
    mesh = make_mesh(shape=(1, 1))
    be = ShardedPallasBackend(16, ck, mesh)
    assert not be.interpret
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = be.eval(b, xs, bundle=kb)
        want = eval_batch_np(prg, b, kb, xs)
        assert np.array_equal(got, want), f"party {b}"


def test_sharded_prefix_1chip_mesh_compiled():
    """The shard_map-wrapped prefix evaluator on a real 1-device TPU mesh
    (compiled tree frontier + gather + walk), vs the oracle."""
    from dcf_tpu.parallel import ShardedPrefixBackend, make_mesh

    ck, prg, alphas, betas, bundle, xs = _workload(83, 1, 16, 37)
    mesh = make_mesh(shape=(1, 1))
    ys = {}
    staged = None
    for b in (0, 1):
        be = ShardedPrefixBackend(16, ck, mesh, prefix_levels=12)
        assert not be.interpret
        be.put_bundle(bundle.for_party(b))
        if staged is None:
            staged = be.stage(xs)
            be0 = be
        y = be.eval_staged(b, staged)
        ys[b] = y
        got = be.staged_to_bytes(y, staged["m"])
        want = eval_batch_np(prg, b, bundle.for_party(b), xs)
        assert np.array_equal(got, want), f"party {b}"
    assert int(be0.points_mismatch_count(
        ys[0], ys[1], alphas[0].tobytes(), betas[0].tobytes(),
        staged)) == 0


def test_sharded_keylanes_1chip_mesh_compiled():
    """The shard_map-wrapped keylanes kernel on a real 1-device TPU mesh
    (the config-5 pod path's compiled-plumbing proof), incl. the
    on-device relu counter through the sharded output layout."""
    from dcf_tpu.parallel import ShardedKeyLanesBackend, make_mesh

    ck, prg, alphas, betas, bundle, xs = _workload(78, 40, 2, 24)
    mesh = make_mesh(shape=(1, 1))
    be = ShardedKeyLanesBackend(16, ck, mesh, level_chunk=4)
    assert not be.interpret
    be.put_bundle(bundle)
    staged = be.stage(xs)
    ys = {}
    for b in (0, 1):
        y = be.eval_staged(b, staged)
        ys[b] = y
        got = be.staged_to_bytes(y, staged["m"])
        want = eval_batch_np(prg, b, bundle.for_party(b), xs)
        assert np.array_equal(got, want), f"party {b}"
    assert int(be.relu_mismatch_count(ys[0], ys[1], alphas, betas, xs)) == 0


def test_sharded_tree_1chip_mesh_compiled():
    """The shard_map-wrapped tree expand kernel + in-shard verification
    on a real 1-device TPU mesh, both bounds, with a negative control."""
    from dcf_tpu.parallel import ShardedTreeFullDomain, make_mesh

    n_bits = 16
    ck, prg, alphas, betas, bundle, _xs = _workload(80, 1, 2, 1)
    fd = ShardedTreeFullDomain(16, ck, make_mesh(shape=(1, 1)))
    assert not fd.interpret
    alpha = int.from_bytes(alphas[0].tobytes(), "big")
    beta = betas[0].tobytes()
    assert fd.check(bundle, alpha, beta, n_bits) == 0
    wrong = bytes(b ^ 1 for b in beta)
    assert fd.check(bundle, alpha, wrong, n_bits) == alpha


def test_sharded_hybrid_1chip_mesh_compiled():
    """The large-lambda hybrid under shard_map on a real 1-device TPU
    mesh (compiled narrow Mosaic walk + per-shard MXU wide matmul)."""
    from dcf_tpu.parallel import ShardedLargeLambdaBackend, make_mesh

    ck, prg, _a, _b, bundle, xs = _workload(81, 2, 2, 9, lam=144)
    mesh = make_mesh(shape=(1, 1))
    be = ShardedLargeLambdaBackend(144, ck, mesh)
    assert not be.interpret
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = be.eval(b, xs, bundle=kb)
        want = eval_batch_np(prg, b, kb, xs)
        assert np.array_equal(got, want), f"party {b}"


def test_mxu_linear_cipher_compiled():
    """The MXU-linear cipher formulation (benchmarks/micro_mxu.py, the
    round-4 pricing probe) is bit-identical to the shipped v3 cipher AS
    COMPILED Mosaic programs — whatever the pricing verdict, the probe
    must measure a correct program."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from functools import partial

    from benchmarks.micro_mxu import _cipher_kernel, linear_layer_matrices
    from dcf_tpu.ops.aes_bitsliced import round_key_masks_bitmajor

    m, m_final = linear_layer_matrices()
    rk = jnp.asarray(round_key_masks_bitmajor(bytes(range(32))))
    m_bf = jnp.asarray(m, jnp.bfloat16)
    mf_bf = jnp.asarray(m_final, jnp.bfloat16)
    nprng = np.random.default_rng(79)
    st = jnp.asarray(nprng.integers(-(2 ** 31), 2 ** 31, (128, 128),
                                    dtype=np.int64).astype(np.int32))
    out = jax.ShapeDtypeStruct((128, 128), jnp.int32)
    ys = {}
    for variant in ("v3", "mxu"):
        f = jax.jit(lambda *a, v=variant: pl.pallas_call(
            partial(_cipher_kernel, iters=3, variant=v), out_shape=out)(*a))
        ys[variant] = np.asarray(f(rk, m_bf, mf_bf, st))
    assert np.array_equal(ys["v3"], ys["mxu"])
