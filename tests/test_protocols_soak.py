"""Slow serve-MIC soak: sustained closed-loop protocol load with
intermittent ``protocols.combine`` fault injection (ISSUE 5 CI
satellite).

Serial-CI-leg material (``-m "protocols and slow"``): seconds of
threaded closed-loop load against a registered MIC protocol key while
the combine seam fails intermittently.  The service must stay up,
complete or typed-fail every request, keep the queue drained, and still
serve bit-exact combined [m, M, lam] shares afterwards.
"""

import numpy as np
import pytest

from dcf_tpu import Dcf
from dcf_tpu.protocols import mic_oracle
from dcf_tpu.serve.loadgen import closed_loop
from dcf_tpu.testing import faults

pytestmark = [pytest.mark.protocols, pytest.mark.slow]

NB, LAM = 2, 16
N = 1 << 16


def test_serve_mic_soak_under_combine_faults():
    rng = np.random.default_rng(0x50AD)
    ck = [rng.bytes(32), rng.bytes(32)]
    dcf = Dcf(NB, LAM, ck, backend="bitsliced")
    svc = dcf.serve(max_batch=64, max_delay_ms=2.0, retries=1,
                    max_queued_points=4096)
    intervals = [(10, 200), (300, 1000), (5000, 2000), (0, N),
                 (7, 7), (40000, 50000), (60000, 61000), (65000, N)]
    betas = rng.integers(0, 256, (8, LAM), dtype=np.uint8)
    pb = dcf.mic(intervals, betas, rng=rng)
    svc.register_key("mic-soak", pb)

    calls = {"n": 0}

    def every_ninth(*_args):
        calls["n"] += 1
        if calls["n"] % 9 == 0:
            raise faults.InjectedFault("intermittent combine failure")

    with svc:
        # Warm the padded-shape ladder before the timed soak (same
        # reasoning as the plain serve soak: a compile inside the
        # window starves the batch count the assertions rely on).
        m = 1
        while m <= 64:
            svc.evaluate("mic-soak",
                         rng.integers(0, 256, (m, NB), dtype=np.uint8),
                         timeout=180)
            m *= 2
        with faults.inject("protocols.combine", handler=every_ninth):
            res = closed_loop(
                svc, ["mic-soak"], duration_s=5.0, concurrency=3,
                min_points=1, max_points=48, seed=11)
            rounds = 1
            while calls["n"] < 9 and rounds < 4:
                more = closed_loop(
                    svc, ["mic-soak"], duration_s=5.0, concurrency=3,
                    min_points=1, max_points=48, seed=11 + rounds)
                res.requests_ok += more.requests_ok
                res.points_ok += more.points_ok
                res.requests_failed += more.requests_failed
                res.requests_shed += more.requests_shed
                rounds += 1
        # post-soak, faults disarmed: combined shares still bit-exact
        xs = rng.integers(0, 256, (9, NB), dtype=np.uint8)
        y0 = svc.evaluate("mic-soak", xs, b=0, timeout=60)
        y1 = svc.evaluate("mic-soak", xs, b=1, timeout=60)
        assert y0.shape == (8, 9, LAM)
        assert np.array_equal(y0 ^ y1, mic_oracle(xs, intervals, betas))

    assert res.requests_ok > 0
    assert res.points_ok > 0
    snap = svc.metrics_snapshot()
    assert snap["serve_queue_depth"] == 0
    assert snap["serve_queue_points"] == 0
    assert snap["serve_retries_total"] >= 1
    assert calls["n"] >= 9  # the combine fault really fired mid-soak
