"""The MXU-linear cipher formulation (benchmarks/micro_mxu.py) is
bit-identical to the shipped bitsliced cipher.

The probe prices the AES linear layer as a GF(2) matmul on the MXU
(ROOFLINE.md round-4 lever); whatever the pricing verdict, the
formulation itself must be exact — bf16 x bf16 -> f32 products of 0/1
with row sums <= 128 are inside bf16's exact-integer range."""

import numpy as np

from benchmarks.micro_mxu import aes256_mxu_linear, linear_layer_matrices
from dcf_tpu.ops.aes_bitsliced import (
    aes256_encrypt_planes_bitmajor,
    round_key_masks_bitmajor,
)


def test_linear_matrices_are_gf2():
    m, m_final = linear_layer_matrices()
    assert m.shape == (128, 128) and m_final.shape == (128, 128)
    assert set(np.unique(m)) <= {0, 1}
    assert set(np.unique(m_final)) <= {0, 1}
    # ShiftRows is a permutation: exactly one 1 per row/column.
    assert (m_final.sum(axis=0) == 1).all()
    assert (m_final.sum(axis=1) == 1).all()
    # MixColumns∘ShiftRows is invertible: full GF(2) rank.
    r = m.copy()
    rank = 0
    for col in range(128):
        rows = np.nonzero(r[rank:, col])[0]
        if not len(rows):
            continue
        pivot = rank + rows[0]
        r[[rank, pivot]] = r[[pivot, rank]]
        elim = np.nonzero(r[:, col])[0]
        for i in elim:
            if i != rank:
                r[i] ^= r[rank]
        rank += 1
    assert rank == 128


def test_mxu_cipher_matches_bitsliced():
    import jax.numpy as jnp

    m, m_final = linear_layer_matrices()
    rk = round_key_masks_bitmajor(bytes(range(7, 39)))
    rng = np.random.default_rng(42)
    st = rng.integers(-(2 ** 31), 2 ** 31, (128, 8), dtype=np.int64
                      ).astype(np.int32)
    want = aes256_encrypt_planes_bitmajor(
        np, rk.view(np.uint32), st.view(np.uint32), np.uint32(0xFFFFFFFF))
    got = np.asarray(aes256_mxu_linear(
        jnp.asarray(rk), jnp.asarray(st), jnp.asarray(m, jnp.bfloat16),
        jnp.asarray(m_final, jnp.bfloat16)))
    assert np.array_equal(got.view(np.uint32), want)
