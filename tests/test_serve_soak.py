"""Slow end-to-end serve soak: sustained concurrent load with
intermittent fault injection against the worker thread.

Serial-CI-leg material (``-m "serve and slow"``): several seconds of
closed-loop load from multiple client threads, with the ``serve.eval``
seam failing intermittently the whole time.  The service must stay up,
complete or typed-fail every request, keep its queue drained, and still
serve bit-exactly afterwards.
"""

import numpy as np
import pytest

from dcf_tpu import Dcf
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.serve.loadgen import closed_loop
from dcf_tpu.testing import faults

# lockwatch: the soak runs on the serial CI leg with the lock-order
# watchdog armed, so every lock order the service takes under load is
# continuously proven acyclic (inversions raise LockOrderError instead
# of deadlocking once in a thousand runs).
pytestmark = [pytest.mark.serve, pytest.mark.slow, pytest.mark.lockwatch]

NB, LAM = 2, 16


def test_soak_under_intermittent_faults():
    rng = np.random.default_rng(0x50AC)
    ck = [rng.bytes(32), rng.bytes(32)]
    dcf = Dcf(NB, LAM, ck, backend="bitsliced")
    svc = dcf.serve(max_batch=64, max_delay_ms=2.0, retries=1,
                    max_queued_points=4096)
    bundles = {}
    for name in ("s0k", "s1k", "s2k"):
        alphas = rng.integers(0, 256, (1, NB), dtype=np.uint8)
        betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
        bundles[name] = dcf.gen(alphas, betas, rng=rng)
        svc.register_key(name, bundles[name])

    calls = {"n": 0}

    def every_ninth(*_args):
        calls["n"] += 1
        if calls["n"] % 9 == 0:
            raise faults.InjectedFault("intermittent eval failure")

    with svc:
        # Warm the whole padded-shape ladder before the timed soak: the
        # generator's ragged sizes (1..48, max_batch 64) can land
        # batches on any power of two up to 64, and an XLA compile
        # inside the 5s window would starve the batch count the
        # fault-rate assertions below rely on.
        m = 1
        while m <= 64:
            svc.evaluate("s0k",
                         rng.integers(0, 256, (m, NB), dtype=np.uint8),
                         timeout=180)
            m *= 2
        with faults.inject("serve.eval", handler=every_ninth):
            res = closed_loop(
                svc, list(bundles), duration_s=5.0, concurrency=3,
                min_points=1, max_points=48, seed=7)
            rounds = 1
            while calls["n"] < 9 and rounds < 4:
                # A heavily contended CI host can fit few batches in 5s;
                # keep soaking (bounded) until the fault really fired.
                more = closed_loop(
                    svc, list(bundles), duration_s=5.0, concurrency=3,
                    min_points=1, max_points=48, seed=7 + rounds)
                res.requests_ok += more.requests_ok
                res.points_ok += more.points_ok
                res.requests_failed += more.requests_failed
                res.requests_shed += more.requests_shed
                rounds += 1
        # post-soak, faults disarmed: parity is still bit-exact
        prg = HirosePrgNp(LAM, ck)
        xs = rng.integers(0, 256, (9, NB), dtype=np.uint8)
        y0 = svc.evaluate("s1k", xs, b=0, timeout=60)
        y1 = svc.evaluate("s1k", xs, b=1, timeout=60)
        want = eval_batch_np(prg, 0, bundles["s1k"].for_party(0), xs) ^ \
            eval_batch_np(prg, 1, bundles["s1k"].for_party(1), xs)
        assert np.array_equal(y0 ^ y1, want)

    assert res.requests_ok > 0
    assert res.points_ok > 0
    # every client interaction was accounted: ok, shed, or typed-failed
    snap = svc.metrics_snapshot()
    assert snap["serve_queue_depth"] == 0
    assert snap["serve_queue_points"] == 0
    # with retries=1, most intermittent failures recover; the retry
    # counter must show the harness actually exercised the path
    assert snap["serve_retries_total"] >= 1
    assert calls["n"] >= 9  # the fault really fired during the soak
