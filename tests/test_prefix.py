"""Prefix-shared eval (frontier gather + remaining-level walk) parity.

The top-k tree expansion, the per-point frontier gather with the t-bit
stashed in the masked plane, the in-kernel bit transpose, and the
remaining-level walk must compose to EXACTLY the from-root walk —
bit-for-bit against the numpy oracle, both parties, both bounds.
"""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp


def rand_bytes(rng: random.Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_prefix_pallas_matches_numpy(bound):
    from dcf_tpu.backends.pallas_prefix import PrefixPallasBackend

    rng = random.Random(51)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(15)
    n_bytes, m = 2, 37  # ragged m exercises tile padding through the gather
    alphas = nprng.integers(0, 256, (1, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (1, 16), dtype=np.uint8)
    bundle = gen_batch(prg_np, alphas, betas, random_s0s(1, 16, nprng),
                       bound)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs[0] = alphas[0]  # boundary point
    xs[1] = 0
    xs[2] = 255

    be = PrefixPallasBackend(16, cipher_keys, interpret=True, tile_words=2)
    assert be._bundle_dev is None
    ys = {}
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = be.eval(b, xs, bundle=kb)
        want = eval_batch_np(prg_np, b, kb, xs)
        assert np.array_equal(got, want), f"party {b} {bound}"
        ys[b] = got
    recon = ys[0] ^ ys[1]
    a = alphas[0].tobytes()
    for j in range(m):
        x = xs[j].tobytes()
        hit = x < a if bound is spec.Bound.LT_BETA else x > a
        want = betas[0].tobytes() if hit else bytes(16)
        assert recon[0, j].tobytes() == want


def test_prefix_staged_roundtrip_and_counter():
    """Staged path: frontier cached per party (one tree expansion each),
    device mismatch counter zero on clean shares and nonzero under a
    corrupted beta expectation (negative control)."""
    from dcf_tpu.backends.pallas_prefix import PrefixPallasBackend

    rng = random.Random(52)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(16)
    n_bytes, m = 2, 64
    alpha = nprng.integers(0, 256, (1, n_bytes), dtype=np.uint8)
    beta = nprng.integers(0, 256, (1, 16), dtype=np.uint8)
    bundle = gen_batch(prg_np, alpha, beta, random_s0s(1, 16, nprng),
                       spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)

    be = PrefixPallasBackend(16, cipher_keys, interpret=True, tile_words=2)
    be.put_bundle(bundle.for_party(0))
    be1 = PrefixPallasBackend(16, cipher_keys, interpret=True, tile_words=2)
    be1.put_bundle(bundle.for_party(1))
    staged = be.stage(xs)
    y0 = be.eval_staged(0, staged)
    y1 = be1.eval_staged(1, staged)
    # Frontier built once per party and reused on the second eval.
    t0 = be._frontier[0]
    y0b = be.eval_staged(0, staged)
    assert be._frontier[0] is t0
    assert np.array_equal(np.asarray(y0), np.asarray(y0b))
    assert int(be.points_mismatch_count(
        y0, y1, alpha[0].tobytes(), beta[0].tobytes(), staged)) == 0
    wrong = bytes(b ^ 1 for b in beta[0].tobytes())
    n_inside = sum(xs[j].tobytes() < alpha[0].tobytes() for j in range(m))
    got = int(be.points_mismatch_count(
        y0, y1, alpha[0].tobytes(), wrong, staged))
    assert got == n_inside  # exactly the points inside the bound flip
    # Bytes out match the from-root backend's conversion contract.
    yb = be.staged_to_bytes(y0, staged["m"])
    want = eval_batch_np(prg_np, 0, bundle.for_party(0), xs)
    assert np.array_equal(yb, want)


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_prefix_multikey_matches_numpy(bound):
    """K=3 keys over shared points: per-key frontiers stacked, shared
    prefix indices offset per key, one flat gather — bit-exact vs the
    oracle for every key."""
    from dcf_tpu.backends.pallas_prefix import PrefixPallasBackend

    rng = random.Random(54)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(19)
    # m = 32 exactly fills one lane word: the wrong-beta control below
    # counts every point, and pad points (genuine x=0 evals) would
    # otherwise land inside an LT bound and pollute the expected count.
    k_num, n_bytes, m = 3, 2, 32
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(prg_np, alphas, betas,
                       random_s0s(k_num, 16, nprng), bound)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs[0] = alphas[0]

    be = PrefixPallasBackend(16, cipher_keys, interpret=True, tile_words=2)
    be1 = PrefixPallasBackend(16, cipher_keys, interpret=True,
                              tile_words=2)
    be.put_bundle(bundle.for_party(0))
    be1.put_bundle(bundle.for_party(1))
    staged = be.stage(xs)
    ys_dev = {0: be.eval_staged(0, staged), 1: be1.eval_staged(1, staged)}
    ys = {}
    for b, bk in ((0, be), (1, be1)):
        got = bk.staged_to_bytes(ys_dev[b], staged["m"])
        want = eval_batch_np(prg_np, b, bundle.for_party(b), xs)
        assert np.array_equal(got, want), f"party {b} {bound}"
        ys[b] = got
    recon = ys[0] ^ ys[1]
    for i in range(k_num):
        a = alphas[i].tobytes()
        for j in range(m):
            x = xs[j].tobytes()
            hit = x < a if bound is spec.Bound.LT_BETA else x > a
            want_y = betas[i].tobytes() if hit else bytes(16)
            assert recon[i, j].tobytes() == want_y
    # The MULTI-KEY device counter (per-key alphas as data): zero on
    # clean shares, and the exact per-key inside-count on a wrong beta.
    gt = bound is spec.Bound.GT_BETA
    assert int(be.points_mismatch_count(
        ys_dev[0], ys_dev[1], alphas, betas, staged, gt=gt)) == 0
    wrong = betas ^ np.uint8(1)
    n_inside = sum(
        (xs[j].tobytes() < alphas[i].tobytes()) != gt and
        xs[j].tobytes() != alphas[i].tobytes()
        for i in range(k_num) for j in range(m))
    got_mism = int(be.points_mismatch_count(
        ys_dev[0], ys_dev[1], alphas, wrong, staged, gt=gt))
    assert got_mism == n_inside


def test_prefix_validation():
    from dcf_tpu.backends.pallas_prefix import PrefixPallasBackend

    rng = random.Random(53)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(17)
    be = PrefixPallasBackend(16, cipher_keys, interpret=True)
    # Per-key POINT batches have no shared prefixes to exploit.
    b2 = gen_batch(prg_np,
                   nprng.integers(0, 256, (2, 2), dtype=np.uint8),
                   nprng.integers(0, 256, (2, 16), dtype=np.uint8),
                   random_s0s(2, 16, nprng), spec.Bound.LT_BETA)
    be.put_bundle(b2.for_party(0))
    with pytest.raises(ValueError, match="shared points"):
        be.eval(0, nprng.integers(0, 256, (2, 5, 2), dtype=np.uint8))
    # Too-shallow domains have no prefix to share.
    b1 = gen_batch(prg_np,
                   nprng.integers(0, 256, (1, 1), dtype=np.uint8),
                   nprng.integers(0, 256, (1, 16), dtype=np.uint8),
                   random_s0s(1, 16, nprng), spec.Bound.LT_BETA)
    with pytest.raises(ValueError, match="too shallow"):
        be.put_bundle(b1.for_party(0))
    with pytest.raises(ValueError, match="host_levels"):
        PrefixPallasBackend(16, cipher_keys, prefix_levels=4,
                            host_levels=6)
