"""dcf_tpu.serve.shardmap + serve.router: the pod-scale serving tier
(ISSUE 13).

Covers the shard ring (rendezvous placement: deterministic,
membership-order-free, minimally disruptive under seeded add/remove
fuzz with the moved-key fraction pinned around 1/N), the router
(two-hop parity vs the numpy oracle with the payload relayed
header-decode-only, unknown-tenant/unknown-key refusals staying typed
through the hop, CRITICAL failover to the replica with everything else
refused typed + hinted, the hot-swap generation guard crossing the
wire as ``StaleStateError``), the PR 12 wire-fuzz discipline re-run
against the ROUTER's socket (a mangled frame kills one connection,
never the accept loop), the ``EdgeClientPool`` reconnect/backoff
transport, and the pod metrics rollup + loadgen reconciliation.  The
kill-a-shard failover soak rides the serial slow leg.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from dcf_tpu import Dcf
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.errors import (
    BackendUnavailableError,
    CircuitOpenError,
    StaleStateError,
)
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.serve import (
    DcfRouter,
    EdgeClient,
    EdgeClientPool,
    EdgeServer,
    ShardMap,
    ShardSpec,
    TenantSpec,
    rollup_snapshots,
)
from dcf_tpu.serve.edge import decode_response, encode_request
from dcf_tpu.testing import faults
from dcf_tpu.testing.faults import FakeClock

pytestmark = pytest.mark.pod

NB, LAM = 2, 16


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0x90D)


@pytest.fixture(scope="module")
def ck(rng):
    return [rng.bytes(32), rng.bytes(32)]


@pytest.fixture(scope="module")
def dcf(ck):
    return Dcf(NB, LAM, ck, backend="bitsliced")


@pytest.fixture(scope="module")
def prg(ck):
    return HirosePrgNp(LAM, ck)


@pytest.fixture(scope="module")
def bundles(dcf, rng):
    out = {}
    for i in range(6):
        alphas = rng.integers(0, 256, (1, NB), dtype=np.uint8)
        betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
        out[f"pod-key-{i}"] = dcf.gen(alphas, betas, rng=rng)
    return out


def recon_oracle(prg, bundle, xs):
    return eval_batch_np(prg, 0, bundle.for_party(0), xs) ^ \
        eval_batch_np(prg, 1, bundle.for_party(1), xs)


class MiniPod:
    """N in-process shard "hosts" (each a real DcfService + EdgeServer
    over real TCP) behind one router — the threaded-leg stand-in for
    pod_bench's subprocesses (the tier-1 lane must not pay N jax
    process startups per test)."""

    def __init__(self, dcf, bundles, n=2, router_kw=None,
                 service_kw=None):
        self.svcs, self.servers, specs = [], [], []
        for i in range(n):
            svc = dcf.serve(max_batch=32, max_delay_ms=1.0,
                            **(service_kw or {}))
            svc.start()
            srv = EdgeServer(svc).start()
            self.svcs.append(svc)
            self.servers.append(srv)
            specs.append(ShardSpec(f"shard-{i}", *srv.address))
        self.map = ShardMap(specs)
        self._index = {s.host_id: i for i, s in enumerate(specs)}
        for name, kb in bundles.items():
            # Owner AND replica register the key (the warm-replica
            # discipline pod provisioning gives real shards via the
            # durable store).
            for spec in self.map.placement(name, replicas=1):
                self.svcs[self._index[spec.host_id]].register_key(
                    name, kb)
        self.router = DcfRouter(self.map, n_bytes=NB,
                                **(router_kw or {}))

    def svc_of(self, host_id):
        return self.svcs[self._index[host_id]]

    def kill(self, host_id):
        """SIGKILL-equivalent for an in-process shard: edge torn down,
        service abandoned undrained."""
        i = self._index[host_id]
        self.servers[i].close()
        self.svcs[i].close(drain=False)

    def close(self):
        self.router.close()
        for srv in self.servers:
            srv.close()
        for svc in self.svcs:
            try:
                svc.close(drain=False)
            except Exception:  # fallback-ok: best-effort teardown of
                # an already-killed shard
                pass


# ------------------------------------------------------ the ring


def test_rendezvous_deterministic_and_total():
    specs = [ShardSpec(f"h{i}", port=1000 + i) for i in range(4)]
    a = ShardMap(specs)
    b = ShardMap(reversed(specs))  # membership ORDER must not matter
    for i in range(50):
        key = f"key-{i}"
        assert a.owner(key).host_id == b.owner(key).host_id
        ranked = a.ranked(key)
        assert [s.host_id for s in ranked] == \
            [s.host_id for s in b.ranked(key)]
        assert sorted(s.host_id for s in ranked) == a.host_ids()
        assert ranked[0] == a.owner(key)
        assert ranked[1] == a.replica(key)
        assert a.placement(key, replicas=1) == ranked[:2]
    # Port/address changes move nothing: placement is keyed on host_id.
    moved = ShardMap([ShardSpec(s.host_id, port=2000 + i)
                      for i, s in enumerate(specs)])
    assert all(moved.owner(f"key-{i}").host_id
               == a.owner(f"key-{i}").host_id for i in range(50))


def test_membership_change_minimal_disruption_fuzz():
    """Seeded add/remove fuzz: removal moves EXACTLY the removed
    host's keys (to each key's next-ranked host); an addition steals
    ~1/N of the keys, every one landing ON the new host; ownership
    stays balanced throughout."""
    rng = np.random.default_rng(0x2156)
    keys = [f"k{i}" for i in range(2000)]
    ring = ShardMap([ShardSpec(f"h{i}") for i in range(4)])
    for step in range(6):
        owners = {k: ring.owner(k).host_id for k in keys}
        counts = {h: 0 for h in ring.host_ids()}
        for o in owners.values():
            counts[o] += 1
        fair = len(keys) / len(ring)
        assert all(0.6 * fair <= c <= 1.4 * fair
                   for c in counts.values()), (step, counts)
        if step % 2 == 0:
            new_id = f"h{10 + step}"
            grown = ring.with_host(ShardSpec(new_id))
            moved = {k for k in keys
                     if grown.owner(k).host_id != owners[k]}
            # Every stolen key lands ON the newcomer, and the stolen
            # fraction is ~1/N_new (binomial: 2000 draws, generous
            # band so the pin is about the mechanism, not seed luck).
            assert all(grown.owner(k).host_id == new_id for k in moved)
            frac = len(moved) / len(keys)
            assert 0.5 / len(grown) <= frac <= 1.6 / len(grown), frac
            ring = grown
        else:
            victim = ring.host_ids()[int(rng.integers(0, len(ring)))]
            shrunk = ring.without_host(victim)
            for k in keys:
                if owners[k] != victim:
                    assert shrunk.owner(k).host_id == owners[k]
                else:
                    # The orphaned keys fall to their old SECOND
                    # choice — the replica the failover tier (and the
                    # frame replication) already pointed at.
                    assert shrunk.owner(k).host_id == \
                        ring.ranked(k)[1].host_id
            ring = shrunk


def test_shardmap_membership_contracts():
    with pytest.raises(ValueError):
        ShardMap([])
    with pytest.raises(ValueError):
        ShardMap([ShardSpec("a"), ShardSpec("a", port=2)])
    with pytest.raises(ValueError):
        ShardSpec("")
    ring = ShardMap([ShardSpec("a")])
    with pytest.raises(ValueError):
        ring.without_host("nope")
    assert ring.replica("k") is None  # single host: no failover target


# ------------------------------------------------- routed serving


def test_routed_parity_vs_oracle_and_spread(dcf, bundles, prg, rng):
    """Ragged requests, both parties, routed across 3 shards over real
    TCP: every reconstruction bit-exact vs the numpy oracle, and the
    traffic demonstrably FANNED OUT (more than one shard forwarded)."""
    pod = MiniPod(dcf, bundles, n=3)
    try:
        for i, (name, kb) in enumerate(sorted(bundles.items())):
            m = int(rng.integers(1, 40)) if i != 2 else 1
            xs = rng.integers(0, 256, (m, NB), dtype=np.uint8)
            got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
                pod.router.evaluate(name, xs, b=1, timeout=60)
            assert np.array_equal(got, recon_oracle(prg, kb, xs)), name
        snap = pod.router.metrics_snapshot()
        fanned = [s for s in pod.map.host_ids()
                  if snap[f"router_forwards_total{{shard={s}}}"] > 0]
        assert len(fanned) >= 2, snap
    finally:
        pod.close()


def test_routed_wire_parity_through_pod_door(dcf, bundles, prg, rng):
    """DCFE on BOTH sides: an EdgeClient at the pod door, the router
    relaying to shard EdgeServers — two hops, bit-exact."""
    pod = MiniPod(dcf, bundles, n=2)
    pod.router.start()
    try:
        name = sorted(bundles)[0]
        xs = rng.integers(0, 256, (9, NB), dtype=np.uint8)
        with EdgeClient(*pod.router.address, n_bytes=NB) as c:
            got = c.evaluate(name, xs, b=0, timeout=60) ^ \
                c.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, bundles[name], xs))
    finally:
        pod.close()


def test_unknown_key_and_tenant_stay_typed_through_router(dcf, bundles,
                                                          rng):
    pod = MiniPod(dcf, bundles, n=2, router_kw=dict(
        tenants=(TenantSpec("gold", "critical"),)))
    pod.router.start()
    try:
        xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
        with EdgeClient(*pod.router.address, n_bytes=NB,
                        tenant="intruder") as c:
            with pytest.raises(ValueError, match="unknown tenant"):
                c.evaluate(sorted(bundles)[0], xs, timeout=60)
        with EdgeClient(*pod.router.address, n_bytes=NB,
                        tenant="gold") as c:
            with pytest.raises(ValueError, match="no bundle"):
                c.evaluate("no-such-key", xs, timeout=60)
            # The refusals were request-level: the same connection
            # still serves a real key afterwards.
            y = c.evaluate(sorted(bundles)[0], xs, timeout=60)
            assert y.shape == (1, 3, LAM)
    finally:
        pod.close()


def test_critical_failover_replica_serves_others_refused_typed(
        dcf, bundles, prg, rng):
    """Kill a key's owner: CRITICAL traffic fails over to the replica
    (bit-exact — the replica registered the same bundle, generation
    discipline intact), NORMAL traffic is refused typed WITH
    retry_after_s, and the refusal names the suspect shard."""
    pod = MiniPod(dcf, bundles, n=3)
    try:
        name = sorted(bundles)[0]
        owner = pod.map.owner(name).host_id
        pod.kill(owner)
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        got = pod.router.evaluate(name, xs, b=0, timeout=60,
                                  priority="critical") ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60,
                                priority="critical")
        assert np.array_equal(got, recon_oracle(prg, bundles[name], xs))
        assert pod.router.suspect_remaining(owner) > 0
        with pytest.raises(CircuitOpenError) as ei:
            pod.router.evaluate(name, xs, b=0, timeout=60)
        assert ei.value.retry_after_s is not None
        assert ei.value.retry_after_s > 0
        # Keys the dead shard does NOT own keep serving undisturbed.
        other = next(k for k in sorted(bundles)
                     if owner not in {s.host_id for s in
                                      pod.map.placement(k, replicas=1)})
        got = pod.router.evaluate(other, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(other, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, bundles[other],
                                                xs))
        snap = pod.router.metrics_snapshot()
        assert snap["router_failovers_total"] >= 1
        assert snap["router_suspect_refusals_total"] >= 1
    finally:
        pod.close()


def test_hot_swap_generation_guard_crosses_the_router(dcf, bundles,
                                                      prg, rng):
    """ISSUE 13 acceptance: a re-registration racing a forwarded eval
    fails ``StaleStateError`` — typed across BOTH hops (the E_STALE
    wire code keeps the class) — and never serves mixed key images;
    the next request serves the NEW key bit-exact."""
    pod = MiniPod(dcf, bundles, n=2)
    try:
        name = sorted(bundles)[0]
        owner_svc = pod.svc_of(pod.map.owner(name).host_id)
        new_kb = dcf.gen(
            rng.integers(0, 256, (1, NB), dtype=np.uint8),
            rng.integers(0, 256, (1, LAM), dtype=np.uint8), rng=rng)
        swapped = {"n": 0}

        def swap_once(key_id, _points):
            # Fires on the shard worker at stage time, AFTER the group
            # snapshot was taken and BEFORE the residency check — the
            # exact race the generation guard exists for.
            if key_id == name and swapped["n"] == 0:
                swapped["n"] = 1
                owner_svc.register_key(name, new_kb)

        xs = rng.integers(0, 256, (5, NB), dtype=np.uint8)
        with faults.inject("serve.stage", handler=swap_once):
            fut = pod.router.submit(name, xs, b=0)
            with pytest.raises(StaleStateError):
                fut.result(60)
        assert swapped["n"] == 1
        got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, new_kb, xs))
    finally:
        pod.close()


# ------------------------------------------------- wire fuzz


def _valid_request_frame(key_id: str, xs) -> bytes:
    return encode_request(7, "", key_id, 0, 255, None,
                          np.ascontiguousarray(xs).data, xs.shape[1],
                          xs.shape[0])


def _raw_exchange(addr, payload: bytes) -> list:
    """Send raw bytes to the router door, drain to EOF, decode
    response frames (reset counts as EOF — the typed-containment
    hangup)."""
    s = socket.create_connection(addr, timeout=30)
    try:
        s.sendall(payload)
        s.shutdown(socket.SHUT_WR)
        data = b""
        while True:
            try:
                chunk = s.recv(1 << 16)
            except ConnectionResetError:
                break
            if not chunk:
                break
            data += chunk
    finally:
        s.close()
    frames, off = [], 0
    while off < len(data):
        (body_len,) = struct.unpack_from("<I", data, off)
        frames.append(decode_response(data[off + 4:off + 4 + body_len]))
        off += 4 + body_len
    return frames


def test_wire_fuzz_through_router_kills_one_connection_only(
        dcf, bundles, prg, rng):
    """The PR 12 wire-fuzz suite re-run against the ROUTER's accept
    loop: byte-flipped frames, truncations and oversized length
    prefixes each die as a typed per-connection outcome — and a
    healthy concurrent connection (plus a fresh one after every
    mangled attempt) keeps round-tripping, so the fuzz never cost the
    router its accept loop."""
    pod = MiniPod(dcf, bundles, n=2)
    pod.router.start()
    addr = pod.router.address
    name = sorted(bundles)[0]
    xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
    healthy = EdgeClient(*addr, n_bytes=NB)
    try:
        frame = _valid_request_frame(name, xs)
        body = frame[4:]
        mangles = []
        for off in rng.choice(len(body), size=12, replace=False):
            buf = bytearray(frame)
            buf[4 + int(off)] ^= 0x41
            mangles.append(bytes(buf))
        mangles.append(frame[:len(frame) // 2])      # truncation
        mangles.append(struct.pack("<I", 1 << 30))   # oversized prefix
        for i, wire in enumerate(mangles):
            frames = _raw_exchange(addr, wire)
            for kind, _rid, code, _retry, _msg in frames:
                assert kind == "error", (i, frames)
            # The healthy long-lived connection survived the mangled
            # one's death...
            y = healthy.evaluate(name, xs, b=0, timeout=60)
            assert np.array_equal(
                y, eval_batch_np(prg, 0, bundles[name].for_party(0),
                                 xs))
            assert not healthy.closed
        # ...and the accept loop still takes fresh connections.
        with EdgeClient(*addr, n_bytes=NB) as c:
            c.evaluate(name, xs, b=0, timeout=60)
    finally:
        healthy.close()
        pod.close()


# ------------------------------------------------- the client pool


def test_edge_client_pool_reconnects_and_backs_off(dcf, bundles, prg,
                                                   rng, monkeypatch):
    """The ISSUE 13 pool satellite: a dead connection is replaced on
    the next lease (the PR 12 ``closed`` signal), a dark target fails
    typed WITHOUT dialing until the backoff elapses on the injectable
    clock, and the first good dial resets the backoff."""
    import dcf_tpu.serve.edge as edge_mod

    svc = dcf.serve(max_batch=32, max_delay_ms=1.0)
    name = sorted(bundles)[0]
    svc.register_key(name, bundles[name])
    svc.start()
    server = EdgeServer(svc).start()
    host, port = server.address
    clk = FakeClock(50.0)
    dialed = {"n": 0}
    real_connect = socket.create_connection

    def counting_connect(*a, **kw):
        dialed["n"] += 1
        return real_connect(*a, **kw)

    monkeypatch.setattr(edge_mod.socket, "create_connection",
                        counting_connect)
    pool = EdgeClientPool(host, port, n_bytes=NB, size=1, clock=clk,
                          reconnect_backoff_s=1.0, max_backoff_s=4.0)
    xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
    try:
        y = pool.evaluate(name, xs, timeout=60)
        assert np.array_equal(
            y, eval_batch_np(prg, 0, bundles[name].for_party(0), xs))
        assert (pool.dials, pool.reconnects) == (1, 0)
        # Kill the pooled connection: the next lease notices `closed`
        # and replaces it — the hand-rolled bench loop, promoted.
        pool._slots[0].close()
        y = pool.evaluate(name, xs, timeout=60)
        assert np.array_equal(
            y, eval_batch_np(prg, 0, bundles[name].for_party(0), xs))
        assert (pool.dials, pool.reconnects) == (2, 1)

        # Tear the whole target down: the pooled client notices EOF...
        server.close()
        deadline = time.monotonic() + 10
        while not pool._slots[0].closed:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # ...the dial fails typed and arms the backoff...
        before = dialed["n"]
        with pytest.raises(BackendUnavailableError, match="connect"):
            pool.submit(name, xs)
        assert dialed["n"] == before + 1
        # ...and while dark, leases fail typed WITHOUT dialing.
        with pytest.raises(BackendUnavailableError, match="dark"):
            pool.submit(name, xs)
        assert dialed["n"] == before + 1
        clk.advance(1.5)  # past the 1.0s backoff: dialing resumes
        with pytest.raises(BackendUnavailableError, match="connect"):
            pool.submit(name, xs)
        assert dialed["n"] == before + 2
    finally:
        pool.close()
        server.close()
        svc.close()


def test_edge_client_pool_validates_config():
    with pytest.raises(ValueError):
        EdgeClientPool("127.0.0.1", 1, n_bytes=NB, size=0)
    with pytest.raises(ValueError):
        EdgeClientPool("127.0.0.1", 1, n_bytes=NB,
                       reconnect_backoff_s=0.0)


# ------------------------------------------------- rollup + loadgen


def test_rollup_snapshots_sums_the_pod_view():
    a = {"serve_requests_total": 3, "serve_queue_depth": 1,
         "h_sum": 1.5, "h_count": 2, "h_bounds": [0.1, 1.0],
         "h_buckets": [1, 2],
         "serve_shed_by_class_total{priority=batch}": 1}
    b = {"serve_requests_total": 4, "serve_queue_depth": 2,
         "h_sum": 0.5, "h_count": 1, "h_bounds": [0.1, 1.0],
         "h_buckets": [0, 1], "edge_frames_total": 9}
    roll = rollup_snapshots([a, b])
    assert roll["serve_requests_total"] == 7
    assert roll["serve_queue_depth"] == 3
    assert roll["h_sum"] == 2.0 and roll["h_count"] == 3
    assert roll["h_buckets"] == [1, 3]
    assert roll["h_bounds"] == [0.1, 1.0]
    assert roll["edge_frames_total"] == 9  # single-host series carry
    assert roll["serve_shed_by_class_total{priority=batch}"] == 1
    assert list(roll) == sorted(roll)  # still a deterministic snapshot
    with pytest.raises(ValueError, match="bounds"):
        rollup_snapshots([a, {"h_bounds": [0.2, 1.0]}])


def test_loadgen_reconciles_against_pod_rollup(dcf, bundles, prg, rng):
    """The ISSUE 13 small fix, live: an open-loop run against a
    2-shard pod reconciles sent/expired/per-class sheds against the
    SUM of the shards' snapshots — which a single service's snapshot
    cannot provide (each shard saw only its keys' traffic)."""
    from dcf_tpu.serve.loadgen import open_loop, reconcile_against_rollup

    pod = MiniPod(dcf, bundles, n=2)
    try:
        before = rollup_snapshots(
            [svc.metrics_snapshot() for svc in pod.svcs])
        res = open_loop(pod.router, sorted(bundles), rate_rps=60.0,
                        duration_s=1.0, min_points=1, max_points=8,
                        seed=5)
        after = rollup_snapshots(
            [svc.metrics_snapshot() for svc in pod.svcs])
        recon = reconcile_against_rollup(res, before, after)
        assert recon["reconciled"], recon
        assert res.sent > 0
        assert res.sent == recon["sent"]["pod"]
        # The single-process assumption really is broken behind a
        # router: when both shards own keys (they do, for this seed's
        # placement), no ONE shard's snapshot saw all the accepted
        # requests — only the rollup closes the ledger.
        owner_set = {pod.map.owner(k).host_id for k in bundles}
        if len(owner_set) > 1:
            per_host = [svc.metrics_snapshot()["serve_requests_total"]
                        for svc in pod.svcs]
            assert all(h < after["serve_requests_total"]
                       for h in per_host), per_host
    finally:
        pod.close()


# ------------------------------------------------- the slow soak


@pytest.mark.slow
def test_pod_failover_soak_every_request_accounted(dcf, bundles, prg,
                                                   rng):
    """Serial-leg soak (ISSUE 13 CI satellite): 3 in-process shards
    under 3-thread mixed CRITICAL/NORMAL load, one shard killed
    mid-run — every request completes bit-exact vs the numpy oracle
    or is refused typed WITH retry_after_s; afterwards every
    victim-owned key serves CRITICAL traffic from its replica."""
    from dcf_tpu.errors import DcfError

    pod = MiniPod(dcf, bundles, n=3)
    stats = {"ok": 0, "mismatch": 0, "refused_hinted": 0,
             "refused_unhinted": 0, "unaccounted": 0}
    lock = threading.Lock()
    stop = threading.Event()
    names = sorted(bundles)

    def client(i):
        crng = np.random.default_rng(100 + i)
        while not stop.is_set():
            name = names[int(crng.integers(0, len(names)))]
            pr = "critical" if crng.random() < 0.5 else "normal"
            m = int(crng.integers(1, 17))
            xs = crng.integers(0, 256, (m, NB), dtype=np.uint8)
            try:
                f0 = pod.router.submit(name, xs, b=0, priority=pr)
                f1 = pod.router.submit(name, xs, b=1, priority=pr)
                got = f0.result(60) ^ f1.result(60)
            except DcfError as e:
                hinted = getattr(e, "retry_after_s", None) is not None
                with lock:
                    stats["refused_hinted" if hinted else
                          "refused_unhinted"] += 1
                continue
            except Exception:  # fallback-ok: the gate's failure arm —
                # anything untyped is exactly what the soak hunts
                with lock:
                    stats["unaccounted"] += 1
                continue
            with lock:
                if np.array_equal(got,
                                  recon_oracle(prg, bundles[name], xs)):
                    stats["ok"] += 1
                else:
                    stats["mismatch"] += 1

    victim = pod.map.owner(names[0]).host_id
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(1.5)
        pod.kill(victim)
        time.sleep(3.0)
        stop.set()
        for t in threads:
            t.join(30)
        assert stats["ok"] >= 3, stats
        assert stats["mismatch"] == 0, stats
        assert stats["unaccounted"] == 0, stats
        assert stats["refused_unhinted"] == 0, stats
        # Victim-owned keys still serve CRITICAL from their replicas.
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        for name in names:
            if pod.map.owner(name).host_id != victim:
                continue
            got = pod.router.evaluate(name, xs, b=0, timeout=60,
                                      priority="critical") ^ \
                pod.router.evaluate(name, xs, b=1, timeout=60,
                                    priority="critical")
            assert np.array_equal(got,
                                  recon_oracle(prg, bundles[name], xs))
    finally:
        stop.set()
        pod.close()
