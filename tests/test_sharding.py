"""Sharded eval on the 8-virtual-device CPU mesh: parity + mesh shapes."""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp


def rand_bytes(rng: random.Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


def test_mesh_shapes():
    import jax
    from dcf_tpu.parallel import make_mesh

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh(8)
    assert mesh.shape == {"keys": 4, "points": 2}
    mesh1 = make_mesh(1)
    assert mesh1.shape == {"keys": 1, "points": 1}
    with pytest.raises(ValueError):
        make_mesh(16)


def test_sharded_bitsliced_matches_numpy():
    """The fast (bit-plane) core sharded over the 8-device mesh: parity
    with the numpy oracle for shared and per-key points, incl. point
    padding to the per-shard lane granule."""
    from dcf_tpu.parallel import ShardedBitslicedBackend, make_mesh

    rng = random.Random(33)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(8)
    k_num, n_bytes, m = 8, 2, 37  # ragged m: exercises shard padding
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(
        prg_np, alphas, betas, random_s0s(k_num, 16, nprng),
        spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs3 = nprng.integers(0, 256, (k_num, m, n_bytes), dtype=np.uint8)

    mesh = make_mesh(8)
    backend = ShardedBitslicedBackend(16, cipher_keys, mesh)
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = backend.eval(b, xs, bundle=kb)
        assert np.array_equal(got, eval_batch_np(prg_np, b, kb, xs)), \
            f"party {b} shared"
        got3 = backend.eval(b, xs3)
        assert np.array_equal(got3, eval_batch_np(prg_np, b, kb, xs3)), \
            f"party {b} per-key"


def test_sharded_eval_matches_numpy():
    from dcf_tpu.parallel import ShardedJaxBackend, make_mesh

    rng = random.Random(31)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(6)
    k_num, n_bytes, m = 8, 2, 12  # K divisible by 4, M by 2
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(prg_np, alphas, betas, random_s0s(k_num, 16, nprng), spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)

    mesh = make_mesh(8)
    backend = ShardedJaxBackend(16, cipher_keys, mesh)
    ys = {}
    for b in (0, 1):
        want = eval_batch_np(prg_np, b, bundle.for_party(b), xs)
        got = backend.eval(b, xs, bundle=bundle.for_party(b))
        assert np.array_equal(got, want), f"party {b} sharded mismatch"
        ys[b] = got
    # Two-party reconstruction across the mesh output.
    recon = ys[0] ^ ys[1]
    for i in range(k_num):
        a = alphas[i].tobytes()
        for j in range(m):
            expect = betas[i].tobytes() if xs[j].tobytes() < a else bytes(16)
            assert recon[i, j].tobytes() == expect


def test_sharded_pallas_matches_numpy():
    """The flagship Pallas walk kernel under shard_map on the 8-device
    mesh (interpreter mode — no TPU): parity with the numpy oracle for
    shared and per-key points, both parties, both bounds, ragged m."""
    from dcf_tpu.parallel import ShardedPallasBackend, make_mesh

    rng = random.Random(34)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(9)
    k_num, n_bytes, m = 4, 2, 37  # ragged m exercises per-shard tile pad
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    for bound in (spec.Bound.LT_BETA, spec.Bound.GT_BETA):
        bundle = gen_batch(
            prg_np, alphas, betas, random_s0s(k_num, 16, nprng), bound)
        xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
        xs[0] = alphas[0]  # exact-alpha point
        xs3 = nprng.integers(0, 256, (k_num, m, n_bytes), dtype=np.uint8)

        mesh = make_mesh(8)  # keys=4 x points=2
        backend = ShardedPallasBackend(16, cipher_keys, mesh, interpret=True)
        for b in (0, 1):
            kb = bundle.for_party(b)
            got = backend.eval(b, xs, bundle=kb)
            assert np.array_equal(got, eval_batch_np(prg_np, b, kb, xs)), \
                f"party {b} shared {bound}"
            got3 = backend.eval(b, xs3)
            assert np.array_equal(got3, eval_batch_np(prg_np, b, kb, xs3)), \
                f"party {b} per-key {bound}"


def test_sharded_pallas_staged_roundtrip():
    """Staged protocol (stage / eval_staged / staged_to_bytes) through the
    sharded Pallas path + two-party reconstruction."""
    from dcf_tpu.parallel import ShardedPallasBackend, make_mesh

    rng = random.Random(35)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(10)
    k_num, n_bytes, m = 2, 2, 64
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(prg_np, alphas, betas, random_s0s(k_num, 16, nprng),
                       spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)

    mesh = make_mesh(shape=(2, 4))
    ys = {}
    for b in (0, 1):
        backend = ShardedPallasBackend(16, cipher_keys, mesh, interpret=True)
        backend.put_bundle(bundle.for_party(b))
        staged = backend.stage(xs)
        y = backend.eval_staged(b, staged)
        ys[b] = backend.staged_to_bytes(y, staged["m"])
    recon = ys[0] ^ ys[1]
    for i in range(k_num):
        a = alphas[i].tobytes()
        for j in range(m):
            expect = betas[i].tobytes() if xs[j].tobytes() < a else bytes(16)
            assert recon[i, j].tobytes() == expect


def test_sharded_keylanes_matches_numpy():
    """The many-keys (config-5) kernel under shard_map: parity with the
    numpy oracle + the on-device relu mismatch counter, 8-device mesh."""
    from dcf_tpu.parallel import ShardedKeyLanesBackend, make_mesh

    rng = random.Random(36)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(11)
    k_num, n_bytes, m = 40, 2, 9  # ragged keys (40 < 4*32) and points
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(prg_np, alphas, betas, random_s0s(k_num, 16, nprng),
                       spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs[0] = alphas[0]

    mesh = make_mesh(8)  # keys=4 x points=2
    backend = ShardedKeyLanesBackend(
        16, cipher_keys, mesh, m_tile=2, kw_tile=1, level_chunk=4,
        interpret=True)
    backend.put_bundle(bundle)
    staged = backend.stage(xs)
    ys_dev = {}
    for b in (0, 1):
        y = backend.eval_staged(b, staged)
        ys_dev[b] = y
        got = backend.staged_to_bytes(y, staged["m"])
        want = eval_batch_np(prg_np, b, bundle.for_party(b), xs)
        assert np.array_equal(got, want), f"party {b}"
    mism = int(backend.relu_mismatch_count(
        ys_dev[0], ys_dev[1], alphas, betas, xs))
    assert mism == 0


@pytest.mark.parametrize("gt", [False, True])
def test_sharded_tree_fulldomain(gt):
    """The GGM tree expand kernel sharded over the 8-device mesh: each
    device expands a disjoint sub-frontier and verifies its own leaves
    (shard-aware position -> domain-value map), both bounds, plus a
    negative control proving the counter detects corruption."""
    from dcf_tpu.backends.fulldomain import TreeFullDomain
    from dcf_tpu.parallel import ShardedTreeFullDomain, make_mesh

    rng = random.Random(37)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(12)
    n_bits = 16  # 8 host levels (frontier 256 nodes = 1 word/device) + 8
    alpha = int(nprng.integers(0, 1 << n_bits))
    beta = nprng.integers(0, 256, 16, dtype=np.uint8).tobytes()
    bound = spec.Bound.GT_BETA if gt else spec.Bound.LT_BETA
    bundle = gen_batch(
        prg_np,
        np.frombuffer(alpha.to_bytes(2, "big"), dtype=np.uint8)[None],
        np.frombuffer(beta, dtype=np.uint8)[None],
        random_s0s(1, 16, nprng), bound)

    mesh = make_mesh(8)
    fd = ShardedTreeFullDomain(16, cipher_keys, mesh, interpret=True)
    assert fd.host_levels == 8
    assert fd.check(bundle, alpha, beta, n_bits, gt=gt) == 0
    # Agreement with the unsharded evaluator's verdict on a WRONG beta:
    # both counters must see exactly the points inside the bound.
    wrong = bytes(b ^ 1 for b in beta)
    got = fd.check(bundle, alpha, wrong, n_bits, gt=gt)
    want = TreeFullDomain(16, cipher_keys, interpret=True).check(
        bundle, alpha, wrong, n_bits, gt=gt)
    inside = ((1 << n_bits) - 1 - alpha) if gt else alpha
    assert got == want == inside


def test_sharded_large_lambda_matches_numpy():
    """The large-lambda hybrid under shard_map on the 8-device mesh:
    parity with the numpy oracle, both parties, both bounds, ragged m."""
    from dcf_tpu.parallel import ShardedLargeLambdaBackend, make_mesh

    lam = 64
    rng = random.Random(39)
    cipher_keys = [rand_bytes(rng, 32) for _ in range(18)]  # index 17
    prg_np = HirosePrgNp(lam, cipher_keys)
    nprng = np.random.default_rng(13)
    k_num, n_bytes, m = 4, 2, 37  # K divides keys=4; ragged m pads
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, lam), dtype=np.uint8)
    mesh = make_mesh(8)  # keys=4 x points=2
    for bound in (spec.Bound.LT_BETA, spec.Bound.GT_BETA):
        bundle = gen_batch(prg_np, alphas, betas,
                           random_s0s(k_num, lam, nprng), bound)
        xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
        xs[0] = alphas[0]
        be = ShardedLargeLambdaBackend(lam, cipher_keys, mesh,
                                       interpret=True)
        for b in (0, 1):
            kb = bundle.for_party(b)
            got = be.eval(b, xs, bundle=kb)
            want = eval_batch_np(prg_np, b, kb, xs)
            assert np.array_equal(got, want), f"party {b} {bound}"


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_sharded_prefix_matches_numpy(bound):
    """The prefix-shared evaluator under shard_map on a 1x8 points mesh
    (interpreter mode): parity with the numpy oracle, both parties, both
    bounds, ragged m, staged roundtrip + device counter."""
    from dcf_tpu.parallel import ShardedPrefixBackend, make_mesh

    rng = random.Random(41)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(18)
    n_bytes, m = 2, 37  # ragged m pads per shard
    alphas = nprng.integers(0, 256, (1, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (1, 16), dtype=np.uint8)
    bundle = gen_batch(prg_np, alphas, betas, random_s0s(1, 16, nprng),
                       bound)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs[0] = alphas[0]

    mesh = make_mesh(shape=(1, 8))
    bes = {b: ShardedPrefixBackend(16, cipher_keys, mesh, interpret=True,
                                   tile_words=2) for b in (0, 1)}
    ys = {}
    staged = None
    for b in (0, 1):
        bes[b].put_bundle(bundle.for_party(b))
        if staged is None:
            staged = bes[b].stage(xs)
        y = bes[b].eval_staged(b, staged)
        ys[b] = y
        got = bes[b].staged_to_bytes(y, staged["m"])
        want = eval_batch_np(prg_np, b, bundle.for_party(b), xs)
        assert np.array_equal(got, want), f"party {b} {bound}"
    assert int(bes[0].points_mismatch_count(
        ys[0], ys[1], alphas[0].tobytes(), betas[0].tobytes(), staged,
        gt=bound is spec.Bound.GT_BETA)) == 0
    # keys axis must be 1
    with pytest.raises(ValueError, match="single-key"):
        ShardedPrefixBackend(16, cipher_keys, make_mesh(8), interpret=True)


def test_sharded_prefix_multikey_matches_numpy():
    """K=3 keys through the SHARDED prefix path (keys axis stays 1;
    every device walks all keys on its point shard): bit-exact for every
    key — the regression case where a missing k_num in the shard body
    silently evaluated only key 0."""
    from dcf_tpu.parallel import ShardedPrefixBackend, make_mesh

    rng = random.Random(42)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(20)
    k_num, n_bytes, m = 3, 2, 13
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(prg_np, alphas, betas,
                       random_s0s(k_num, 16, nprng), spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs[0] = alphas[0]

    mesh = make_mesh(shape=(1, 8))
    be = ShardedPrefixBackend(16, cipher_keys, mesh, interpret=True,
                              tile_words=2)
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = be.eval(b, xs, bundle=kb)
        want = eval_batch_np(prg_np, b, kb, xs)
        assert got.shape == (k_num, m, 16)
        assert np.array_equal(got, want), f"party {b}"


def test_facade_mesh_hybrid_auto():
    """Dcf(..., lam>=48, mesh=...) auto-routes to the sharded hybrid."""
    import warnings as _warnings

    from dcf_tpu import Dcf, ReferenceContractWarning
    from dcf_tpu.parallel import ShardedLargeLambdaBackend, make_mesh

    rng = random.Random(40)
    cipher_keys = [rand_bytes(rng, 32) for _ in range(18)]
    nprng = np.random.default_rng(14)
    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore", ReferenceContractWarning)
        dcf = Dcf(2, 64, cipher_keys, mesh=make_mesh(8))
    assert dcf.backend_name == "hybrid"
    alphas = nprng.integers(0, 256, (4, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (4, 64), dtype=np.uint8)
    bundle = dcf.gen(alphas, betas, rng=nprng)
    xs = nprng.integers(0, 256, (6, 2), dtype=np.uint8)
    recon = dcf.eval(0, bundle, xs) ^ dcf.eval(1, bundle, xs)
    assert isinstance(dcf._eval_backends[0], ShardedLargeLambdaBackend)
    for i in range(4):
        a = alphas[i].tobytes()
        for j in range(6):
            want = betas[i].tobytes() if xs[j].tobytes() < a else bytes(64)
            assert recon[i, j].tobytes() == want


def test_sharded_tree_validation():
    from dcf_tpu.parallel import ShardedTreeFullDomain, make_mesh

    rng = random.Random(38)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    mesh = make_mesh(8)
    with pytest.raises(ValueError, match="host_levels"):
        ShardedTreeFullDomain(16, cipher_keys, mesh, host_levels=7,
                              interpret=True)


def test_sharded_eval_divisibility_errors():
    from dcf_tpu.parallel import ShardedJaxBackend, make_mesh

    rng = random.Random(32)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(7)
    bundle = gen_batch(
        prg_np,
        nprng.integers(0, 256, (3, 2), dtype=np.uint8),
        nprng.integers(0, 256, (3, 16), dtype=np.uint8),
        random_s0s(3, 16, nprng),
        spec.Bound.LT_BETA,
    )
    backend = ShardedJaxBackend(16, cipher_keys, make_mesh(8))
    with pytest.raises(ValueError):
        backend.put_bundle(bundle.for_party(0))  # 3 keys % 4 != 0
