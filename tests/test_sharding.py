"""Sharded eval on the 8-virtual-device CPU mesh: parity + mesh shapes."""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp


def rand_bytes(rng: random.Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


def test_mesh_shapes():
    import jax
    from dcf_tpu.parallel import make_mesh

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh(8)
    assert mesh.shape == {"keys": 4, "points": 2}
    mesh1 = make_mesh(1)
    assert mesh1.shape == {"keys": 1, "points": 1}
    with pytest.raises(ValueError):
        make_mesh(16)


def test_sharded_bitsliced_matches_numpy():
    """The fast (bit-plane) core sharded over the 8-device mesh: parity
    with the numpy oracle for shared and per-key points, incl. point
    padding to the per-shard lane granule."""
    from dcf_tpu.parallel import ShardedBitslicedBackend, make_mesh

    rng = random.Random(33)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(8)
    k_num, n_bytes, m = 8, 2, 37  # ragged m: exercises shard padding
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(
        prg_np, alphas, betas, random_s0s(k_num, 16, nprng),
        spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs3 = nprng.integers(0, 256, (k_num, m, n_bytes), dtype=np.uint8)

    mesh = make_mesh(8)
    backend = ShardedBitslicedBackend(16, cipher_keys, mesh)
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = backend.eval(b, xs, bundle=kb)
        assert np.array_equal(got, eval_batch_np(prg_np, b, kb, xs)), \
            f"party {b} shared"
        got3 = backend.eval(b, xs3)
        assert np.array_equal(got3, eval_batch_np(prg_np, b, kb, xs3)), \
            f"party {b} per-key"


def test_sharded_eval_matches_numpy():
    from dcf_tpu.parallel import ShardedJaxBackend, make_mesh

    rng = random.Random(31)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(6)
    k_num, n_bytes, m = 8, 2, 12  # K divisible by 4, M by 2
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(prg_np, alphas, betas, random_s0s(k_num, 16, nprng), spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)

    mesh = make_mesh(8)
    backend = ShardedJaxBackend(16, cipher_keys, mesh)
    ys = {}
    for b in (0, 1):
        want = eval_batch_np(prg_np, b, bundle.for_party(b), xs)
        got = backend.eval(b, xs, bundle=bundle.for_party(b))
        assert np.array_equal(got, want), f"party {b} sharded mismatch"
        ys[b] = got
    # Two-party reconstruction across the mesh output.
    recon = ys[0] ^ ys[1]
    for i in range(k_num):
        a = alphas[i].tobytes()
        for j in range(m):
            expect = betas[i].tobytes() if xs[j].tobytes() < a else bytes(16)
            assert recon[i, j].tobytes() == expect


def test_sharded_eval_divisibility_errors():
    from dcf_tpu.parallel import ShardedJaxBackend, make_mesh

    rng = random.Random(32)
    cipher_keys = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg_np = HirosePrgNp(16, cipher_keys)
    nprng = np.random.default_rng(7)
    bundle = gen_batch(
        prg_np,
        nprng.integers(0, 256, (3, 2), dtype=np.uint8),
        nprng.integers(0, 256, (3, 16), dtype=np.uint8),
        random_s0s(3, 16, nprng),
        spec.Bound.LT_BETA,
    )
    backend = ShardedJaxBackend(16, cipher_keys, make_mesh(8))
    with pytest.raises(ValueError):
        backend.put_bundle(bundle.for_party(0))  # 3 keys % 4 != 0
