"""The Prg seam: the GGM walk is generic over the PRG construction.

Reference ``trait Prg`` (/root/reference/src/lib.rs:52-58) encodes this in
types; here it is the structural protocol documented in dcf_tpu/ops/prg.py.
These tests wire the non-cryptographic mock (tests/mock_prg.py) through
every generic consumer of the seam — spec gen/eval, batched host gen,
numpy eval, and the JAX scan backend — proving the protocol logic never
depends on Hirose/AES internals, and doing so two orders of magnitude
faster than the AES-backed spec parity tests.
"""

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from tests.mock_prg import MockPrgNp, MockPrgSpec, mock_prg_gen_jax


@pytest.mark.parametrize("lam", [16, 32, 48])
def test_mock_twins_bit_identical(lam):
    """The three mock twins (bytes / numpy / jax) agree byte-for-byte —
    the same three-way parity contract the Hirose implementations keep."""
    import jax.numpy as jnp

    mk_spec = MockPrgSpec(lam)
    mk_np = MockPrgNp(lam)
    seeds = np.random.default_rng(21).integers(
        0, 256, (9, lam), dtype=np.uint8)
    out = mk_np.gen(seeds)
    jout = [np.asarray(a) for a in mock_prg_gen_jax((), lam, jnp.asarray(seeds))]
    for i in range(seeds.shape[0]):
        (s_l, v_l, t_l), (s_r, v_r, t_r) = mk_spec.gen(seeds[i].tobytes())
        assert out.s_l[i].tobytes() == s_l == jout[0][i].tobytes()
        assert out.v_l[i].tobytes() == v_l == jout[1][i].tobytes()
        assert out.s_r[i].tobytes() == s_r == jout[3][i].tobytes()
        assert out.v_r[i].tobytes() == v_r == jout[4][i].tobytes()
        assert bool(out.t_l[i]) == t_l == bool(jout[2][i])
        assert bool(out.t_r[i]) == t_r == bool(jout[5][i])


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
@pytest.mark.parametrize("lam", [16, 32])
def test_mock_gen_batch_matches_spec_gen(bound, lam):
    """spec.gen and gen_batch produce identical keys under the mock PRG —
    keygen's correction-word logic is PRG-agnostic."""
    k_num, n_bytes = 3, 2
    nprng = np.random.default_rng(22)
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, lam), dtype=np.uint8)
    s0s = random_s0s(k_num, lam, nprng)
    bundle = gen_batch(MockPrgNp(lam), alphas, betas, s0s, bound)
    mk_spec = MockPrgSpec(lam)
    for i in range(k_num):
        share = spec.gen(
            mk_spec,
            spec.CmpFn(alphas[i].tobytes(), betas[i].tobytes()),
            [s0s[i, 0].tobytes(), s0s[i, 1].tobytes()],
            bound,
        )
        got = bundle.to_shares()[i]
        assert got.s0s == share.s0s
        assert got.cws == share.cws
        assert got.cw_np1 == share.cw_np1


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_mock_end_to_end_all_generic_backends(bound):
    """Full two-party protocol under the mock PRG across spec, numpy and
    JAX evaluation — identical shares from all three, and reconstruction
    equals the comparison function.  With n_bytes=4 (32 levels) this runs
    in seconds; the AES-backed spec would take minutes at this shape."""
    from dcf_tpu.backends.jax_backend import JaxBackend

    lam, k_num, n_bytes, m = 16, 2, 4, 16
    nprng = np.random.default_rng(23)
    mk_np = MockPrgNp(lam)
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, lam), dtype=np.uint8)
    s0s = random_s0s(k_num, lam, nprng)
    bundle = gen_batch(mk_np, alphas, betas, s0s, bound)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs[0] = alphas[0]  # boundary point

    y0 = eval_batch_np(mk_np, 0, bundle.for_party(0), xs)
    y1 = eval_batch_np(mk_np, 1, bundle.for_party(1), xs)

    # JAX backend with the mock wired through the prg_fn seam.  The
    # cipher_keys arg only sizes the (unused) Hirose round-key tuple.
    ck = [bytes(32), bytes(32)]
    jb0 = JaxBackend(lam, ck, prg_fn=mock_prg_gen_jax)
    jb1 = JaxBackend(lam, ck, prg_fn=mock_prg_gen_jax)
    jy0 = jb0.eval(0, xs, bundle.for_party(0))
    jy1 = jb1.eval(1, xs, bundle.for_party(1))
    assert np.array_equal(jy0, y0)
    assert np.array_equal(jy1, y1)

    # Spec eval spot-check on a few points (the slow path, even mocked).
    mk_spec = MockPrgSpec(lam)
    k0 = bundle.to_shares()[0].for_party(0)
    for j in range(4):
        assert y0[0, j].tobytes() == spec.eval_point(
            mk_spec, False, k0, xs[j].tobytes())

    recon = y0 ^ y1
    for i in range(k_num):
        a = alphas[i].tobytes()
        for j in range(m):
            x = xs[j].tobytes()
            hit = x < a if bound is spec.Bound.LT_BETA else x > a
            expect = betas[i].tobytes() if hit else bytes(lam)
            assert recon[i, j].tobytes() == expect
