"""DPF protocol layer (ISSUE 19): keygen, per-point eval, wire format.

The contract under test: ``protocols.dpf`` — the GGM walk minus the
comparison accumulation.  ``dpf_gen_on_device`` (the PR 10 K-packed
keygen kernel minus the v column) must be BYTE-IDENTICAL to the host
``dpf_gen_batch``; both parties' per-point shares must XOR to the
``dpf_oracle`` golden model (beta at alpha, zero elsewhere, including
the exact point x = alpha); and the DCFK v3 ``proto=2`` frame must
round-trip bit-exact with the version gate holding both ways (the
cross-reader fuzz rides tests/test_keys_fuzz.py).
"""

import warnings

import numpy as np
import pytest

from dcf_tpu.errors import ShapeError
from dcf_tpu.gen import random_s0s
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.protocols import decode_proto_frame
from dcf_tpu.protocols.dpf import (
    DPF_DEVICE_LAM,
    DpfBundle,
    dpf_device_fallback_count,
    dpf_eval_points,
    dpf_gen_batch,
    dpf_gen_on_device,
)
from dcf_tpu.protocols.oracle import dpf_oracle

pytestmark = pytest.mark.dpf

NB = 2  # 16-bit domain


def _cipher_keys(rng, lam: int) -> list:
    n = max(2, 2 * (lam // 16))
    if lam >= 32:
        n = max(n, 18)
    return [bytes(rng.integers(0, 256, 32, dtype=np.uint8))
            for _ in range(n)]


def _prg(lam, ck):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return HirosePrgNp(lam, ck)


def _alpha_bytes(vals, nb: int) -> np.ndarray:
    return np.array([list(int(v).to_bytes(nb, "big")) for v in vals],
                    dtype=np.uint8)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0xD9F)


def test_device_keygen_byte_identical_to_host(rng):
    """The Pallas DPF keygen walk produces the same bytes as the host
    walk — same K-packed kernel as PR 10 keygen, minus cw_v — with no
    counted fallback along the way."""
    lam = DPF_DEVICE_LAM
    ck = _cipher_keys(rng, lam)
    before = dpf_device_fallback_count()
    for k_num in (1, 3):
        alphas = rng.integers(0, 256, (k_num, NB), dtype=np.uint8)
        betas = rng.integers(0, 256, (k_num, lam), dtype=np.uint8)
        s0s = random_s0s(k_num, lam, rng)
        dev = dpf_gen_on_device(lam, ck, alphas, betas, s0s)
        host = dpf_gen_batch(_prg(lam, ck), alphas, betas, s0s)
        assert dev.to_bytes() == host.to_bytes()
    assert dpf_device_fallback_count() == before


def test_eval_points_vs_oracle_both_parties(rng):
    """XOR of the two per-point share walks equals the golden model at
    every probed point — the boundary x = alpha, its neighbours, and
    random points — for every packed key."""
    lam = 16
    ck = _cipher_keys(rng, lam)
    prg = _prg(lam, ck)
    alpha_vals = [0, 0xFFFF, int(rng.integers(1, 0xFFFF))]
    alphas = _alpha_bytes(alpha_vals, NB)
    betas = rng.integers(0, 256, (len(alpha_vals), lam), dtype=np.uint8)
    bundle = dpf_gen_batch(prg, alphas, betas,
                           random_s0s(len(alpha_vals), lam, rng))
    probe = sorted({v for a in alpha_vals
                    for v in (max(a - 1, 0), a, min(a + 1, 0xFFFF))}
                   | {int(x) for x in rng.integers(0, 1 << 16, 8)})
    xs = _alpha_bytes(probe, NB)
    y0 = dpf_eval_points(prg, bundle.for_party(0), 0, xs)
    y1 = dpf_eval_points(prg, bundle.for_party(1), 1, xs)
    recon = y0 ^ y1
    for i, a in enumerate(alpha_vals):
        want = dpf_oracle(xs, a, betas[i])
        np.testing.assert_array_equal(recon[i], want)


def test_wire_roundtrip_and_party_restriction(rng):
    lam = 16
    bundle = dpf_gen_batch(
        _prg(lam, _cipher_keys(rng, lam)),
        rng.integers(0, 256, (2, NB), dtype=np.uint8),
        rng.integers(0, 256, (2, lam), dtype=np.uint8),
        random_s0s(2, lam, rng))
    frame = bundle.to_bytes()
    back = DpfBundle.from_bytes(frame)
    for name in ("s0s", "cw_s", "cw_t", "cw_np1"):
        np.testing.assert_array_equal(getattr(back, name),
                                      getattr(bundle, name))
    # the typed-frame dispatcher routes proto=2 here
    assert isinstance(decode_proto_frame(frame), DpfBundle)
    # party restriction drops the other seed column, nothing else
    p0 = bundle.for_party(0)
    assert p0.s0s.shape[1] == 1
    np.testing.assert_array_equal(p0.s0s[:, 0], bundle.s0s[:, 0])
    with pytest.raises(ShapeError, match="already party-restricted"):
        p0.for_party(0)
    with pytest.raises(ValueError, match="party must be 0 or 1"):
        bundle.for_party(2)


def test_keygen_input_validation(rng):
    lam = 16
    prg = _prg(lam, _cipher_keys(rng, lam))
    good_a = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    good_b = rng.integers(0, 256, (2, lam), dtype=np.uint8)
    good_s = random_s0s(2, lam, rng)
    with pytest.raises(ShapeError):
        dpf_gen_batch(prg, good_a.astype(np.int64), good_b, good_s)
    with pytest.raises(ShapeError):
        dpf_gen_batch(prg, good_a, good_b[:1], good_s)
    with pytest.raises(ShapeError):
        dpf_gen_batch(prg, good_a, good_b, good_s[:, :1])
    with pytest.raises(ValueError, match="party must be 0 or 1"):
        dpf_eval_points(prg, dpf_gen_batch(prg, good_a, good_b, good_s),
                        2, good_a)


def test_repr_redacts_key_material(rng):
    lam = 16
    bundle = dpf_gen_batch(
        _prg(lam, _cipher_keys(rng, lam)),
        rng.integers(0, 256, (1, NB), dtype=np.uint8),
        rng.integers(0, 256, (1, lam), dtype=np.uint8),
        random_s0s(1, lam, rng))
    text = repr(bundle)
    assert "redacted" in text
    assert bundle.s0s.tobytes().hex()[:16] not in text
