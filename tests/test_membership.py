"""dcf_tpu.serve.membership: autonomous ring membership (ISSUE 15).

Covers the membership controller's three verbs — health-driven
auto-eject with pre-commit re-replication (live via the anti-entropy
pull, durable via ``KeyStore.replicate_to``), graceful
warm-before-admit join, and the three-phase drain with its deferred
in-flight forget — plus the ring-epoch fence end to end
(``RingEpochError`` / ``E_EPOCH``: adopt-or-refuse at the service,
typed hinted refusal over the wire, a stale router structurally
refused), the membership/health interleavings (eject racing an
in-flight forwarded eval, a join racing a mid-warm registration, a
drain racing a hot-swap — all typed, never bit-mismatched), the
``membership.migrate`` fault seam's abort containment, the
``KeyStore.replicate_to`` bounded transient-retry satellite, and the
control-verb wire-fuzz extension (all five verbs die typed
per-connection, both directions).  The serve_host SIGTERM drain and
the ``pod_bench --churn`` CLI smoke ride the serial slow leg (see
tests/test_cli.py for the latter).
"""

import pathlib
import socket
import struct
import threading
import time

import numpy as np
import pytest

from dcf_tpu import Dcf
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.errors import (
    BackendUnavailableError,
    KeyQuarantinedError,
    RingEpochError,
)
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.serve import (
    DcfRouter,
    EdgeClient,
    EdgeServer,
    KeyStore,
    MembershipController,
    ShardMap,
    ShardSpec,
)
from dcf_tpu.serve.edge import (
    E_EPOCH,
    decode_response,
    encode_digest,
    encode_ping,
    encode_pong,
    encode_register,
    encode_request,
    encode_sync,
)
from dcf_tpu.serve.health import DOWN, UP
from dcf_tpu.testing import faults
from dcf_tpu.testing.faults import FakeClock

pytestmark = pytest.mark.membership

NB, LAM = 2, 16


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0x15E)


@pytest.fixture(scope="module")
def ck(rng):
    return [rng.bytes(32), rng.bytes(32)]


@pytest.fixture(scope="module")
def dcf(ck):
    return Dcf(NB, LAM, ck, backend="bitsliced")


@pytest.fixture(scope="module")
def prg(ck):
    return HirosePrgNp(LAM, ck)


def mk_bundle(dcf, rng):
    alphas = rng.integers(0, 256, (1, NB), dtype=np.uint8)
    betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
    return dcf.gen(alphas, betas, rng=rng)


def recon_oracle(prg, kb, xs):
    return eval_batch_np(prg, 0, kb.for_party(0), xs) ^ \
        eval_batch_np(prg, 1, kb.for_party(1), xs)


class MemberPod:
    """N in-process shard hosts behind one router with a
    ``MembershipController`` on a fake clock — the tier-1 stand-in
    for pod_bench --churn's subprocesses."""

    def __init__(self, dcf, n=3, ctrl_kw=None, stores=None):
        self.dcf = dcf
        self.svcs, self.servers, specs = [], [], []
        for i in range(n):
            svc, srv, spec = self._mk_shard(f"shard-{i}")
            self.svcs.append(svc)
            self.servers.append(srv)
            specs.append(spec)
        self.map = ShardMap(specs)
        self._index = {s.host_id: i for i, s in enumerate(specs)}
        self.router = DcfRouter(
            self.map, n_bytes=NB, probe_fail_n=2, probe_recover_m=2,
            reconnect_backoff_s=0.01, max_backoff_s=0.05,
            probe_interval_s=0.05)
        self.clk = FakeClock(100.0)
        kw = dict(eject_grace_s=2.0, drain_grace_s=1.0, min_hosts=2)
        kw.update(ctrl_kw or {})
        self.ctrl = MembershipController(self.router, clock=self.clk,
                                         stores=stores, **kw)

    def _mk_shard(self, host_id):
        svc = self.dcf.serve(max_batch=32, max_delay_ms=1.0)
        svc.start()
        srv = EdgeServer(svc).start()
        return svc, srv, ShardSpec(host_id, *srv.address)

    def add_shard(self, host_id):
        """A started-but-unadmitted extra host (the join candidate)."""
        svc, srv, spec = self._mk_shard(host_id)
        self.svcs.append(svc)
        self.servers.append(srv)
        self._index[host_id] = len(self.svcs) - 1
        return spec

    def svc_of(self, host_id):
        return self.svcs[self._index[host_id]]

    def key_owned_by(self, host_id, prefix="mb-key", ring=None):
        ring = ring if ring is not None else self.router.map
        n = 0
        while True:
            name = f"{prefix}-{n}"
            if ring.owner(name).host_id == host_id:
                return name
            n += 1

    def kill(self, host_id):
        i = self._index[host_id]
        self.servers[i].close()
        self.svcs[i].close(drain=False)

    def pump_until(self, host_id, state, rounds=120, sleep=0.05):
        for _ in range(rounds):
            if self.router.health.pump()[host_id] == state:
                return True
            time.sleep(sleep)
        return False

    def close(self):
        self.ctrl.close()
        self.router.close()
        for srv in self.servers:
            srv.close()
        for svc in self.svcs:
            try:
                svc.close(drain=False)
            except Exception:  # fallback-ok: best-effort teardown of
                # an already-killed shard
                pass


# ------------------------------------------------- config contracts


def test_controller_validates_config(dcf):
    pod = MemberPod(dcf, n=2)
    try:
        with pytest.raises(ValueError):
            MembershipController(pod.router, eject_grace_s=-1)
        with pytest.raises(ValueError):
            MembershipController(pod.router, min_hosts=0)
        with pytest.raises(ValueError):
            MembershipController(pod.router, poll_interval_s=0)
    finally:
        pod.close()


def test_set_ring_epoch_monotonic_contract(dcf):
    pod = MemberPod(dcf, n=2)
    try:
        pod.router.set_ring(pod.map, epoch=3)
        assert pod.router.ring_epoch == 3
        for stale in (3, 1, 0):
            with pytest.raises(ValueError, match="monotonic"):
                pod.router.set_ring(pod.map, epoch=stale)
        assert pod.router.metrics_snapshot()["router_ring_epoch"] == 3
    finally:
        pod.close()


# ------------------------------------------------- auto-eject


def test_auto_eject_after_grace_rereplicates_live_keys(dcf, prg, rng):
    """The tentpole loop: a shard DOWN past the grace is auto-ejected
    — the ring shrinks, the epoch bumps, and every key it held is on
    its NEW placement (generation preserved) before the swap commits.
    An in-flight/post-kill request for a victim key resolves typed
    (hinted refusal) or bit-exact via failover — never mismatched —
    and after the eject the key serves NORMAL traffic bit-exact on
    the new ring."""
    pod = MemberPod(dcf, n=3)
    try:
        victim = "shard-0"
        name = pod.key_owned_by(victim)
        kb = mk_bundle(dcf, rng)
        gen = pod.router.register_key(name, kb)
        other = pod.key_owned_by("shard-1", prefix="mb-other")
        kb2 = mk_bundle(dcf, rng)
        gen2 = pod.router.register_key(other, kb2)
        pod.kill(victim)
        # The eject-racing-a-request interleaving: before the prober
        # has spoken, a NORMAL submit is refused typed WITH a hint
        # (request-plane suspicion), CRITICAL fails over bit-exact.
        xs = rng.integers(0, 256, (5, NB), dtype=np.uint8)
        from dcf_tpu.errors import CircuitOpenError

        with pytest.raises(CircuitOpenError) as ei:
            pod.router.evaluate(name, xs, b=0, timeout=60)
        assert ei.value.retry_after_s is not None
        got = pod.router.evaluate(name, xs, b=0, timeout=60,
                                  priority="critical") ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60,
                                priority="critical")
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
        assert pod.pump_until(victim, DOWN)
        # Grace not elapsed: DOWN alone never ejects.
        assert pod.ctrl.pump() == []
        assert victim in pod.router.map
        pod.clk.advance(1.0)
        assert pod.ctrl.pump() == []
        pod.clk.advance(1.5)  # past eject_grace_s=2.0
        events = pod.ctrl.pump()
        assert [e.kind for e in events] == ["eject"]
        assert events[0].host_id == victim and events[0].epoch == 1
        assert victim not in pod.router.map
        assert pod.router.ring_epoch == 1
        assert len(pod.router.map) == 2
        # Re-replication: BOTH survivors (the key's full new
        # placement) hold the victim's key at the preserved
        # generation; the untouched key kept its own.
        for hid in ("shard-1", "shard-2"):
            digest = pod.svc_of(hid).replication_digest()
            assert digest.get(name) == gen, (hid, digest)
        placed = {s.host_id for s in
                  pod.router.map.placement(other, replicas=1)}
        for hid in placed:
            assert pod.svc_of(hid).replication_digest()[other] == gen2
        # ...and the ejected ring serves NORMAL traffic bit-exact.
        got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
        snap = pod.router.metrics_snapshot()
        assert snap["membership_ejections_total"] == 1
        assert snap["membership_ring_size"] == 2
        assert snap["router_ring_epoch"] == 1
        # The victim's per-host state and series are gone (the
        # set_ring forget discipline).
        assert victim not in pod.router._pools
        leftovers = {k for k in snap if victim in k}
        assert leftovers == set(), leftovers
    finally:
        pod.close()


def test_eject_skipped_below_min_hosts_and_during_multi_failure(
        dcf, rng):
    """Safety rails: auto-eject never shrinks the ring below
    ``min_hosts`` (a 2-host ring keeps its DOWN member — promotion
    serves, ejection would strand the keys on a lone host), and never
    runs while a SECOND shard is DOWN (a double failure is recovery
    territory, not reconfiguration)."""
    pod = MemberPod(dcf, n=2)
    try:
        pod.kill("shard-1")
        assert pod.pump_until("shard-1", DOWN)
        pod.ctrl.pump()
        pod.clk.advance(10.0)
        assert pod.ctrl.pump() == []
        assert "shard-1" in pod.router.map
        snap = pod.router.metrics_snapshot()
        assert snap["membership_eject_skipped_total"] >= 1
        assert snap["membership_ejections_total"] == 0
    finally:
        pod.close()
    pod = MemberPod(dcf, n=3)
    try:
        pod.kill("shard-0")
        pod.kill("shard-1")
        assert pod.pump_until("shard-0", DOWN)
        assert pod.pump_until("shard-1", DOWN)
        pod.ctrl.pump()
        pod.clk.advance(10.0)
        assert pod.ctrl.pump() == []
        assert len(pod.router.map) == 3  # both skipped: multi-failure
        assert pod.router.metrics_snapshot()[
            "membership_eject_skipped_total"] >= 2
    finally:
        pod.close()


def test_eject_replicates_durable_frames_via_stores(dcf, rng,
                                                    tmp_path):
    """The durable half: the victim's on-disk store survives its
    process and is the re-replication SOURCE — after the eject, every
    store in the key's new placement holds the frame at the
    provisioned generation (``KeyStore.replicate_to``, monotonic
    guard), and the zero-loss audit passes."""
    stores = {f"shard-{i}": KeyStore(str(tmp_path / f"shard-{i}"))
              for i in range(3)}
    pod = MemberPod(dcf, n=3, stores=stores)
    try:
        victim = "shard-0"
        name = pod.key_owned_by(victim, prefix="mb-dur")
        kb = mk_bundle(dcf, rng)
        gen = pod.router.register_key(name, kb)  # live everywhere the
        # ring places it, so serving survives the eject
        placed = [s.host_id
                  for s in pod.router.map.placement(name, replicas=1)]
        stores[placed[0]].put(name, kb, generation=gen)
        stores[placed[0]].replicate_to(stores[placed[1]], name)
        pod.kill(victim)
        assert pod.pump_until(victim, DOWN)
        pod.ctrl.pump()
        pod.clk.advance(3.0)
        assert [e.kind for e in pod.ctrl.pump()] == ["eject"]
        new_placed = {s.host_id for s in
                      pod.router.map.placement(name, replicas=1)}
        assert victim not in new_placed
        for hid in new_placed:
            assert stores[hid].digest().get(name) == gen, hid
        assert pod.ctrl.lost_keys(exclude={victim}) == []
        snap = pod.router.metrics_snapshot()
        assert snap["membership_durable_replications_total"] >= 1
        assert snap["membership_lost_keys_total"] == 0
    finally:
        pod.close()


def test_migrate_seam_aborts_change_typed_then_retries(dcf, rng):
    """The ``membership.migrate`` fault seam: a migration source dying
    mid-change ABORTS the eject — counted, ring and epoch untouched —
    and a later pump (seam disarmed) completes it.  Never a
    half-migrated commit."""
    pod = MemberPod(dcf, n=3)
    try:
        victim = "shard-0"
        name = pod.key_owned_by(victim)
        gen = pod.router.register_key(name, mk_bundle(dcf, rng))
        pod.kill(victim)
        assert pod.pump_until(victim, DOWN)
        pod.ctrl.pump()
        pod.clk.advance(3.0)
        with faults.inject("membership.migrate"):
            assert pod.ctrl.pump() == []
            assert victim in pod.router.map
            assert pod.router.ring_epoch == 0
        snap = pod.router.metrics_snapshot()
        assert snap["membership_change_failures_total"] >= 1
        assert snap["membership_ejections_total"] == 0
        # Disarmed: the retry commits.
        assert [e.kind for e in pod.ctrl.pump()] == ["eject"]
        assert victim not in pod.router.map
        for hid in ("shard-1", "shard-2"):
            assert pod.svc_of(hid).replication_digest()[name] == gen
    finally:
        pod.close()


# ------------------------------------------------- graceful join


def test_join_warms_before_admission_and_converges_racing_reg(
        dcf, prg, rng):
    """Graceful join: the newcomer is warmed through the anti-entropy
    pull BEFORE the swap (its digest holds every key the prospective
    ring places on it, generations preserved — no cold-miss storm),
    the epoch bumps, and a registration racing the warm is converged
    by the post-admission sweep.  All outcomes typed; the racing key
    serves its registered bits bit-exact."""
    pod = MemberPod(dcf, n=2)
    try:
        bundles, gens = {}, {}
        for i in range(4):
            name = f"mb-join-{i}"
            bundles[name] = mk_bundle(dcf, rng)
            gens[name] = pod.router.register_key(name, bundles[name])
        spec = pod.add_shard("shard-2")
        prospective = pod.router.map.with_host(spec)
        race = pod.key_owned_by("shard-2", prefix="mb-race",
                                ring=prospective)
        bundles[race] = mk_bundle(dcf, rng)
        orig = pod.ctrl._converge
        calls = {"n": 0}

        def racing_converge(*a, **kw):
            moved = orig(*a, **kw)
            calls["n"] += 1
            if calls["n"] == 1:
                # Mid-warm, pre-admission: the registration lands on
                # the OLD 2-host ring — the post-admit sweep must
                # carry it onto the newcomer.
                gens[race] = pod.router.register_key(race,
                                                     bundles[race])
            return moved

        pod.ctrl._converge = racing_converge
        ev = pod.ctrl.join(spec)
        assert ev.kind == "join" and ev.epoch == 1
        assert "shard-2" in pod.router.map
        assert pod.router.ring_epoch == 1
        assert calls["n"] == 2  # warm + post-admit sweep
        digest = pod.svc_of("shard-2").replication_digest()
        for name, gen in gens.items():
            placed = {s.host_id for s in
                      pod.router.map.placement(name, replicas=1)}
            if "shard-2" in placed:
                assert digest.get(name) == gen, (name, digest)
        assert digest.get(race) == gens[race]
        xs = rng.integers(0, 256, (6, NB), dtype=np.uint8)
        for name in (race, sorted(gens)[0]):
            got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
                pod.router.evaluate(name, xs, b=1, timeout=60)
            assert np.array_equal(got,
                                  recon_oracle(prg, bundles[name], xs))
        assert pod.router.metrics_snapshot()[
            "membership_joins_total"] == 1
    finally:
        pod.close()


def test_join_aborts_typed_on_unreachable_host_and_cleans_up(dcf,
                                                             rng):
    pod = MemberPod(dcf, n=2)
    try:
        pod.router.register_key("mb-ja", mk_bundle(dcf, rng))
        dead = ShardSpec("shard-dead", "127.0.0.1", 1)
        with pytest.raises(BackendUnavailableError):
            pod.ctrl.join(dead)
        assert "shard-dead" not in pod.router.map
        assert "shard-dead" not in pod.router._pools
        assert pod.router.ring_epoch == 0
        snap = pod.router.metrics_snapshot()
        assert snap["membership_change_failures_total"] == 1
        assert snap["membership_joins_total"] == 0
        with pytest.raises(ValueError, match="already in the ring"):
            pod.ctrl.join(pod.map.hosts()[0])
    finally:
        pod.close()


# ------------------------------------------------- graceful drain


def test_drain_migrates_defers_forget_and_converges_hot_swap(
        dcf, prg, rng):
    """The three-phase drain: frames migrate (the drainee is the
    source), the swap commits under a fresh epoch, and the drainee's
    pool survives until the in-flight grace elapses on the clock —
    only then is it forgotten (pump completes it, typed event).  A
    hot-swap racing the migration is converged by the post-swap
    sweep: the key serves the NEW bundle's bits on the new ring,
    never the old's, never mismatched."""
    pod = MemberPod(dcf, n=3)
    try:
        drainee = "shard-0"
        name = pod.key_owned_by(drainee, prefix="mb-drain")
        kb_old = mk_bundle(dcf, rng)
        pod.router.register_key(name, kb_old)
        kb_new = mk_bundle(dcf, rng)
        swapped = {}
        orig = pod.ctrl._converge
        calls = {"n": 0}

        def racing_converge(*a, **kw):
            moved = orig(*a, **kw)
            calls["n"] += 1
            if calls["n"] == 1:
                # Post-migration, pre-swap: the hot-swap lands on the
                # OLD ring (the drainee is still the owner) at a
                # strictly newer generation.
                swapped["gen"] = pod.router.register_key(name, kb_new)
            return moved

        pod.ctrl._converge = racing_converge
        ev = pod.ctrl.drain(drainee)
        assert ev.kind == "drain" and ev.epoch == 1
        assert drainee not in pod.router.map
        assert pod.router.ring_epoch == 1
        # Retained through the grace: the pool is still installed for
        # in-flight relays...
        assert drainee in pod.router._pools
        assert pod.ctrl.draining() == {drainee: pytest.approx(101.0)}
        assert pod.ctrl.pump() == []  # grace not elapsed
        assert drainee in pod.router._pools
        # ...and the hot-swap converged onto the new owner before the
        # drainee goes away: newest generation, newest bits.
        placed = {s.host_id for s in
                  pod.router.map.placement(name, replicas=1)}
        for hid in placed:
            assert pod.svc_of(hid).replication_digest()[name] \
                == swapped["gen"]
        xs = rng.integers(0, 256, (7, NB), dtype=np.uint8)
        got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb_new, xs))
        pod.clk.advance(1.5)
        events = pod.ctrl.pump()
        assert [e.kind for e in events] == ["drain-complete"]
        assert drainee not in pod.router._pools
        assert pod.ctrl.draining() == {}
        snap = pod.router.metrics_snapshot()
        assert snap["membership_drains_total"] == 1
        assert snap["membership_draining_hosts"] == 0
        leftovers = {k for k in snap if drainee in k}
        assert leftovers == set(), leftovers
    finally:
        pod.close()


def test_drain_validations(dcf):
    pod = MemberPod(dcf, n=1, ctrl_kw=dict(min_hosts=1))
    try:
        with pytest.raises(ValueError, match="not in the ring"):
            pod.ctrl.drain("shard-9")
        with pytest.raises(ValueError, match="last host"):
            pod.ctrl.drain("shard-0")
    finally:
        pod.close()


def test_rejoin_within_drain_grace_does_not_wedge_pump(dcf, prg, rng):
    """A drained host that re-joins BEFORE its in-flight grace elapses
    (a rolling restart faster than ``drain_grace_s``) must not wedge
    the control loop: the retained pool is a ring member's pool again,
    so the deferred forget is SKIPPED — the drain window still closes
    with its typed event, later pumps keep running (auto-eject stays
    armed), and the host keeps serving through the surviving link."""
    pod = MemberPod(dcf, n=3)
    try:
        name = pod.key_owned_by("shard-0", prefix="mb-rr")
        kb = mk_bundle(dcf, rng)
        pod.router.register_key(name, kb)
        spec = next(s for s in pod.map.hosts()
                    if s.host_id == "shard-0")
        assert pod.ctrl.drain("shard-0").kind == "drain"
        ev = pod.ctrl.join(spec)  # same live process, within the grace
        assert ev.kind == "join" and "shard-0" in pod.router.map
        pod.clk.advance(1.5)  # past drain_grace_s=1.0
        assert [e.kind for e in pod.ctrl.pump()] == ["drain-complete"]
        assert "shard-0" in pod.router._pools  # the member's pool
        assert pod.ctrl.draining() == {}
        assert pod.ctrl.pump() == []  # the control loop is alive
        xs = rng.integers(0, 256, (5, NB), dtype=np.uint8)
        got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
    finally:
        pod.close()


def test_post_commit_sweep_failure_does_not_abort_the_change(dcf,
                                                             rng):
    """A transient failure in the POST-swap convergence sweep lands
    AFTER the commit: the change must still report committed (event,
    counters, the drain-grace bookkeeping that pump's deferred forget
    reads) with the failure counted — re-raising would leak the
    retained pool forever and make a retry die on the ring-membership
    validation."""
    pod = MemberPod(dcf, n=3)
    try:
        pod.router.register_key(
            pod.key_owned_by("shard-0", prefix="mb-ps"),
            mk_bundle(dcf, rng))
        orig = pod.ctrl._converge
        calls = {"n": 0}

        def flaky_converge(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 2:  # the post-swap sweep
                raise BackendUnavailableError("peer died post-commit")
            return orig(*a, **kw)

        pod.ctrl._converge = flaky_converge
        ev = pod.ctrl.drain("shard-0")
        assert ev.kind == "drain" and ev.epoch == 1
        assert "shard-0" not in pod.router.map
        assert "shard-0" in pod.ctrl.draining()
        snap = pod.router.metrics_snapshot()
        assert snap["membership_drains_total"] == 1
        assert snap["membership_change_failures_total"] == 1
        pod.clk.advance(1.5)
        assert [e.kind for e in pod.ctrl.pump()] == ["drain-complete"]
        assert "shard-0" not in pod.router._pools
    finally:
        pod.close()


def test_join_redials_when_rejoining_host_changed_address(dcf, prg,
                                                          rng):
    """A drained host's REPLACEMENT process on a new port re-joining
    within the grace: the retained pool is wired to the OLD endpoint,
    so ``preconnect``/``set_ring`` must re-dial instead of reusing it
    — otherwise every forward for the host lands on the dying
    process."""
    pod = MemberPod(dcf, n=3)
    try:
        name = pod.key_owned_by("shard-0", prefix="mb-ra")
        kb = mk_bundle(dcf, rng)
        pod.router.register_key(name, kb)
        assert pod.ctrl.drain("shard-0").kind == "drain"
        old_port = pod.router._pools["shard-0"].port
        spec = pod.add_shard("shard-0")  # same identity, fresh port
        assert spec.port != old_port
        assert pod.ctrl.join(spec).kind == "join"
        assert pod.router._pools["shard-0"].port == spec.port
        # The warm landed on the NEW process and the key serves.
        assert pod.svc_of("shard-0").replication_digest().get(name)
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
    finally:
        pod.close()


def test_lost_keys_audit_counts_each_loss_once(dcf, rng, tmp_path):
    """``lost_keys`` is a read-only audit: polling it must not inflate
    ``membership_lost_keys_total`` — each loss counts once, and a key
    lost, healed, then lost again counts as a fresh loss."""
    stores = {"shard-0": KeyStore(str(tmp_path / "s0")),
              "shard-1": KeyStore(str(tmp_path / "s1"))}
    pod = MemberPod(dcf, n=2, stores=stores)
    try:
        stores["shard-0"].put("lk", mk_bundle(dcf, rng), generation=1)
        assert pod.ctrl.lost_keys(exclude={"shard-0"}) == ["lk"]
        assert pod.ctrl.lost_keys(exclude={"shard-0"}) == ["lk"]
        assert pod.router.metrics_snapshot()[
            "membership_lost_keys_total"] == 1
        # Healed (the key reaches another store), then lost again —
        # the second loss is a fresh one and counts.
        stores["shard-0"].replicate_to(stores["shard-1"], "lk")
        assert pod.ctrl.lost_keys(exclude={"shard-0"}) == []
        assert pod.ctrl.lost_keys(
            exclude={"shard-0", "shard-1"}) == ["lk"]
        assert pod.router.metrics_snapshot()[
            "membership_lost_keys_total"] == 2
    finally:
        pod.close()


def test_unreachable_store_does_not_wedge_eject(dcf, rng, tmp_path):
    """A store whose digest read FAILS (the disk died with its
    process) must not wedge membership: the eject proceeds without it
    — counted ``membership_store_unreachable_total`` — instead of
    aborting on every pump forever while the victim's keys sit on a
    lone promoted replica."""
    stores = {f"shard-{i}": KeyStore(str(tmp_path / f"shard-{i}"))
              for i in range(3)}
    pod = MemberPod(dcf, n=3, stores=stores)
    try:
        victim = "shard-0"
        name = pod.key_owned_by(victim, prefix="mb-ds")
        kb = mk_bundle(dcf, rng)
        gen = pod.router.register_key(name, kb)
        placed = [s.host_id
                  for s in pod.router.map.placement(name, replicas=1)]
        stores[placed[0]].put(name, kb, generation=gen)
        stores[placed[0]].replicate_to(stores[placed[1]], name)

        def dead_digest():
            raise OSError("mount gone")

        stores[victim].digest = dead_digest
        pod.kill(victim)
        assert pod.pump_until(victim, DOWN)
        pod.ctrl.pump()
        pod.clk.advance(3.0)
        assert [e.kind for e in pod.ctrl.pump()] == ["eject"]
        assert victim not in pod.router.map
        assert pod.router.metrics_snapshot()[
            "membership_store_unreachable_total"] >= 1
        for hid in pod.router.map.placement_ids(name, replicas=1):
            assert stores[hid].digest().get(name) == gen, hid
    finally:
        pod.close()


def test_durable_copy_falls_back_to_another_holder(dcf, rng,
                                                   tmp_path):
    """One source exhausting its bounded retries must not abort the
    change while ANOTHER replica holds the same generation: the copy
    falls through to the next holder, and only an all-holders failure
    aborts (the conservative direction)."""
    stores = {f"shard-{i}": KeyStore(str(tmp_path / f"s{i}"))
              for i in range(3)}
    pod = MemberPod(dcf, n=3, stores=stores)
    try:
        kb = mk_bundle(dcf, rng)
        name = "mb-fb"
        ring = pod.router.map
        dst = [s.host_id
               for s in ring.placement(name, replicas=1)][1]
        holders = sorted(h for h in stores if h != dst)
        for h in holders:
            stores[h].put(name, kb, generation=2)

        def boom(*a, **kw):
            raise BackendUnavailableError("source store down")

        stores[holders[0]].replicate_to = boom
        assert pod.ctrl._replicate_durable(ring, exclude=set()) == 1
        assert stores[dst].digest().get(name) == 2
        # Every holder failing IS the abort.
        stores[holders[1]].replicate_to = boom
        stores[dst].delete(name)
        with pytest.raises(BackendUnavailableError):
            pod.ctrl._replicate_durable(ring, exclude=set())
    finally:
        pod.close()


def test_unadmitted_request_cannot_adopt_epoch(dcf, prg, rng):
    """The fence must not be a single-packet DoS: a REQUEST frame
    from an UNADMITTED sender (unknown tenant) carrying a huge epoch
    is refused WITHOUT adoption — the observed maximum moves only on
    admitted requests (and the trusted PING/REGISTER verbs), so a
    forged frame cannot fence out the real router."""
    from dcf_tpu.serve import TenantSpec

    svc = dcf.serve(max_batch=32, max_delay_ms=1.0,
                    tenants=(TenantSpec("router", "critical"),))
    svc.start()
    server = EdgeServer(svc).start()
    addr = server.address
    kb = mk_bundle(dcf, rng)
    svc.register_key("ep-key", kb)
    svc.check_ring_epoch(3)  # the pod's real epoch
    xs = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    try:
        forged = encode_request(1, "intruder", "ep-key", 0, 255, None,
                                xs.tobytes(), NB, 2,
                                epoch=(1 << 32) - 1)
        frames = _raw_exchange(addr, forged)
        assert [f[0] for f in frames] == ["error"]
        assert svc.ring_epoch == 3  # NOT adopted
        with EdgeClient(*addr, n_bytes=NB, tenant="router") as c:
            # The real router still serves at the real epoch...
            y = c.submit_bytes("ep-key", xs.tobytes(), b=0,
                               epoch=3).result(timeout=60)
            assert np.array_equal(
                y, eval_batch_np(prg, 0, kb.for_party(0), xs))
            # ...and an ADMITTED newer epoch still adopts.
            c.submit_bytes("ep-key", xs.tobytes(), b=0,
                           epoch=4).result(timeout=60)
        assert svc.ring_epoch == 4
    finally:
        server.close()
        svc.close(drain=False)


def test_edge_graceful_drain_delivers_queued_responses(dcf, prg, rng):
    """The serve_host shutdown ordering, in process: after
    ``stop_accepting`` (new dials refused, live links OPEN) a request
    already accepted is DRAINED — ``close(drain=True)`` completes it
    and ``EdgeServer.close(drain_s=)`` flushes the response over the
    still-open connection — so a planned restart never drops acked
    work."""
    svc = dcf.serve(max_batch=32, max_delay_ms=50.0)
    svc.start()
    server = EdgeServer(svc).start()
    addr = server.address
    kb = mk_bundle(dcf, rng)
    svc.register_key("gd-key", kb)
    client = EdgeClient(*addr, n_bytes=NB)
    try:
        xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
        fut = client.submit("gd-key", xs, b=0)
        server.stop_accepting()
        with pytest.raises(OSError):
            socket.create_connection(addr, timeout=2)
        svc.close(drain=True)
        server.close(drain_s=5.0)
        y = fut.result(timeout=30)
        assert np.array_equal(
            y, eval_batch_np(prg, 0, kb.for_party(0), xs))
    finally:
        client.close()
        server.close()
        svc.close(drain=False)


# ------------------------------------------------- the epoch fence


def test_epoch_fence_adopt_and_refuse_in_process_and_wire(dcf, prg,
                                                          rng):
    """The fence end to end: a service adopts a newer epoch
    (monotonic max, gauge written), passes an equal one, refuses an
    older one typed with a retry hint (counted) — in-process AND over
    the wire for REQUEST, REGISTER and PING frames (``E_EPOCH``, the
    connection surviving every refusal).  The key keeps serving the
    current-epoch bits after each refusal, and the PONG echoes the
    shard's epoch (the convergence probe)."""
    svc = dcf.serve(max_batch=32, max_delay_ms=1.0)
    svc.start()
    server = EdgeServer(svc).start()
    try:
        assert svc.ring_epoch == 0
        assert svc.check_ring_epoch(0) == 0  # unfenced: no-op
        assert svc.check_ring_epoch(5) == 5  # adopt
        assert svc.check_ring_epoch(5) == 5  # equal passes
        with pytest.raises(RingEpochError) as ei:
            svc.check_ring_epoch(4)
        assert ei.value.retry_after_s is not None
        snap = svc.metrics_snapshot()
        assert snap["serve_ring_epoch"] == 5
        assert snap["serve_epoch_fenced_total"] == 1
        kb = mk_bundle(dcf, rng)
        svc.register_key("fence-key", kb)
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        with EdgeClient(*server.address, n_bytes=NB) as c:
            # PING: adoption + echo.
            assert c.ping_epoch(timeout=30, epoch=7) == 7
            assert c.ping_epoch(timeout=30) == 7  # unfenced echo
            # REQUEST at a stale epoch: typed, hinted, E_EPOCH.
            with pytest.raises(RingEpochError) as ei:
                c.submit_bytes("fence-key", xs.data, b=0,
                               epoch=6).result(30)
            assert ei.value.wire_code == E_EPOCH
            assert ei.value.retry_after_s is not None
            # REGISTER at a stale epoch: same fence, key untouched.
            with pytest.raises(RingEpochError):
                c.register_frame("fence-key",
                                 mk_bundle(dcf, rng).to_bytes(),
                                 epoch=3)
            # A stale PING is refused too (a stale prober must learn).
            with pytest.raises(RingEpochError):
                c.ping(timeout=30, epoch=2)
            # The connection survived all three refusals, and the key
            # serves the CURRENT bits at the current epoch.
            y0 = c.submit_bytes("fence-key", xs.data, b=0,
                                epoch=7).result(60)
            assert np.array_equal(
                y0, eval_batch_np(prg, 0, kb.for_party(0), xs))
        assert svc.metrics_snapshot()[
            "serve_epoch_fenced_total"] == 4
    finally:
        server.close()
        svc.close(drain=False)


def test_stale_router_structurally_refused(dcf, prg, rng):
    """Two routers over one pod: the one that applied the membership
    commit (higher epoch) keeps serving; the one still on the old
    ring is refused typed ``RingEpochError`` WITH a hint on every
    forward — counted on ``router_stale_epoch_total``, never marked
    shard-suspect (the shard is fine; the ROUTER is stale)."""
    pod = MemberPod(dcf, n=2)
    stale_router = None
    try:
        name = pod.key_owned_by("shard-0")
        kb = mk_bundle(dcf, rng)
        pod.router.register_key(name, kb)
        stale_router = DcfRouter(pod.map, n_bytes=NB)
        stale_router.set_ring(pod.map, epoch=1)
        pod.router.set_ring(pod.map, epoch=2)
        xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
        # The current router's forward teaches the shards epoch 2...
        got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
        # ...after which the stale router is structurally refused.
        with pytest.raises(RingEpochError) as ei:
            stale_router.evaluate(name, xs, b=0, timeout=60)
        assert ei.value.retry_after_s is not None
        snap = stale_router.metrics_snapshot()
        assert snap["router_stale_epoch_total"] >= 1
        assert stale_router.suspect_remaining("shard-0") == 0.0
        # Refreshing the stale router's ring re-admits it.
        stale_router.set_ring(pod.map, epoch=2)
        got = stale_router.evaluate(name, xs, b=0, timeout=60) ^ \
            stale_router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
    finally:
        if stale_router is not None:
            stale_router.close()
        pod.close()


# ------------------------------------------------- replicate_to retry


def test_replicate_to_bounded_retry_with_backoff(dcf, rng, tmp_path):
    """The ISSUE 15 satellite: a transient transport ``OSError`` on
    the destination publish is retried with doubling backoff —
    counted — and succeeds; exhaustion dies typed
    ``BackendUnavailableError`` with the cause chained.  A one-packet
    blip must not abort a whole migration."""
    src = KeyStore(str(tmp_path / "src"))
    dst = KeyStore(str(tmp_path / "dst"))
    kb = mk_bundle(dcf, rng)
    src.put("rk", kb, generation=5)
    naps: list = []
    with faults.inject_schedule("store.write", window_evals=2,
                                exc=OSError("injected blip")):
        gen = src.replicate_to(dst, "rk", retries=3, backoff_s=0.05,
                               sleep=naps.append)
    assert gen == 5
    assert dst.digest() == {"rk": 5}
    assert naps == [0.05, 0.1]  # doubling backoff, one per retry
    assert src._metrics.counter(
        "serve_store_replicate_retries_total").value == 2
    # Exhaustion: typed, cause-chained, counted per attempt.
    dst2 = KeyStore(str(tmp_path / "dst2"))
    with faults.inject_schedule("store.write", window_evals=99,
                                exc=OSError("still down")):
        with pytest.raises(BackendUnavailableError) as ei:
            src.replicate_to(dst2, "rk", retries=2, backoff_s=0.01,
                             sleep=naps.append)
    assert isinstance(ei.value.__cause__, OSError)
    assert dst2.digest() == {}
    with pytest.raises(ValueError):
        src.replicate_to(dst2, "rk", retries=-1)
    # Validation failures are NEVER retried: a corrupt source frame
    # quarantines immediately (re-reading damage does not repair it).
    src.put("bad", kb, generation=1)
    ent = src._read_manifest()["bad"]
    path = tmp_path / "src" / ent["file"]
    raw = bytearray(path.read_bytes())
    raw[10] ^= 0xFF
    path.write_bytes(bytes(raw))
    before = len(naps)
    with pytest.raises(KeyQuarantinedError):
        src.replicate_to(dst2, "bad", retries=5, sleep=naps.append)
    assert len(naps) == before  # zero retry naps


# ------------------------------------------------- control-verb fuzz


def _raw_exchange(addr, wire: bytes) -> list:
    s = socket.create_connection(addr, timeout=30)
    data = b""
    try:
        s.sendall(wire)
        s.shutdown(socket.SHUT_WR)
        s.settimeout(30)
        while True:
            try:
                chunk = s.recv(1 << 16)
            except OSError:
                break
            if not chunk:
                break
            data += chunk
    finally:
        s.close()
    frames, off = [], 0
    while off < len(data):
        (body_len,) = struct.unpack_from("<I", data, off)
        frames.append(decode_response(data[off + 4:off + 4 + body_len]))
        off += 4 + body_len
    return frames


def test_wire_fuzz_all_control_verbs_die_typed_per_connection(
        dcf, rng):
    """The ISSUE 15 fuzz satellite, server door: seeded byte-flips,
    truncations and an oversized length prefix over ALL FIVE control
    verbs (PING/PONG/REGISTER/DIGEST/SYNC — PONG and SYNC are
    client-side frames, so even their PRISTINE forms must die typed
    at a server) each cost exactly one connection — never a non-error
    response, never the reader thread, never the accept loop — with a
    healthy pinned connection round-tripping throughout and fresh
    dials accepted after."""
    svc = dcf.serve(max_batch=32, max_delay_ms=1.0)
    svc.start()
    server = EdgeServer(svc).start()
    addr = server.address
    kb = mk_bundle(dcf, rng)
    valid = {
        "ping": encode_ping(11, 0),
        "register": encode_register(12, "fz-key", kb.to_bytes(), 0,
                                    False),
        "digest": encode_digest(13, {"fz-key": 3}, mode=1),
        "pong": encode_pong(14, 0),
        "sync": encode_sync(15, [("fz-key", 1, False, b"notakey")]),
    }
    healthy = EdgeClient(*addr, n_bytes=NB)
    try:
        for verb, frame in sorted(valid.items()):
            mangles = []
            if verb in ("pong", "sync"):
                mangles.append(frame)  # pristine, but not a server
                # frame: the type dispatch itself must kill typed
            for off in rng.choice(len(frame) - 4, size=4,
                                  replace=False):
                buf = bytearray(frame)
                buf[4 + int(off)] ^= 0x41
                mangles.append(bytes(buf))
            mangles.append(frame[: max(len(frame) // 2, 5)])
            mangles.append(struct.pack("<I", 1 << 30))
            for i, wire in enumerate(mangles):
                frames = _raw_exchange(addr, wire)
                for decoded in frames:
                    assert decoded[0] == "error", (verb, i, decoded)
                assert healthy.ping(timeout=30)
                assert not healthy.closed
        # Nothing fuzzed ever registered; the accept loop still dials.
        assert "fz-key" not in svc.replication_digest()
        with EdgeClient(*addr, n_bytes=NB) as fresh:
            assert fresh.ping(timeout=30)
    finally:
        healthy.close()
        server.close()
        svc.close(drain=False)


def test_corrupt_control_response_fails_client_typed(rng):
    """The client direction: a corrupted PONG off the wire fails the
    pending control round trip typed (``BackendUnavailableError`` —
    the reader cannot trust the stream) and latches ``closed``, the
    pool's reconnect signal — never a hang, never an untyped
    escape."""
    lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    host, port = lst.getsockname()[:2]
    box: dict = {}

    def fake_server():
        conn, _ = lst.accept()
        try:
            conn.settimeout(30)
            conn.recv(1 << 16)  # the client's ping frame
            pong = bytearray(encode_pong(1, 0))
            pong[9] ^= 0x7F  # corrupt inside the body: CRC must catch
            conn.sendall(bytes(pong))
        finally:
            conn.close()

    t = threading.Thread(target=fake_server, daemon=True)
    t.start()
    c = EdgeClient(host, port, n_bytes=NB)
    try:
        with pytest.raises(BackendUnavailableError):
            c.ping(timeout=30)
        assert c.closed
    finally:
        c.close()
        lst.close()
        t.join(10)


# ------------------------------------------------- CI satellites


def test_membership_layer_lint_clean():
    """The ISSUE-15 CI satellite: ``serve/membership.py`` sweeps clean
    under ALL six dcflint passes — determinism (grace and drain math
    on the injectable clock only) and secret hygiene (migrations move
    DCFK frames; the controller logs names, hosts, epochs and counts
    only) are the load-bearing ones."""
    from tools.dcflint import run_path

    repo = pathlib.Path(__file__).resolve().parent.parent
    assert run_path(repo / "dcf_tpu" / "serve" / "membership.py") == []


def test_cli_churn_flags_validated_fast():
    """``pod_bench --churn`` applies the fail-fast flag discipline:
    bad shard counts, grace, probe cadence and conflicting scenario
    flags die loudly before any subprocess is spawned."""
    from dcf_tpu import cli

    with pytest.raises(SystemExit, match="shards >= 3"):
        cli.main(["pod_bench", "--churn", "--shards=2"])
    with pytest.raises(SystemExit, match="eject-grace"):
        cli.main(["pod_bench", "--churn", "--eject-grace=0"])
    with pytest.raises(SystemExit, match="probe-interval"):
        cli.main(["pod_bench", "--churn", "--probe-interval=0"])
    with pytest.raises(SystemExit, match="live-bundles"):
        cli.main(["pod_bench", "--churn", "--live-bundles=-1"])
    with pytest.raises(SystemExit, match="separate"):
        cli.main(["pod_bench", "--churn", "--partition"])


# ------------------------------------------------- the slow legs


@pytest.mark.slow
def test_serve_host_sigterm_drains_and_unadvertises(dcf, rng,
                                                    tmp_path):
    """The graceful-shutdown satellite, end to end: a serve_host
    subprocess warm-restores its store, advertises via the ready
    file, and on SIGTERM drains, writes a final metrics snapshot,
    REMOVES the ready file, and exits 0.  (SIGKILL stays the crash
    test — pod_bench's kill soak owns that path.)"""
    import json
    import os
    import signal
    import subprocess
    import sys

    store_dir = tmp_path / "host-store"
    store = KeyStore(str(store_dir))
    kb = mk_bundle(dcf, rng)
    store.put("sh-key", kb, generation=3)
    ready = tmp_path / "ready.json"
    metrics = tmp_path / "metrics.json"
    proc = subprocess.Popen(
        [sys.executable, "-m", "dcf_tpu.cli", "serve_host",
         "--store-dir", str(store_dir), "--ready-file", str(ready),
         "--metrics-file", str(metrics), "--seed", "7",
         "--backend", "cpu", "--max-batch", "32"])
    try:
        deadline = time.monotonic() + 300
        while not ready.exists():
            assert proc.poll() is None, "serve_host died before ready"
            assert time.monotonic() < deadline, "never became ready"
            time.sleep(0.2)
        doc = json.loads(ready.read_text())
        assert doc["restored"] == 1
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(120)
        assert rc == 0
        assert not ready.exists()  # un-advertised on the way out
        snap = json.loads(metrics.read_text())  # final snapshot
        assert snap["serve_store_restored_total"] == 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(30)
        if os.path.exists(str(ready)):
            os.unlink(str(ready))
