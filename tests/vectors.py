"""Shared test vectors, byte-identical to the reference crate's.

These are the hardcoded constants from /root/reference/src/lib.rs:359-370 and
/root/reference/src/prg.rs:79-84 (data, not code): two AES-256 keys, five
alpha values straddling ALPHAS[2] (the last three differ only in the final
byte, 0x55 < 0x56 < 0x57), a fixed beta, and the PRG test seed.
"""

KEYS = [
    b"j9\x1b_\xb3X\xf33\xacW\x15\x1b\x0812K\xb3I\xb9\x90r\x1cN\xb5\xee9W\xd3\xbb@\xc6d",
    b"\x9b\x15\xc8\x0f\xb7\xbc!q\x9e\x89\xb8\xf7\x0e\xa0S\x9dN\xfa\x0c;\x16\xe4\x98\x82b\xfcdy\xb5\x8c{\xc2",
]

ALPHAS = [
    b"K\xa9W\xf5\xdd\x05\xe9\xfc?\x04\xf6\xfbUo\xa8C",
    b"\xc2GK\xda\xc6\xbb\x99\x98Fq\"f\xb7\x8csU",
    b"\xc2GK\xda\xc6\xbb\x99\x98Fq\"f\xb7\x8csV",
    b"\xc2GK\xda\xc6\xbb\x99\x98Fq\"f\xb7\x8csW",
    b"\xef\x96\x97\xd7\x8f\x8a\xa4AP\n\xb35\xb5k\xff\x97",
]

BETA = b"\x03\x11\x97\x12C\x8a\xe9#\x81\xa8\xde\xa8\x8f \xc0\xbb"

PRG_SEED = b"*L\x8f%y\x12Z\x94*E\x8f$+NH\x19"

assert all(len(k) == 32 for k in KEYS)
assert all(len(a) == 16 for a in ALPHAS)
assert len(BETA) == 16 and len(PRG_SEED) == 16
