"""Fixed-point gate suite over the additive output group (ISSUE 20).

Three layers, matching the subsystem's own:

* the GATE ALGEBRA (``protocols.fixedpoint``): signed comparison via
  the DCF offset trick, faithful truncation from two prefix ICs plus an
  additive constant share, and spline sigmoid as an r-shifted MIC —
  each reconstructed bit-exactly against its numpy golden oracle across
  groups, masks (including r=0, N-1 and the sign boundary) and domain
  widths;
* the ADDITIVE PROTOCOL layer underneath (``group="add*"`` threaded
  through keygen/combine): backend-family parity — host/bitsliced/
  prefix facades and the sharded 2x2-mesh backends — both parties, both
  bounds, x exactly on a cut, against the same oracles that pin the XOR
  path;
* the SERVED form (``workloads.gates.GateServer``): component bundles
  registered through ``DcfService`` under derived ids, shares folded
  client-side, hot-swap by re-registration, and (slow leg) a soak under
  injected ``protocols.combine`` faults riding the service's
  retry-then-evict discipline.

Unit tests run in tier-1 on the threaded legs; the fault soak
(``gates and slow``) rides the serial CI leg.
"""

import numpy as np
import pytest

from dcf_tpu import Dcf
from dcf_tpu.errors import ShapeError
from dcf_tpu.protocols import (
    eval_sigmoid_share,
    eval_sign_share,
    eval_trunc_share,
    gate_reconstruct,
    gen_sigmoid_gate,
    gen_sign_gate,
    gen_trunc_gate,
    mic_oracle,
    sigmoid_fixed_oracle,
    sigmoid_table,
    sign_oracle,
    trunc_oracle,
)
from dcf_tpu.protocols.fixedpoint import decode_lanes, encode_lanes
from dcf_tpu.spec import Bound
from dcf_tpu.testing import faults
from dcf_tpu.utils.groups import np_group_add
from dcf_tpu.workloads import GateServer

pytestmark = pytest.mark.gates

NB, LAM = 2, 16
W = 8 * NB
N = 1 << W


@pytest.fixture
def rng():
    return np.random.default_rng(0xF1BED)


@pytest.fixture
def ck(rng):
    return [rng.bytes(32), rng.bytes(32)]


@pytest.fixture
def dcf(ck):
    return Dcf(NB, LAM, ck, backend="bitsliced")


@pytest.fixture
def dcf_low(ck):
    return Dcf(1, LAM, ck, backend="bitsliced")


def gate_points(rng, n=128):
    """Random masked inputs plus every boundary the gates care about:
    0, N-1, the sign boundary, and the f=8 truncation carry edges."""
    return np.concatenate([
        rng.integers(0, N, size=n, dtype=np.int64),
        np.array([0, 1, N - 1, N // 2, N // 2 - 1, 255, 256, 257],
                 dtype=np.int64)])


# ------------------------------------------------------------ lane codec


def test_lane_codec_roundtrip():
    got = decode_lanes(
        encode_lanes(np.array([5, -3, 70000]), "add16", LAM), "add16")
    assert got.tolist() == [5, (N - 3) % N, 70000 % N]


def test_lane_codec_refuses_floats():
    with pytest.raises(ShapeError):
        encode_lanes(np.array([1.5]), "add16", LAM)


# ------------------------------------------------------------- sign gate


@pytest.mark.parametrize("group", ["add16", "add32"])
def test_sign_gate_bit_exact(dcf, rng, group):
    """sign(x) = IC over [2^{w-1}+r, r) on the masked input: both
    parties' shares group-add to the oracle for every mask class."""
    x_hat = gate_points(rng)
    for r in (0, 1, 12345, N // 2, N - 1, 0x1200, 0x00FF):
        g = gen_sign_gate(dcf, r, rng, group)
        y0 = eval_sign_share(dcf, 0, g.for_party(0), x_hat)
        y1 = eval_sign_share(dcf, 1, g.for_party(1), x_hat)
        got = gate_reconstruct(y0, y1, group)
        want = sign_oracle((x_hat - r) % N, W)
        assert np.array_equal(got, want), (group, r)


# ------------------------------------------------------------ truncation


def test_trunc_gate_bit_exact(dcf, dcf_low, rng):
    """Faithful truncation (not probabilistic): the borrow IC on the
    f-bit low half and the wraparound IC on the full domain make the
    identity exact for EVERY input, including the carry edges."""
    x_hat = gate_points(rng)
    for r in (0, 1, 0x1200, 0x00FF, 0xFF00, N - 1, 54321):
        g = gen_trunc_gate(dcf, dcf_low, r, 8, rng, "add16")
        y0 = eval_trunc_share(dcf, dcf_low, 0, g.for_party(0), x_hat)
        y1 = eval_trunc_share(dcf, dcf_low, 1, g.for_party(1), x_hat)
        got = gate_reconstruct(y0, y1, "add16")
        assert np.array_equal(got, trunc_oracle(x_hat, r, 8, W)), r


def test_trunc_gate_wide_domain(ck, rng):
    """Same identity on the 4-byte domain with a 2-byte fraction —
    the low-half service really is a different-width Dcf facade."""
    d4 = Dcf(4, LAM, ck, backend="bitsliced")
    d4_low = Dcf(2, LAM, ck, backend="bitsliced")
    n4 = 1 << 32
    xh = np.concatenate([
        rng.integers(0, n4, size=48, dtype=np.int64),
        np.array([0, 1, n4 - 1, n4 // 2], dtype=np.int64)])
    for r in (0, 0xDEADBEEF, 0x0000FFFF, n4 - 1):
        g = gen_trunc_gate(d4, d4_low, r, 16, rng, "add32")
        y0 = eval_trunc_share(d4, d4_low, 0, g.for_party(0), xh)
        y1 = eval_trunc_share(d4, d4_low, 1, g.for_party(1), xh)
        assert np.array_equal(gate_reconstruct(y0, y1, "add32"),
                              trunc_oracle(xh, r, 16, 32)), r


def test_trunc_const_share_party_restricted(dcf, dcf_low, rng):
    g = gen_trunc_gate(dcf, dcf_low, 77, 8, rng, "add16")
    g0 = g.for_party(0)
    assert g0.const_for(0).shape == (LAM,)
    with pytest.raises(ShapeError):
        g0.const_for(1)


def test_trunc_repr_redacts_const_share(dcf, dcf_low, rng):
    """secret-hygiene rule 3 in action: the repr shows geometry, never
    the additive scalar shares (the pair reveals the mask's high bits)."""
    g = gen_trunc_gate(dcf, dcf_low, 0x1234, 8, rng, "add16")
    text = repr(g)
    assert "const_share" not in text or "redacted" in text
    for b in (0, 1):
        assert g.const_for(b).tobytes().hex() not in text


# --------------------------------------------------------------- sigmoid


def test_sigmoid_table_contract():
    f = 8
    cuts, vals = sigmoid_table(W, f, 16)
    assert len(cuts) == 16 and cuts[0] == 0
    assert vals.min() == 0 and vals.max() <= (1 << f)  # saturates
    # value at x=0 ~ sigma(0)=0.5; pieces anchor at cut boundaries so
    # the piece containing 0 carries its MIDPOINT's sigma — allow the
    # half-piece-width slack, not exact 2^{f-1}.
    mid = sigmoid_fixed_oracle(np.array([0]), cuts, vals)[0]
    assert abs(int(mid) - (1 << (f - 1))) <= 40, mid
    with pytest.raises(ShapeError):
        sigmoid_table(W, f, 15)  # odd m: pieces come in +/- pairs
    with pytest.raises(ShapeError):
        sigmoid_table(W, f, 2)  # below the minimum partition
    with pytest.raises(ShapeError):
        sigmoid_table(W, W, 16)  # f must leave integer bits


@pytest.mark.parametrize("group", ["add16", "add32"])
def test_sigmoid_gate_bit_exact(dcf, rng, group):
    """The r-shifted partition is still a partition: served spline
    output equals the table oracle on the unmasked input, bit-exact."""
    x_hat = gate_points(rng)
    for r in (0, 7, 0x8000, 0x1234, N - 1):
        g = gen_sigmoid_gate(dcf, r, rng, group, f=8, m=16)
        y0 = eval_sigmoid_share(dcf, 0, g.for_party(0), x_hat)
        y1 = eval_sigmoid_share(dcf, 1, g.for_party(1), x_hat)
        got = gate_reconstruct(y0, y1, group)
        want = sigmoid_fixed_oracle((x_hat - r) % N, g.cuts, g.values)
        assert np.array_equal(got, want), (group, r)


def test_sigmoid_accuracy_pin():
    """m=32 table max abs error vs the real sigmoid is bounded by
    slope x piece half-width: 0.25 * (8/15) ~ 0.07.  Pin at 0.08 so a
    regression in cut placement (not float noise) trips it."""
    f = 8
    cuts, vals = sigmoid_table(W, f, 32)
    xs = np.arange(0, N, 37, dtype=np.int64)
    tab = sigmoid_fixed_oracle(xs, cuts, vals) / (1 << f)
    signed = np.where(xs >= N // 2, xs - N, xs)
    true = 1.0 / (1.0 + np.exp(-signed / (1 << f)))
    assert np.abs(tab - true).max() < 0.08


def test_gates_refuse_xor_group(dcf, rng):
    with pytest.raises(ShapeError):
        gen_sign_gate(dcf, 5, rng, "xor")


# ------------------------------------ additive backend-family parity


IV = [(10, 60), (60, 300), (300, 4096), (40000, 40001), (60000, N),
      (5000, 5000), (0, N), (50000, 2000)]
# plain, adjacent, big, singleton, suffix, empty, full-domain, wrap


def edge_points(rng, n=48):
    """Random points plus every IV endpoint (x exactly on a cut)."""
    return np.vstack([
        rng.integers(0, 256, size=(n, NB), dtype=np.uint8),
        np.array([[0, 10], [0, 59], [0, 60], [19, 136], [234, 96],
                  [255, 255], [0, 0], [195, 80]], dtype=np.uint8)])


@pytest.mark.parametrize("backend", ["auto", "bitsliced", "prefix"])
def test_additive_mic_facade_backend_parity(ck, rng, backend):
    """Every facade backend family reconstructs the additive MIC
    bit-exactly: both parties, both bounds, points on the cuts."""
    d = Dcf(NB, LAM, ck, backend=backend)
    xs = edge_points(rng)
    for group in ("add16", "add32", "add8"):
        for bound in (Bound.LT_BETA, Bound.GT_BETA):
            betas = rng.integers(0, 256, size=(len(IV), LAM),
                                 dtype=np.uint8)
            pb = d.mic(IV, betas, bound=bound, rng=rng, group=group)
            assert pb.group == group
            y0 = d.eval_mic(0, pb.for_party(0), xs)
            y1 = d.eval_mic(1, pb.for_party(1), xs)
            got = np_group_add(y0, y1, group)
            assert np.array_equal(got, mic_oracle(xs, IV, betas)), \
                (backend, group, bound)


def test_additive_sharded_mesh_parity(rng):
    """The sharded 2x2-mesh backends (Pallas walk + prefix frontier,
    interpret mode) match the host oracle per-party for additive
    bundles: both parties, both bounds, x=alpha and the domain edges."""
    import jax
    from jax.sharding import Mesh

    from dcf_tpu.backends.numpy_backend import eval_batch_np
    from dcf_tpu.gen import gen_batch
    from dcf_tpu.ops.prg import HirosePrgNp
    from dcf_tpu.parallel.pallas_sharded import (
        ShardedPallasBackend,
        ShardedPrefixBackend,
    )

    cks = [bytes(range(32)), bytes(range(1, 33))]
    prg = HirosePrgNp(LAM, cks)
    n_bits, nb = 24, 3
    n_tot = 1 << n_bits
    mesh22 = Mesh(np.array(jax.devices())[:4].reshape(2, 2),
                  ("keys", "points"))
    mesh14 = Mesh(np.array(jax.devices())[:4].reshape(1, 4),
                  ("keys", "points"))

    def to_bytes(vals):
        out = np.zeros((len(vals), nb), dtype=np.uint8)
        for j in range(nb):
            out[:, j] = (vals >> (8 * (nb - 1 - j))) & 0xFF
        return out

    for group, bound in (("add32", Bound.LT_BETA),
                         ("add8", Bound.GT_BETA)):
        k_num = 2
        alphas = rng.integers(0, n_tot, size=k_num, dtype=np.uint64)
        betas = rng.integers(0, 256, size=(k_num, LAM), dtype=np.uint8)
        s0s = rng.integers(0, 256, size=(k_num, 2, LAM), dtype=np.uint8)
        bundle = gen_batch(prg, to_bytes(alphas), betas, s0s, bound,
                           group=group)
        m = 48
        xs = rng.integers(0, n_tot, size=m, dtype=np.uint64)
        xs[:k_num] = alphas  # x exactly on alpha
        xs[k_num], xs[k_num + 1] = 0, n_tot - 1
        xb = to_bytes(xs)
        want = [eval_batch_np(prg, b, bundle.for_party(b), xb)
                for b in (0, 1)]

        for b in (0, 1):
            be = ShardedPallasBackend(LAM, cks, mesh22, interpret=True)
            be.put_bundle(bundle.for_party(b))
            st = be.stage(xb[None].repeat(k_num, axis=0))
            out = be.staged_to_bytes(be.eval_staged(b, st), m)
            assert np.array_equal(out, want[b]), \
                ("sharded-pallas", group, bound, b)

            bp = ShardedPrefixBackend(LAM, cks, mesh14, prefix_levels=6,
                                      interpret=True, host_levels=6)
            bp.put_bundle(bundle.for_party(b))
            stp = bp.stage(xb)
            out = bp.staged_to_bytes(bp.eval_staged(b, stp), m)
            assert np.array_equal(out, want[b]), \
                ("sharded-prefix", group, bound, b)


# ----------------------------------------------------------- served path


def make_gate_server(d, d_low, **knobs):
    knobs.setdefault("max_batch", 64)
    svc = d.serve(**knobs).start()
    svc_low = d_low.serve(**knobs).start()
    return svc, svc_low, GateServer(svc, svc_low)


def test_served_gates_bit_exact(dcf, dcf_low, rng):
    """All three gates through the SERVED path (started services,
    registry snapshots, client-side fold) vs the same oracles, plus
    hot-swap by re-registration."""
    svc, svc_low, gs = make_gate_server(dcf, dcf_low)
    try:
        x_hat = gate_points(rng)
        r1, r2, r3 = 0x1234, 0xBEEF, 0x00FF
        gs.register("cmp", gen_sign_gate(dcf, r1, rng, "add16"))
        gs.register("trunc",
                    gen_trunc_gate(dcf, dcf_low, r2, 8, rng, "add16"))
        sg = gen_sigmoid_gate(dcf, r3, rng, "add16", f=8, m=16)
        gs.register("sig", sg)

        got = decode_lanes(gs.reconstruct("cmp", x_hat), "add16")
        assert np.array_equal(got, sign_oracle((x_hat - r1) % N, W))
        got = decode_lanes(gs.reconstruct("trunc", x_hat), "add16")
        assert np.array_equal(got, trunc_oracle(x_hat, r2, 8, W))
        got = decode_lanes(gs.reconstruct("sig", x_hat), "add16")
        assert np.array_equal(
            got, sigmoid_fixed_oracle((x_hat - r3) % N, sg.cuts,
                                      sg.values))

        # hot-swap: a fresh mask under the same gate id is a new dealer
        # generation — the swapped components must all be the new ones.
        gs.register("sig",
                    gen_sigmoid_gate(dcf, 777, rng, "add16", f=8, m=16))
        sg2 = gs.gate("sig")
        got = decode_lanes(gs.reconstruct("sig", x_hat), "add16")
        assert np.array_equal(
            got, sigmoid_fixed_oracle((x_hat - 777) % N, sg2.cuts,
                                      sg2.values))
    finally:
        svc.close()
        svc_low.close()


def test_gate_server_typed_refusals(dcf, dcf_low, rng):
    svc = dcf.serve()
    try:
        gs = GateServer(svc)  # no low-domain service
        with pytest.raises(ShapeError):
            gs.register("t", gen_trunc_gate(dcf, dcf_low, 1, 8, rng,
                                            "add16"))
        with pytest.raises(ShapeError):
            gs.register("x", object())
        with pytest.raises(ShapeError):
            gs.eval_share("missing", 0, np.array([1]))
    finally:
        svc.close()


@pytest.mark.slow
def test_served_gate_soak_under_combine_faults(dcf, dcf_low, rng):
    """The acceptance fault clause, served form: a deterministic
    every-5th-fire ``protocols.combine`` fault under many rounds of all
    three gates; the service's retry machinery absorbs every injected
    failure (never two consecutive on one key, so the breaker stays
    closed) and each round reconstructs bit-exactly.  Serial CI leg
    only (gates and slow)."""
    svc, svc_low, gs = make_gate_server(dcf, dcf_low, retries=3)
    try:
        r1, r2, r3 = 0x0100, 0xFFFE, 0x8421
        gs.register("cmp", gen_sign_gate(dcf, r1, rng, "add16"))
        gs.register("trunc",
                    gen_trunc_gate(dcf, dcf_low, r2, 8, rng, "add16"))
        sg = gen_sigmoid_gate(dcf, r3, rng, "add16", f=8, m=16)
        gs.register("sig", sg)

        fired = {"n": 0}

        def every_fifth(*args):
            fired["n"] += 1
            if fired["n"] % 5 == 0:
                raise faults.InjectedFault(
                    f"injected combine fault #{fired['n']}")

        with faults.inject("protocols.combine", handler=every_fifth):
            for round_i in range(25):
                x_hat = rng.integers(0, N, size=96, dtype=np.int64)
                got = decode_lanes(gs.reconstruct("cmp", x_hat),
                                   "add16")
                assert np.array_equal(
                    got, sign_oracle((x_hat - r1) % N, W)), round_i
                got = decode_lanes(gs.reconstruct("trunc", x_hat),
                                   "add16")
                assert np.array_equal(
                    got, trunc_oracle(x_hat, r2, 8, W)), round_i
                got = decode_lanes(gs.reconstruct("sig", x_hat),
                                   "add16")
                assert np.array_equal(
                    got, sigmoid_fixed_oracle((x_hat - r3) % N,
                                              sg.cuts, sg.values)), \
                    round_i
        assert fired["n"] >= 100  # the seam really rode every batch
    finally:
        svc.close()
        svc_low.close()
