"""Seeded byte-flip fuzz over the DCFK wire formats (ISSUE 6 satellite).

~200 random single-byte corruptions per format (offset and flipped bits
drawn from a seeded RNG, so a failure names a reproducible frame):
every mutation of a valid v2 frame fed to ``KeyBundle.from_bytes`` and
every mutation of a valid v3 protocol frame fed to
``ProtocolBundle.from_bytes`` must raise the typed ``KeyFormatError`` —
never a bare exception (numpy buffer errors, struct errors, enum
lookups), and never a silent success with wrong key material or wrong
combine masks.

Why every flip is catchable: the CRC32 trailer covers the header AND
payload, so any payload/header flip that survives field validation dies
at the CRC check; flips of the version field move the frame to a reader
path whose size arithmetic no longer matches (v1 has no trailer, v3 has
a wider header), which the strict exact-size section decode rejects.
The fuzz pins exactly that reasoning against regressions in either
reader (they share ``keys._decode_sections`` by design).

ISSUE 20 extends the sweep to the v4 ADDITIVE-GROUP frames (plain and
protocol, with the widened header carrying the output-group code):
the same seeded flips, truncation/extension sweeps, the group-code
mutation, and the cross-reader gates in both directions — plus the
version-pinning check that XOR frames stay on v2/v3, byte-compatible
with pre-v4 readers.

ISSUE 8 extends the sweep to the DURABLE STORE: the same seeded flips
and truncations applied to the on-disk frame files and to the CRC'd
manifest.  A mutated frame read back through ``KeyStore.load`` must die
``KeyQuarantinedError`` (the typed quarantine — renamed aside, counter
bumped, the other keys untouched); a mutated manifest must die
``KeyFormatError`` on any store operation — never bare, never silent.
"""

import os

import numpy as np
import pytest

from dcf_tpu.errors import KeyFormatError, KeyQuarantinedError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.native import NativeDcf
from dcf_tpu.protocols import ProtocolBundle
from dcf_tpu.protocols.keygen import gen_interval_bundle
from dcf_tpu.serve.store import MANIFEST_NAME, KeyStore
from dcf_tpu.spec import Bound
from dcf_tpu.testing import faults

pytestmark = pytest.mark.faults

NB, LAM, N_FLIPS = 2, 16, 200


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0xF122)


@pytest.fixture(scope="module")
def native(rng):
    return NativeDcf(LAM, [rng.bytes(32), rng.bytes(32)])


@pytest.fixture(scope="module")
def v2_frame(native, rng):
    from dcf_tpu.gen import random_s0s

    alphas = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    betas = rng.integers(0, 256, (2, LAM), dtype=np.uint8)
    bundle = native.gen_batch(alphas, betas, random_s0s(2, LAM, rng),
                              Bound.LT_BETA)
    return bundle.to_bytes()


@pytest.fixture(scope="module")
def v3_frame(native, rng):
    from dcf_tpu.gen import random_s0s

    def gen_fn(alphas, betas, bound):
        return native.gen_batch(
            alphas, betas, random_s0s(alphas.shape[0], LAM, rng), bound)

    betas = rng.integers(0, 256, (2, LAM), dtype=np.uint8)
    pb = gen_interval_bundle(gen_fn, [(10, 60), (100, 200)], betas, NB)
    return pb.to_bytes()


def _fuzz(frame: bytes, decode, rng, n_flips: int) -> None:
    # Clean frame decodes (the fuzz must mutate a VALID baseline).
    decode(frame)
    offsets = rng.integers(0, len(frame), n_flips)
    xors = rng.integers(1, 256, n_flips)
    for i, (off, xor) in enumerate(zip(offsets, xors)):
        mutated = faults.corrupt(frame, int(off), int(xor))
        try:
            decode(mutated)
        except KeyFormatError:
            continue  # the contract: typed, field-naming rejection
        except BaseException as e:  # noqa: BLE001 — the fuzz's point
            pytest.fail(
                f"flip #{i} (offset {off}, xor {xor:#04x}) escaped the "
                f"typed-error contract: {type(e).__name__}: {e}")
        pytest.fail(
            f"flip #{i} (offset {off}, xor {xor:#04x}) decoded "
            "SILENTLY — corrupt key material accepted")


def test_v2_byte_flips_all_rejected_typed(v2_frame, rng):
    _fuzz(v2_frame, KeyBundle.from_bytes, rng, N_FLIPS)


def test_v3_byte_flips_all_rejected_typed(v3_frame, rng):
    _fuzz(v3_frame, ProtocolBundle.from_bytes, rng, N_FLIPS)


def test_v3_frame_fed_to_plain_reader_rejected(v3_frame, rng):
    """Cross-reader flips: a (possibly corrupted) protocol frame must
    never decode as a plain bundle — dropping the combine masks would
    silently break the public correction."""
    with pytest.raises(KeyFormatError, match="protocol section"):
        KeyBundle.from_bytes(v3_frame)
    for _ in range(40):
        mutated = faults.corrupt(v3_frame,
                                 int(rng.integers(0, len(v3_frame))),
                                 int(rng.integers(1, 256)))
        with pytest.raises(KeyFormatError):
            KeyBundle.from_bytes(mutated)


def test_truncations_and_extensions_rejected_typed(v2_frame, v3_frame,
                                                   rng):
    """Length mutations ride along: every truncation point and a tail
    extension must fail typed too (the exact-size discipline)."""
    for frame, decode in ((v2_frame, KeyBundle.from_bytes),
                          (v3_frame, ProtocolBundle.from_bytes)):
        for cut in sorted({int(c) for c in
                           rng.integers(0, len(frame), 25)}):
            with pytest.raises(KeyFormatError):
                decode(frame[:cut])
        with pytest.raises(KeyFormatError):
            decode(frame + b"\x00")


# --------------------------------------- DPF frames (ISSUE 19)


@pytest.fixture(scope="module")
def dpf_frame(rng):
    from dcf_tpu.gen import random_s0s
    from dcf_tpu.ops.prg import HirosePrgNp
    from dcf_tpu.protocols.dpf import dpf_gen_batch

    prg = HirosePrgNp(LAM, [rng.bytes(32), rng.bytes(32)])
    alphas = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    betas = rng.integers(0, 256, (2, LAM), dtype=np.uint8)
    return dpf_gen_batch(prg, alphas, betas,
                         random_s0s(2, LAM, rng)).to_bytes()


def test_dpf_byte_flips_all_rejected_typed(dpf_frame, rng):
    from dcf_tpu.protocols.dpf import DpfBundle

    _fuzz(dpf_frame, DpfBundle.from_bytes, rng, N_FLIPS)


def test_dpf_frame_fed_to_other_readers_rejected(dpf_frame, rng):
    """Version gating one way (ISSUE 19): a DPF frame fed to the plain
    or MIC readers is refused typed with a pointer at the right
    decoder, pristine and under corruption — a plain evaluator walking
    DPF material would read absent ``cw_v`` bytes as seed
    corrections."""
    with pytest.raises(KeyFormatError, match="DpfBundle"):
        KeyBundle.from_bytes(dpf_frame)
    with pytest.raises(KeyFormatError, match="point-function"):
        ProtocolBundle.from_bytes(dpf_frame)
    for _ in range(40):
        mutated = faults.corrupt(dpf_frame,
                                 int(rng.integers(0, len(dpf_frame))),
                                 int(rng.integers(1, 256)))
        with pytest.raises(KeyFormatError):
            KeyBundle.from_bytes(mutated)
        with pytest.raises(KeyFormatError):
            ProtocolBundle.from_bytes(mutated)


def test_plain_and_mic_frames_fed_to_dpf_reader_rejected(v2_frame,
                                                         v3_frame):
    """...and the other way: the DPF reader refuses plain (no proto
    field at all) and MIC frames, each with a pointer at its
    decoder."""
    from dcf_tpu.protocols.dpf import DpfBundle

    with pytest.raises(KeyFormatError, match="KeyBundle.from_bytes"):
        DpfBundle.from_bytes(v2_frame)
    with pytest.raises(KeyFormatError, match="ProtocolBundle"):
        DpfBundle.from_bytes(v3_frame)


def test_dpf_truncations_and_extensions_rejected_typed(dpf_frame, rng):
    from dcf_tpu.protocols.dpf import DpfBundle

    for cut in sorted({int(c) for c in
                       rng.integers(0, len(dpf_frame), 25)}):
        with pytest.raises(KeyFormatError):
            DpfBundle.from_bytes(dpf_frame[:cut])
    with pytest.raises(KeyFormatError):
        DpfBundle.from_bytes(dpf_frame + b"\x00")


# ----------------------------- v4 additive-group frames (ISSUE 20)


@pytest.fixture(scope="module")
def v4_plain_frame(rng):
    from dcf_tpu.gen import gen_batch, random_s0s
    from dcf_tpu.ops.prg import HirosePrgNp

    prg = HirosePrgNp(LAM, [rng.bytes(32), rng.bytes(32)])
    alphas = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    betas = rng.integers(0, 256, (2, LAM), dtype=np.uint8)
    return gen_batch(prg, alphas, betas, random_s0s(2, LAM, rng),
                     Bound.LT_BETA, group="add16").to_bytes()


@pytest.fixture(scope="module")
def v4_proto_frame(rng):
    from dcf_tpu.gen import gen_batch, random_s0s
    from dcf_tpu.ops.prg import HirosePrgNp

    prg = HirosePrgNp(LAM, [rng.bytes(32), rng.bytes(32)])

    def gen_fn(alphas, key_betas, bound):
        return gen_batch(prg, alphas, key_betas,
                         random_s0s(alphas.shape[0], LAM, rng), bound,
                         group="add16")

    betas = rng.integers(0, 256, (2, LAM), dtype=np.uint8)
    pb = gen_interval_bundle(gen_fn, [(10, 60), (100, 200)], betas, NB,
                             group="add16")
    return pb.to_bytes()


def test_version_pinning_xor_stays_pre_v4(v2_frame, v3_frame,
                                          v4_plain_frame,
                                          v4_proto_frame):
    """Only additive bundles write v4: XOR frames stay byte-compatible
    with pre-v4 readers (v2 plain / v3 protocol), so old key stores
    keep loading, while every additive frame announces the wider
    header — a v3-era reader refuses it loudly ("unsupported version
    4") instead of silently reconstructing with the wrong algebra."""
    assert v2_frame[4] == 2 and v3_frame[4] == 3
    assert v4_plain_frame[4] == 4 and v4_proto_frame[4] == 4


def test_v4_plain_byte_flips_all_rejected_typed(v4_plain_frame, rng):
    _fuzz(v4_plain_frame, KeyBundle.from_bytes, rng, N_FLIPS)


def test_v4_proto_byte_flips_all_rejected_typed(v4_proto_frame, rng):
    _fuzz(v4_proto_frame, ProtocolBundle.from_bytes, rng, N_FLIPS)


def test_v4_cross_reader_gates(v4_plain_frame, v4_proto_frame, rng):
    """Cross-reader gating for the additive frames: a v4 protocol frame
    fed to the plain reader (dropping the combine masks) and a v4 plain
    frame fed to the protocol reader are refused typed, pristine and
    under corruption."""
    with pytest.raises(KeyFormatError, match="protocol"):
        KeyBundle.from_bytes(v4_proto_frame)
    with pytest.raises(KeyFormatError):
        ProtocolBundle.from_bytes(v4_plain_frame)
    for frame, decode in ((v4_proto_frame, KeyBundle.from_bytes),
                          (v4_plain_frame, ProtocolBundle.from_bytes)):
        for _ in range(40):
            mutated = faults.corrupt(frame,
                                     int(rng.integers(0, len(frame))),
                                     int(rng.integers(1, 256)))
            with pytest.raises(KeyFormatError):
                decode(mutated)


def test_v4_unknown_group_code_rejected_typed(v4_proto_frame):
    """The group field itself (v4 header, low byte at offset 16) is
    validated before any section decode: an unknown code names itself
    in the error (or dies at the CRC, depending on the flip) — never a
    KeyError out of the code table."""
    bad = bytearray(v4_proto_frame)
    bad[16] = 99
    with pytest.raises(KeyFormatError):
        ProtocolBundle.from_bytes(bytes(bad))


def test_v4_truncations_and_extensions_rejected_typed(v4_plain_frame,
                                                      v4_proto_frame,
                                                      rng):
    for frame, decode in ((v4_plain_frame, KeyBundle.from_bytes),
                          (v4_proto_frame, ProtocolBundle.from_bytes)):
        for cut in sorted({int(c) for c in
                           rng.integers(0, len(frame), 25)}):
            with pytest.raises(KeyFormatError):
                decode(frame[:cut])
        with pytest.raises(KeyFormatError):
            decode(frame + b"\x00")


# --------------------------------------- the durable store (ISSUE 8)


def _overwrite(path, data: bytes) -> None:
    """Replace a store file's bytes in place, bypassing the writer (the
    fuzz models external damage, not the atomic-publish path)."""
    tmp = str(path) + ".fuzz"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        os.write(fd, data)
    finally:
        os.close(fd)
    os.replace(tmp, str(path))


@pytest.fixture()
def store_with_keys(v2_frame, v3_frame, tmp_path):
    """A store holding one plain and one protocol key, plus the frame
    paths for direct mutation."""
    store = KeyStore(str(tmp_path))
    kb = KeyBundle.from_bytes(v2_frame)
    pb = ProtocolBundle.from_bytes(v3_frame)
    store.put("plain", kb, generation=1)
    store.put("proto", pb.keys, protocol=pb, generation=2)
    entries = store._read_manifest()
    paths = {key: tmp_path / entries[key]["file"]
             for key in ("plain", "proto")}
    return store, {"plain": kb, "proto": pb}, paths


@pytest.mark.parametrize("key", ["plain", "proto"])
def test_store_frame_byte_flips_quarantined_typed(store_with_keys, rng,
                                                  key):
    """Every seeded flip of an on-disk frame dies
    ``KeyQuarantinedError`` at ``KeyStore.load`` — never bare, never
    silent — and the pristine frame re-published after each flip loads
    again (the quarantine took the damaged file, not the key id)."""
    store, originals, paths = store_with_keys
    path, n_flips = paths[key], 60
    pristine = open(path, "rb").read()
    offsets = rng.integers(0, len(pristine), n_flips)
    xors = rng.integers(1, 256, n_flips)
    for i, (off, xor) in enumerate(zip(offsets, xors)):
        _overwrite(path, faults.corrupt(pristine, int(off), int(xor)))
        try:
            store.load(key)
        except KeyQuarantinedError:
            pass  # the contract: typed quarantine
        except BaseException as e:  # noqa: BLE001 — the fuzz's point
            pytest.fail(
                f"flip #{i} (offset {off}, xor {xor:#04x}) escaped the "
                f"typed-quarantine contract: {type(e).__name__}: {e}")
        else:
            pytest.fail(
                f"flip #{i} (offset {off}, xor {xor:#04x}) loaded "
                "SILENTLY — corrupt key material accepted from disk")
        # re-publish the pristine frame for the next flip (the
        # quarantine dropped the manifest entry)
        obj = originals[key]
        if key == "proto":
            store.put(key, obj.keys, protocol=obj,
                      generation=store._read_manifest().get(
                          key, {}).get("generation", 2))
        else:
            store.put(key, obj, generation=1)
        store.load(key)
    assert store._metrics.snapshot()[
        "serve_store_quarantined_total"] == n_flips


def test_store_frame_truncations_quarantined_typed(store_with_keys,
                                                   rng):
    """Truncation sweeps on the on-disk frames: typed quarantine at
    every cut point and on a tail extension."""
    store, originals, paths = store_with_keys
    for key in ("plain", "proto"):
        path = paths[key]
        pristine = open(path, "rb").read()
        cuts = sorted({int(c) for c in
                       rng.integers(0, len(pristine), 12)})
        for mutated in [pristine[:c] for c in cuts] + [pristine + b"\0"]:
            _overwrite(path, mutated)
            with pytest.raises(KeyQuarantinedError):
                store.load(key)
            obj = originals[key]
            if key == "proto":
                store.put(key, obj.keys, protocol=obj, generation=2)
            else:
                store.put(key, obj, generation=1)


def test_manifest_byte_flips_rejected_typed(store_with_keys, rng):
    """Every seeded flip of the CRC'd manifest dies ``KeyFormatError``
    on the next store operation — a store whose index cannot be
    trusted must fail loudly, not serve a guess."""
    store, _originals, _paths = store_with_keys
    path = os.path.join(store.root, MANIFEST_NAME)
    pristine = open(path, "rb").read()
    offsets = rng.integers(0, len(pristine), 60)
    xors = rng.integers(1, 256, 60)
    for i, (off, xor) in enumerate(zip(offsets, xors)):
        _overwrite(path, faults.corrupt(pristine, int(off), int(xor)))
        try:
            store.key_ids()
        except KeyFormatError:
            pass
        except BaseException as e:  # noqa: BLE001 — the fuzz's point
            pytest.fail(
                f"manifest flip #{i} (offset {off}, xor {xor:#04x}) "
                f"escaped the typed-error contract: "
                f"{type(e).__name__}: {e}")
        else:
            pytest.fail(
                f"manifest flip #{i} (offset {off}, xor {xor:#04x}) "
                "read SILENTLY — a corrupt index accepted")
        _overwrite(path, pristine)
    # truncations and a tail extension die typed too
    for cut in sorted({int(c) for c in
                       rng.integers(0, len(pristine), 15)}):
        _overwrite(path, pristine[:cut])
        with pytest.raises(KeyFormatError):
            store.key_ids()
    _overwrite(path, pristine + b"\x00")
    with pytest.raises(KeyFormatError):
        store.key_ids()
    _overwrite(path, pristine)
    assert store.key_ids() == ["plain", "proto"]  # pristine still reads
