"""Seeded byte-flip fuzz over the DCFK wire formats (ISSUE 6 satellite).

~200 random single-byte corruptions per format (offset and flipped bits
drawn from a seeded RNG, so a failure names a reproducible frame):
every mutation of a valid v2 frame fed to ``KeyBundle.from_bytes`` and
every mutation of a valid v3 protocol frame fed to
``ProtocolBundle.from_bytes`` must raise the typed ``KeyFormatError`` —
never a bare exception (numpy buffer errors, struct errors, enum
lookups), and never a silent success with wrong key material or wrong
combine masks.

Why every flip is catchable: the CRC32 trailer covers the header AND
payload, so any payload/header flip that survives field validation dies
at the CRC check; flips of the version field move the frame to a reader
path whose size arithmetic no longer matches (v1 has no trailer, v3 has
a wider header), which the strict exact-size section decode rejects.
The fuzz pins exactly that reasoning against regressions in either
reader (they share ``keys._decode_sections`` by design).
"""

import numpy as np
import pytest

from dcf_tpu.errors import KeyFormatError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.native import NativeDcf
from dcf_tpu.protocols import ProtocolBundle
from dcf_tpu.protocols.keygen import gen_interval_bundle
from dcf_tpu.spec import Bound
from dcf_tpu.testing import faults

pytestmark = pytest.mark.faults

NB, LAM, N_FLIPS = 2, 16, 200


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0xF122)


@pytest.fixture(scope="module")
def native(rng):
    return NativeDcf(LAM, [rng.bytes(32), rng.bytes(32)])


@pytest.fixture(scope="module")
def v2_frame(native, rng):
    from dcf_tpu.gen import random_s0s

    alphas = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    betas = rng.integers(0, 256, (2, LAM), dtype=np.uint8)
    bundle = native.gen_batch(alphas, betas, random_s0s(2, LAM, rng),
                              Bound.LT_BETA)
    return bundle.to_bytes()


@pytest.fixture(scope="module")
def v3_frame(native, rng):
    from dcf_tpu.gen import random_s0s

    def gen_fn(alphas, betas, bound):
        return native.gen_batch(
            alphas, betas, random_s0s(alphas.shape[0], LAM, rng), bound)

    betas = rng.integers(0, 256, (2, LAM), dtype=np.uint8)
    pb = gen_interval_bundle(gen_fn, [(10, 60), (100, 200)], betas, NB)
    return pb.to_bytes()


def _fuzz(frame: bytes, decode, rng, n_flips: int) -> None:
    # Clean frame decodes (the fuzz must mutate a VALID baseline).
    decode(frame)
    offsets = rng.integers(0, len(frame), n_flips)
    xors = rng.integers(1, 256, n_flips)
    for i, (off, xor) in enumerate(zip(offsets, xors)):
        mutated = faults.corrupt(frame, int(off), int(xor))
        try:
            decode(mutated)
        except KeyFormatError:
            continue  # the contract: typed, field-naming rejection
        except BaseException as e:  # noqa: BLE001 — the fuzz's point
            pytest.fail(
                f"flip #{i} (offset {off}, xor {xor:#04x}) escaped the "
                f"typed-error contract: {type(e).__name__}: {e}")
        pytest.fail(
            f"flip #{i} (offset {off}, xor {xor:#04x}) decoded "
            "SILENTLY — corrupt key material accepted")


def test_v2_byte_flips_all_rejected_typed(v2_frame, rng):
    _fuzz(v2_frame, KeyBundle.from_bytes, rng, N_FLIPS)


def test_v3_byte_flips_all_rejected_typed(v3_frame, rng):
    _fuzz(v3_frame, ProtocolBundle.from_bytes, rng, N_FLIPS)


def test_v3_frame_fed_to_plain_reader_rejected(v3_frame, rng):
    """Cross-reader flips: a (possibly corrupted) protocol frame must
    never decode as a plain bundle — dropping the combine masks would
    silently break the public correction."""
    with pytest.raises(KeyFormatError, match="protocol section"):
        KeyBundle.from_bytes(v3_frame)
    for _ in range(40):
        mutated = faults.corrupt(v3_frame,
                                 int(rng.integers(0, len(v3_frame))),
                                 int(rng.integers(1, 256)))
        with pytest.raises(KeyFormatError):
            KeyBundle.from_bytes(mutated)


def test_truncations_and_extensions_rejected_typed(v2_frame, v3_frame,
                                                   rng):
    """Length mutations ride along: every truncation point and a tail
    extension must fail typed too (the exact-size discipline)."""
    for frame, decode in ((v2_frame, KeyBundle.from_bytes),
                          (v3_frame, ProtocolBundle.from_bytes)):
        for cut in sorted({int(c) for c in
                           rng.integers(0, len(frame), 25)}):
            with pytest.raises(KeyFormatError):
                decode(frame[:cut])
        with pytest.raises(KeyFormatError):
            decode(frame + b"\x00")
