"""Workload runners: full-domain reconstruction and secure-ReLU streaming."""

import random

import numpy as np

from dcf_tpu import spec
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.workloads import (
    domain_points,
    full_domain_check,
    full_domain_check_device,
    secure_relu_eval,
)


def rand_bytes(rng, n):
    return bytes(rng.getrandbits(8) for _ in range(n))


def test_domain_points():
    pts = domain_points(2, 0x00FE, 4)
    assert pts.tolist() == [[0, 254], [0, 255], [1, 0], [1, 1]]


def test_full_domain_check_bitsliced_n16():
    from dcf_tpu.backends.jax_bitsliced import BitslicedBackend

    rng = random.Random(61)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(5)
    alpha = 0xBEEF
    beta = rand_bytes(rng, 16)
    bundle = gen_batch(
        prg,
        np.array([[0xBE, 0xEF]], dtype=np.uint8),
        np.frombuffer(beta, dtype=np.uint8)[None],
        random_s0s(1, 16, nprng),
        spec.Bound.LT_BETA,
    )
    be0 = BitslicedBackend(16, ck)
    be0.put_bundle(bundle.for_party(0))
    be1 = BitslicedBackend(16, ck)
    be1.put_bundle(bundle.for_party(1))
    mism = full_domain_check(
        lambda xs: be0.eval(0, xs),
        lambda xs: be1.eval(1, xs),
        alpha,
        beta,
        n_bits=16,
        chunk=1 << 14,
    )
    assert mism == 0


def test_full_domain_check_device_n16():
    """Device-resident config 3: on-device iota points + on-device verify.

    Also a negative control: a wrong alpha must be detected, proving the
    device-side comparison actually compares."""
    from dcf_tpu.backends.jax_bitsliced import BitslicedBackend

    rng = random.Random(63)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(7)
    alpha = 0x2FA7
    beta = rand_bytes(rng, 16)
    bundle = gen_batch(
        prg,
        np.array([[0x2F, 0xA7]], dtype=np.uint8),
        np.frombuffer(beta, dtype=np.uint8)[None],
        random_s0s(1, 16, nprng),
        spec.Bound.LT_BETA,
    )
    be0 = BitslicedBackend(16, ck)
    be0.put_bundle(bundle.for_party(0))
    be1 = BitslicedBackend(16, ck)
    be1.put_bundle(bundle.for_party(1))
    assert full_domain_check_device(
        be0, be1, alpha, beta, n_bits=16, chunk=1 << 14) == 0
    # wrong alpha: exactly |alpha' - alpha| points flip classification
    assert full_domain_check_device(
        be0, be1, alpha + 5, beta, n_bits=16, chunk=1 << 14) == 5


def test_full_domain_check_device_pallas_interpret_n8():
    """The bit-major (Pallas) variant of the device full-domain path —
    stage_range tile planning, the _PERM-permuted beta mask, and the
    int32/uint32 bitcasts in _fd_mismatch_bitmajor — via the interpreter."""
    from dcf_tpu.backends.pallas_backend import PallasBackend

    rng = random.Random(64)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(8)
    alpha = 0x6D
    beta = rand_bytes(rng, 16)
    bundle = gen_batch(
        prg,
        np.array([[0x6D]], dtype=np.uint8),
        np.frombuffer(beta, dtype=np.uint8)[None],
        random_s0s(1, 16, nprng),
        spec.Bound.LT_BETA,
    )
    be0 = PallasBackend(16, ck, interpret=True)
    be0.put_bundle(bundle.for_party(0))
    be1 = PallasBackend(16, ck, interpret=True)
    be1.put_bundle(bundle.for_party(1))
    assert full_domain_check_device(
        be0, be1, alpha, beta, n_bits=8, chunk=128) == 0
    # negative control: a shifted alpha flips exactly that many points
    assert full_domain_check_device(
        be0, be1, alpha + 3, beta, n_bits=8, chunk=128) == 3


def test_secure_relu_eval_streams_keys():
    from dcf_tpu.backends.jax_bitsliced import KeyLanesBackend

    rng = random.Random(62)
    ck = [rand_bytes(rng, 32), rand_bytes(rng, 32)]
    prg = HirosePrgNp(16, ck)
    nprng = np.random.default_rng(6)
    k_num, n_bytes, m = 70, 2, 8  # chunk=32 forces 3 slices incl. ragged tail
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, 16), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(k_num, 16, nprng), spec.Bound.LT_BETA)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    recon = secure_relu_eval(
        KeyLanesBackend(16, ck), KeyLanesBackend(16, ck), bundle, xs, key_chunk=32
    )
    for i in range(k_num):
        a = alphas[i].tobytes()
        for j in range(m):
            want = betas[i].tobytes() if xs[j].tobytes() < a else bytes(16)
            assert recon[i, j].tobytes() == want, (i, j)
