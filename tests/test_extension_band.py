"""The extension band 32 <= lam < 144 EXECUTED end to end.

BASELINE.json's headline metric literally reads "(n=128, lam=128)"; the
reference itself cannot run any lam in [32, 144) because its key-count
contract 2*(lam/16) supplies <= 17 ciphers while the encryption loop
indexes ciphers[17] (/root/reference/src/prg.rs:17-18 vs :51).  This
framework supports the band as a documented extension (the caller
supplies enough keys to cover index 17, and a ReferenceContractWarning
fires at the API edge) — these tests are the execution behind that
claim, at the two shapes that matter:

* lam=48  — the hybrid backend's own contract edge (api.py);
* lam=128 — the BASELINE headline's bytes reading.

Coverage: PRG spec/numpy parity, full two-party protocol vs the numpy
oracle through the hybrid device path AND the plain bitsliced path,
both parties, both bounds, facade-reachable.  The recorded bench line
lives in benchmarks/RESULTS_r05.jsonl (dcf_large_lambda --lam=128).
"""

import random
import warnings

import numpy as np
import pytest

from dcf_tpu import Dcf, ReferenceContractWarning, spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp


def rand_bytes(rng, n):
    return bytes(rng.getrandbits(8) for _ in range(n))


def _band_keys(rng):
    return [rand_bytes(rng, 32) for _ in range(18)]  # covers index 17


@pytest.mark.parametrize("lam", [48, 128])
def test_band_prg_spec_numpy_parity(lam):
    """Hirose PRG at band shapes: the spec and numpy twins agree and the
    truncated-loop quirk holds (blocks 2.. are pure feed-forward)."""
    rng = random.Random(61)
    keys = _band_keys(rng)
    with pytest.warns(ReferenceContractWarning,
                      match="reference-inexecutable"):
        prg_spec = spec.HirosePrgSpec(lam, keys)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", ReferenceContractWarning)
        prg_np = HirosePrgNp(lam, keys)
    seeds = np.random.default_rng(61).integers(
        0, 256, (5, lam), dtype=np.uint8)
    out = prg_np.gen(seeds)
    for i in range(5):
        (s_l, v_l, t_l), (s_r, v_r, t_r) = prg_spec.gen(seeds[i].tobytes())
        assert out.s_l[i].tobytes() == s_l
        assert out.v_l[i].tobytes() == v_l
        assert out.s_r[i].tobytes() == s_r
        assert out.v_r[i].tobytes() == v_r
        assert bool(out.t_l[i]) == t_l and bool(out.t_r[i]) == t_r
        # Only blocks 0/1 are ever encrypted (the zip quirk); bytes 32+
        # of every output are literal feed-forward copies.
        seed = seeds[i].tobytes()
        seed_p = bytes(b ^ 0xFF for b in seed)
        assert s_l[32:lam - 1] == seed[32:lam - 1]
        assert v_l[32:lam - 1] == seed_p[32:lam - 1]


@pytest.mark.parametrize("lam", [48, 128])
@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_band_two_party_hybrid_and_bitsliced(lam, bound):
    """Full protocol at band shapes: hybrid (the lam >= 48 device path)
    and bitsliced evals vs the numpy oracle, both parties, plus the XOR
    reconstruction against the plain comparison."""
    from dcf_tpu.backends.jax_bitsliced import BitslicedBackend
    from dcf_tpu.backends.large_lambda import LargeLambdaBackend

    rng = random.Random(62)
    ck = _band_keys(rng)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", ReferenceContractWarning)
        prg = HirosePrgNp(lam, ck)
        be_h = LargeLambdaBackend(lam, ck)  # XLA narrow on CPU
        be_b = BitslicedBackend(lam, ck)
    nprng = np.random.default_rng(62 + lam)
    nb, m = 2, 9
    alphas = nprng.integers(0, 256, (1, nb), dtype=np.uint8)
    betas = nprng.integers(0, 256, (1, lam), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(1, lam, nprng),
                       bound)
    xs = nprng.integers(0, 256, (m, nb), dtype=np.uint8)
    xs[0] = alphas[0]
    ys = {}
    for b in (0, 1):
        kb = bundle.for_party(b)
        want = eval_batch_np(prg, b, kb, xs)
        for be in (be_h, be_b):
            got = be.eval(b, xs, bundle=kb)
            assert np.array_equal(got, want), \
                f"{type(be).__name__} party {b} lam={lam} {bound}"
        ys[b] = want
    recon = ys[0][0] ^ ys[1][0]
    a = alphas[0].tobytes()
    for j in range(m):
        x = xs[j].tobytes()
        hit = x < a if bound is spec.Bound.LT_BETA else x > a
        want_y = betas[0].tobytes() if hit else bytes(lam)
        assert recon[j].tobytes() == want_y


def test_band_facade_lam128():
    """The BASELINE headline shape through the user entry point:
    Dcf(n_bytes=16, lam=128) — n=128 levels, lam=128 bytes — warns once
    and reconstructs correctly (auto -> hybrid)."""
    rng = random.Random(63)
    ck = _band_keys(rng)
    with pytest.warns(ReferenceContractWarning,
                      match="reference-inexecutable"):
        dcf = Dcf(n_bytes=16, lam=128, cipher_keys=ck)
    assert dcf.backend_name == "hybrid"
    nprng = np.random.default_rng(63)
    alphas = nprng.integers(0, 256, (1, 16), dtype=np.uint8)
    betas = nprng.integers(0, 256, (1, 128), dtype=np.uint8)
    bundle = dcf.gen(alphas, betas, rng=nprng)
    xs = nprng.integers(0, 256, (5, 16), dtype=np.uint8)
    xs[0] = alphas[0]
    recon = dcf.eval(0, bundle, xs) ^ dcf.eval(1, bundle, xs)
    a = alphas[0].tobytes()
    for j in range(5):
        want = betas[0].tobytes() if xs[j].tobytes() < a else bytes(128)
        assert recon[0, j].tobytes() == want
