"""Scripted chaos scenarios for the serve resilience layer (ISSUE 6).

Every scenario here replays DETERMINISTICALLY on the injectable fake
clock (``faults.FakeClock``) with ``pump()``-driven serving — no worker
thread, no wall time, no sleeps — covering the acceptance walk end to
end: a backend failing for a scheduled window opens its (key,
backend-family) circuit breaker within the failure threshold, the open
breaker fast-fails NORMAL traffic (``CircuitOpenError``) and brownout
refuses BATCH traffic at the door (``QueueFullError``) while CRITICAL
requests bypass and complete bit-exactly, the breaker half-opens after
the cooldown and closes on one sanctioned probe — exactly one
open/half_open/closed transition each (no thrash) — and every delivered
result reconstructs bit-exactly against the numpy oracle.

Plus the machinery in isolation: the breaker state machine walk,
priority eviction (lowest class first, newest first, all-or-nothing),
brownout hysteresis on queue-depth pressure, injected LATENCY (the
clock-advancing seam — deadline expiry under a slow backend without a
single sleep), seeded flaky faults replaying the same pattern, and
breaker-state lifetime across registry hot-swaps vs unregistration.

The ``chaos and slow`` soak at the bottom runs the real-clock,
3-thread flapping-window version in the serial CI leg only.
"""

import threading

import numpy as np
import pytest

from dcf_tpu import Dcf
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.errors import (
    CircuitOpenError,
    DeadlineExceededError,
    QueueFullError,
)
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.serve import DcfService, ServeConfig
from dcf_tpu.serve.admission import AdmissionQueue, Priority, Request
from dcf_tpu.serve.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerBoard,
    CircuitBreaker,
)
from dcf_tpu.testing import faults
from dcf_tpu.testing.faults import FakeClock

pytestmark = pytest.mark.chaos

NB, LAM = 2, 16


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0xC4A05)


@pytest.fixture(scope="module")
def ck(rng):
    return [rng.bytes(32), rng.bytes(32)]


@pytest.fixture(scope="module")
def dcf(ck):
    return Dcf(NB, LAM, ck, backend="bitsliced")


@pytest.fixture(scope="module")
def prg(ck):
    return HirosePrgNp(LAM, ck)


@pytest.fixture(scope="module")
def bundles(dcf, rng):
    out = {}
    for name in ("relu-a", "relu-b"):
        alphas = rng.integers(0, 256, (1, NB), dtype=np.uint8)
        betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
        out[name] = dcf.gen(alphas, betas, rng=rng)
    return out


def oracle(prg, bundle, b, xs):
    return eval_batch_np(prg, b, bundle.for_party(b), xs)


def make_service(dcf, bundles, clock, **knobs):
    knobs.setdefault("max_batch", 32)
    kwargs = {} if clock is None else {"clock": clock}  # None = real
    svc = DcfService(dcf, ServeConfig(**knobs), **kwargs)
    for name, bundle in bundles.items():
        svc.register_key(name, bundle)
    return svc


def mk_req(m=3, priority=Priority.NORMAL, key="k", enq_t=0.0):
    return Request(key, 0, np.zeros((m, NB), dtype=np.uint8), None,
                   enq_t, priority)


# ------------------------------------------------- breaker state machine


def test_breaker_state_machine_walk():
    """The classic three-state walk on explicit fake times."""
    br = CircuitBreaker(failures_to_open=3, cooldown_s=5.0)
    assert br.state == CLOSED
    br.record_failure(10.0)
    br.record_failure(11.0)
    br.record_success()  # success resets the consecutive count
    br.record_failure(13.0)
    br.record_failure(14.0)
    assert br.state == CLOSED
    br.record_failure(15.0)  # third consecutive -> OPEN
    assert br.state == OPEN
    assert not br.allow(16.0)  # cooldown not elapsed: fail fast
    assert br.allow(16.0, critical=True)  # CRITICAL bypasses
    br.record_failure(16.5)  # bypass failure must NOT restart cooldown
    assert br.opened_at == 15.0
    br.record_success()  # bypass success is not a sanctioned probe
    assert br.state == OPEN
    assert br.allow(20.0)  # cooldown elapsed: this caller is the probe
    assert br.state == HALF_OPEN
    assert not br.allow(20.1)  # one probe at a time
    assert br.allow(20.1, critical=True)  # criticals ride along
    br.record_failure(20.2)  # probe failed -> reopen, cooldown restarts
    assert br.state == OPEN and br.opened_at == 20.2
    assert br.allow(25.2)  # second probe
    br.record_success()
    assert br.state == CLOSED and br.failures == 0


def test_breaker_abort_probe_releases_the_slot():
    br = CircuitBreaker(failures_to_open=1, cooldown_s=1.0)
    br.record_failure(0.0)
    assert br.allow(1.0)  # the probe
    assert not br.allow(1.0)  # slot taken
    br.abort_probe()  # prober died without an outcome
    assert br.allow(1.1)  # next caller can probe; breaker not wedged
    br.abort_probe()


def test_breaker_board_metrics_and_forget():
    clock = FakeClock()
    board = BreakerBoard(failures_to_open=1, cooldown_s=5.0, clock=clock)
    board.allow("k1", "bitsliced")
    board.allow("k2", "bitsliced")
    board.record_failure("k1", "bitsliced")
    board.record_failure("k2", "bitsliced")
    assert board.any_open()
    snap = board._metrics.snapshot()
    assert snap["serve_breakers_open"] == 2
    assert snap["serve_breaker_state{backend=bitsliced,key=k1}"] == 2
    assert snap["serve_breaker_transitions_total{to=open}"] == 2
    board.forget("k1")  # unregistration: the pairing no longer exists
    assert board.state("k1", "bitsliced") == CLOSED
    snap = board._metrics.snapshot()
    assert snap["serve_breakers_open"] == 1
    # Unregistration is not a recovery: forget must not count a
    # to=closed transition (chaos_bench reads that counter as proof the
    # backend healed after the fault window).
    assert "serve_breaker_transitions_total{to=closed}" not in snap
    assert snap["serve_breaker_transitions_total"] == 2
    # Cardinality hygiene: the forgotten pairing's labeled series is
    # REMOVED from the snapshot, not parked at 0 — under key churn dead
    # series would otherwise accumulate forever.
    assert "serve_breaker_state{backend=bitsliced,key=k1}" not in snap
    assert snap["serve_breaker_state{backend=bitsliced,key=k2}"] == 2
    board.forget("k2")
    assert not board.any_open()
    # A late in-flight outcome for a forgotten pairing (unregister raced
    # a dispatched batch — routine under dispatch-ahead) must NOT
    # resurrect the entry or its labeled series: under key churn record_*
    # auto-creating would leak one board entry per churned key forever.
    board.record_failure("k1", "bitsliced")
    board.record_success("k2", "bitsliced")
    assert not board.any_open()
    snap = board._metrics.snapshot()
    assert "serve_breaker_state{backend=bitsliced,key=k1}" not in snap
    assert "serve_breaker_state{backend=bitsliced,key=k2}" not in snap
    assert len(board._breakers) == 0


def test_breaker_survives_hot_swap_cleared_by_unregister(dcf, bundles):
    """Breaker state is (key, family) failure HISTORY: a re-register
    hot-swap keeps it (same dying backend), unregister forgets it."""
    clock = FakeClock()
    svc = make_service(dcf, bundles, clock, breaker_failures=1)
    svc.breakers.allow("relu-a", "bitsliced")  # the gate creates the
    # entry; record_* never does (a late outcome for a forgotten
    # pairing must not resurrect it)
    svc.breakers.record_failure("relu-a", "bitsliced")
    assert svc.breakers.state("relu-a", "bitsliced") == OPEN
    svc.register_key("relu-a", bundles["relu-b"])  # hot-swap
    assert svc.breakers.state("relu-a", "bitsliced") == OPEN
    svc.unregister_key("relu-a")
    assert svc.breakers.state("relu-a", "bitsliced") == CLOSED
    assert not svc.breakers.any_open()


# ------------------------------------------------ the acceptance walk


def test_unregister_racing_dispatch_leaves_no_board_state(dcf, bundles,
                                                          rng):
    """submit -> unregister -> pump: the breaker gate runs before the
    registry read, so allow() re-creates board state for the forgotten
    pairing; the group-failure sweep must forget it again or the board
    leaks one entry per churned key (the allow()-path twin of the
    record_* resurrection guards)."""
    clock = FakeClock()
    svc = make_service(dcf, bundles, clock, breaker_failures=1)
    xs = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    fut = svc.submit("relu-a", xs)
    svc.unregister_key("relu-a")  # forget() runs here, pre-dispatch
    svc.pump()  # gate re-creates ('relu-a', fam); registry fails typed
    with pytest.raises(ValueError, match="registered"):
        fut.result(0)
    assert all(k[0] != "relu-a" for k in svc.breakers._breakers)


def test_scripted_window_open_shed_by_class_recover(dcf, bundles, prg,
                                                    rng):
    """The ISSUE 6 acceptance scenario, scripted on the fake clock.

    A backend failing for a 6-eval window (spread over the failing
    batches and their retries) opens its breaker at the third recorded
    failure; while open, NORMAL requests fast-fail typed
    (CircuitOpenError), BATCH submits are brownout-refused typed
    (QueueFullError), CRITICAL requests bypass and complete BIT-EXACTLY;
    after the cooldown one sanctioned probe closes the breaker — exactly
    one open/half_open/closed transition each, i.e. no thrash — and
    every delivered result reconstructs against the numpy oracle."""
    clock = FakeClock()
    svc = make_service(dcf, bundles, clock, retries=1,
                       breaker_failures=3, breaker_cooldown_s=5.0,
                       brownout_clear_s=1.0)
    xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
    want0 = oracle(prg, bundles["relu-a"], 0, xs)
    want1 = oracle(prg, bundles["relu-a"], 1, xs)

    with faults.inject_schedule("serve.eval", window_evals=6) as sched:
        # Failing batch 1: dispatch + retry = window evals 1, 2.
        f1 = svc.submit("relu-a", xs)
        svc.pump()
        with pytest.raises(faults.InjectedFault):
            f1.result(0)
        assert svc.breakers.state("relu-a", "bitsliced") == CLOSED
        # Failing batch 2: dispatch = 3rd consecutive failure -> OPEN;
        # its retry consumes eval 4 (recorded as a no-op: open).
        f2 = svc.submit("relu-a", xs)
        svc.pump()
        with pytest.raises(faults.InjectedFault):
            f2.result(0)
        assert svc.breakers.state("relu-a", "bitsliced") == OPEN

        # NORMAL while open: fast-fail, no retry budget burned, no
        # window eval consumed.
        consumed = sched.fired
        f3 = svc.submit("relu-a", xs)
        svc.pump()
        with pytest.raises(CircuitOpenError):
            f3.result(0)
        assert sched.fired == consumed  # the backend was never touched

        # BATCH while open: brownout (open breaker = immediate entry)
        # refuses at the door, typed.
        with pytest.raises(QueueFullError, match="brownout"):
            svc.submit("relu-a", xs, priority="batch")
        snap = svc.metrics_snapshot()
        assert snap["serve_brownout"] == 1
        assert snap["serve_brownout_refusals_total"] == 1

        # CRITICAL while open: bypasses the breaker, burns the last two
        # window evals (5, 6) on its dispatch + retry, and FAILS — the
        # backend is still inside its failure window.
        fc1 = svc.submit("relu-a", xs, priority=Priority.CRITICAL)
        svc.pump()
        with pytest.raises(faults.InjectedFault):
            fc1.result(0)
        assert sched.recovered  # the 6-eval window is now consumed

        # CRITICAL after the backend recovered but while the breaker is
        # STILL OPEN: completes bit-exactly (the acceptance clause), and
        # its lucky success must not flip the open breaker (no thrash).
        fc2 = svc.submit("relu-a", xs, b=0, priority="critical")
        fc3 = svc.submit("relu-a", xs, b=1, priority="critical")
        svc.pump()
        assert np.array_equal(fc2.result(0), want0)
        assert np.array_equal(fc2.result(0) ^ fc3.result(0),
                              want0 ^ want1)
        assert svc.breakers.state("relu-a", "bitsliced") == OPEN

        # NORMAL is still fast-failed until the cooldown elapses.
        f4 = svc.submit("relu-a", xs)
        svc.pump()
        with pytest.raises(CircuitOpenError):
            f4.result(0)

        # Cooldown elapses on the injected clock: the next NORMAL batch
        # is the sanctioned half-open probe; it succeeds and closes.
        clock.advance(5.0)
        f5 = svc.submit("relu-a", xs)
        svc.pump()
        assert np.array_equal(f5.result(0), want0)
        assert svc.breakers.state("relu-a", "bitsliced") == CLOSED

    snap = svc.metrics_snapshot()
    # No thrash: exactly one transition per state over the whole walk.
    assert snap["serve_breaker_transitions_total{to=open}"] == 1
    assert snap["serve_breaker_transitions_total{to=half_open}"] == 1
    assert snap["serve_breaker_transitions_total{to=closed}"] == 1
    assert snap["serve_breakers_open"] == 0
    # Shedding was lowest-class-first: CRITICAL never shed.
    assert snap["serve_shed_by_class_total{priority=critical}"] == 0
    assert snap["serve_shed_by_class_total{priority=batch}"] == 1

    # Brownout exits after clear_s of calm (hysteresis), re-admitting
    # BATCH traffic, which then serves bit-exactly.
    clock.advance(0.5)
    svc.pump()  # calm observation 1 (starts the clear window)
    clock.advance(1.1)
    fb = svc.submit("relu-a", xs, priority="batch")
    svc.pump()
    assert np.array_equal(fb.result(0), want0)
    assert svc.metrics_snapshot()["serve_brownout"] == 0


def test_breaker_disabled_keeps_pr4_semantics(dcf, bundles, rng):
    """breaker_failures=0 disables the gate entirely: every batch
    dispatches (and burns retries) no matter how many failures."""
    clock = FakeClock()
    svc = make_service(dcf, bundles, clock, retries=0,
                       breaker_failures=0)
    xs = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    with faults.inject("serve.eval"):
        for _ in range(5):
            f = svc.submit("relu-a", xs)
            svc.pump()
            with pytest.raises(faults.InjectedFault):
                f.result(0)
    snap = svc.metrics_snapshot()
    assert snap["serve_breaker_fast_fails_total"] == 0
    assert snap.get("serve_breaker_transitions_total", 0) == 0


# ------------------------------------------------- priority admission


def test_eviction_lowest_class_first_newest_first():
    q = AdmissionQueue(10)
    b_old = mk_req(4, Priority.BATCH, enq_t=1.0)
    b_new = mk_req(3, Priority.BATCH, enq_t=2.0)
    n1 = mk_req(3, Priority.NORMAL, enq_t=3.0)
    for r in (b_old, b_new, n1):
        q.put(r)
    assert q.points == 10

    # CRITICAL(5) needs 5 points: BATCH evicted newest-first (b_new
    # first, then b_old); NORMAL untouched because the two BATCH
    # evictions already make room.
    c1 = mk_req(5, Priority.CRITICAL, enq_t=4.0)
    q.put(c1)
    with pytest.raises(QueueFullError, match="evicted"):
        b_new.future.result(0)
    with pytest.raises(QueueFullError, match="evicted"):
        b_old.future.result(0)
    assert not n1.future.done()
    assert q.points == 8

    # All-or-nothing: CRITICAL(8) would need 6 more points but only
    # NORMAL(3) is evictable -> the submit sheds, nobody is evicted.
    with pytest.raises(QueueFullError, match="full"):
        q.put(mk_req(8, Priority.CRITICAL, enq_t=5.0))
    assert not n1.future.done()
    assert q.points == 8

    # NORMAL cannot evict NORMAL (strictly-lower-class only).
    with pytest.raises(QueueFullError, match="full"):
        q.put(mk_req(6, Priority.NORMAL, enq_t=6.0))
    assert not n1.future.done()

    snap = q._metrics.snapshot()
    assert snap["serve_queue_evicted_by_class_total{priority=batch}"] == 2
    assert snap["serve_queue_evicted_by_class_total{priority=normal}"] == 0
    assert snap["serve_queue_evicted_total"] == 2
    # Evictions count as sheds (delivered late) in the same totals.
    assert snap["serve_shed_by_class_total{priority=batch}"] == 2


def test_dispatch_order_stays_fifo_across_classes():
    """Classes decide who is SHED, never who jumps the queue."""
    q = AdmissionQueue(100)
    b = mk_req(2, Priority.BATCH, enq_t=1.0)
    c = mk_req(2, Priority.CRITICAL, enq_t=2.0)
    q.put(b)
    q.put(c)
    assert q.take_group(100) == [b, c]  # FIFO, not priority order


def test_brownout_hysteresis_on_queue_depth(dcf, bundles, prg, rng):
    """Queue-depth pressure must HOLD for brownout_after_s before
    brownout enters (one coalescing burst is not an overload), and
    clear_s of calm must pass before it exits.

    The pressure check reads the queue BEFORE the submit's own points
    are admitted, so pressure starts at the first submit that OBSERVES
    a loaded queue."""
    clock = FakeClock()
    svc = make_service(dcf, bundles, clock, breaker_failures=0,
                       max_queued_points=20, brownout_queue_fraction=0.5,
                       brownout_after_s=1.0, brownout_clear_s=2.0)
    xs8 = rng.integers(0, 256, (8, NB), dtype=np.uint8)
    xs2 = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    svc.submit("relu-a", xs8)
    svc.submit("relu-a", xs2)  # observes 8/20 < fraction
    svc.submit("relu-a", xs2)  # observes 10/20: pressure clock starts
    assert svc.metrics_snapshot()["serve_brownout"] == 0
    clock.advance(0.5)
    svc.submit("relu-a", xs2)  # pressure held 0.5s < after_s: not yet
    assert svc.metrics_snapshot()["serve_brownout"] == 0
    clock.advance(0.6)
    svc.submit("relu-a", xs2)  # held 1.1s >= after_s: brownout
    assert svc.metrics_snapshot()["serve_brownout"] == 1
    with pytest.raises(QueueFullError, match="brownout"):
        svc.submit("relu-a", xs2, priority="batch")
    # NORMAL and CRITICAL are still admitted under brownout.
    svc.submit("relu-a", xs2, priority="critical")
    svc.pump()  # drains the queue: pressure gone, calm starts
    assert svc.metrics_snapshot()["serve_brownout"] == 1  # not yet
    clock.advance(1.0)
    svc.pump()  # calm 1.0s < clear_s
    assert svc.metrics_snapshot()["serve_brownout"] == 1
    clock.advance(1.5)
    svc.pump()  # calm 2.5s >= clear_s: exit
    assert svc.metrics_snapshot()["serve_brownout"] == 0
    fb = svc.submit("relu-a", xs2, priority="batch")
    svc.pump()
    assert np.array_equal(fb.result(0),
                          oracle(prg, bundles["relu-a"], 0, xs2))


def test_tiny_queue_bound_does_not_latch_brownout(dcf, bundles, rng):
    """A small max_queued_points must not truncate the brownout depth
    threshold to 0 — an EMPTY queue satisfies ``points >= 0``, so an
    idle service would enter brownout after brownout_after_s and never
    exit, refusing every BATCH submit forever."""
    clock = FakeClock()
    svc = make_service(dcf, bundles, clock, breaker_failures=0,
                       max_queued_points=1, brownout_queue_fraction=0.75,
                       brownout_after_s=0.5, brownout_clear_s=1.0)
    xs1 = rng.integers(0, 256, (1, NB), dtype=np.uint8)
    svc.submit("relu-a", xs1)
    svc.pump()  # empty queue observed; an idle tick, not pressure
    clock.advance(1.0)  # > brownout_after_s of pure idleness
    svc.pump()
    assert svc.metrics_snapshot()["serve_brownout"] == 0
    fb = svc.submit("relu-a", xs1, priority="batch")  # still admitted
    svc.pump()
    fb.result(0)
    with pytest.raises(ValueError, match="max_queued_points"):
        ServeConfig(max_queued_points=0)


def test_stale_open_breaker_does_not_latch_brownout(dcf, bundles, prg,
                                                    rng):
    """An OPEN breaker whose cooldown has elapsed unprobed — e.g. its
    backend family was demoted away from, so no traffic will ever route
    there to probe it — must stop counting as brownout pressure: open
    pressure means *actively failing*, not *historically failed*.
    Without this, one pallas failure before a demotion to bitsliced
    would refuse BATCH traffic forever on a healthy service."""
    clock = FakeClock()
    svc = make_service(dcf, bundles, clock, breaker_failures=1,
                       breaker_cooldown_s=5.0, brownout_clear_s=1.0)
    # A failure recorded against a family the facade no longer selects:
    # after this gate-then-outcome pair nothing will ever call allow()
    # for it again, so it can never half-open.
    svc.breakers.allow("relu-a", "pallas")
    svc.breakers.record_failure("relu-a", "pallas")
    xs = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    with pytest.raises(QueueFullError, match="brownout"):
        svc.submit("relu-a", xs, priority="batch")  # inside cooldown
    clock.advance(5.5)  # cooldown elapsed; the breaker is probe-ready
    svc.pump()  # pressure gone: calm starts
    clock.advance(1.1)  # > brownout_clear_s
    fb = svc.submit("relu-a", xs, priority="batch")
    svc.pump()
    assert np.array_equal(fb.result(0),
                          oracle(prg, bundles["relu-a"], 0, xs))
    assert svc.metrics_snapshot()["serve_brownout"] == 0
    # The stale breaker keeps its state (history is preserved; only
    # unregister forgets) — it just no longer holds the brownout gate.
    assert svc.breakers.state("relu-a", "pallas") == OPEN


def test_loadgen_priority_mix_rejects_negative_weights():
    """A negative weight must fail loudly at the loadgen edge — inside
    the client threads it would kill every one of them at rng.choice
    and silently zero the offered load."""
    from dcf_tpu.serve.loadgen import closed_loop

    for mix in ({"batch": -0.2, "normal": 1.0}, {"batch": 0.0}):
        with pytest.raises(ValueError, match=">= 0 and sum > 0"):
            closed_loop(None, [], duration_s=0.0, concurrency=0,
                        min_points=1, max_points=1, priority_mix=mix)


def test_loadgen_priority_mix_rejects_unknown_class():
    """A typo'd class name must fail loudly at the loadgen edge too —
    inside the clients it would raise from parse_priority on every
    submit, which the broadened client except counts as requests_failed
    (a 100%-failed run with no loud error)."""
    from dcf_tpu.serve.loadgen import closed_loop

    with pytest.raises(ValueError, match="priority"):
        closed_loop(None, [], duration_s=0.0, concurrency=0,
                    min_points=1, max_points=1,
                    priority_mix={"critcal": 1.0})


# ------------------------------------------------- injected latency


def test_latency_seam_expires_deadlines_without_sleeping(dcf, bundles,
                                                         prg, rng):
    """A slow backend modeled by ADVANCING the fake clock at the eval
    seam: the first group's eval pushes the clock past the second
    queued group's deadline, which then expires typed at the next batch
    formation — zero wall-clock sleeps anywhere."""
    clock = FakeClock()
    svc = make_service(dcf, bundles, clock, breaker_failures=0)
    xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
    with faults.inject("serve.eval",
                       handler=faults.latency(clock, 0.2)):
        f_slow = svc.submit("relu-a", xs)  # group 1: eval advances 0.2s
        f_dead = svc.submit("relu-b", xs, deadline_ms=100.0)  # group 2
        svc.pump()
    assert np.array_equal(f_slow.result(0),
                          oracle(prg, bundles["relu-a"], 0, xs))
    with pytest.raises(DeadlineExceededError):
        f_dead.result(0)
    snap = svc.metrics_snapshot()
    assert snap["serve_deadline_expired_total"] == 1
    # The latency showed up in the eval histogram off the same clock.
    assert snap["serve_eval_s_sum"] >= 0.2


def test_latency_then_chains_slow_and_failing(dcf, bundles, rng):
    clock = FakeClock()
    svc = make_service(dcf, bundles, clock, retries=0,
                       breaker_failures=0)
    xs = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    sched = faults.Schedule(window_evals=1)
    with faults.inject("serve.eval",
                       handler=faults.latency(clock, 0.5, then=sched)):
        t0 = clock()
        f = svc.submit("relu-a", xs)
        svc.pump()
        with pytest.raises(faults.InjectedFault):
            f.result(0)
        assert clock() - t0 >= 0.5  # slow AND failing


# ------------------------------------------------- seeded flaky faults


def test_flaky_fault_pattern_is_seed_deterministic(dcf, bundles, prg,
                                                   rng):
    """Two runs with the same (rate, seed) replay the exact same
    ok/fail pattern; every delivered success is bit-exact."""
    xs = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    want = oracle(prg, bundles["relu-a"], 0, xs)

    def run():
        clock = FakeClock()
        svc = make_service(dcf, bundles, clock, retries=0,
                           breaker_failures=0)
        pattern = []
        with faults.inject("serve.eval",
                           handler=faults.flaky(0.5, seed=7)):
            for _ in range(12):
                f = svc.submit("relu-a", xs)
                svc.pump()
                try:
                    y = f.result(0)
                except faults.InjectedFault:
                    pattern.append(False)
                else:
                    assert np.array_equal(y, want)
                    pattern.append(True)
        return pattern

    p1, p2 = run(), run()
    assert p1 == p2
    assert True in p1 and False in p1  # rate=0.5 actually mixes


# ----------------------------------------------------- the chaos soak


@pytest.mark.slow
@pytest.mark.lockwatch  # serial leg: every lock order this soak takes is proven acyclic
def test_soak_flapping_windows_threaded_bit_exact(dcf, bundles, prg,
                                                  rng):
    """Serial-leg soak: 3 client threads of closed-loop load while the
    ``serve.eval`` seam flaps — fail-6 / pass-18, repeating — under a
    short real-clock breaker cooldown.  The breaker must complete at
    least one full open -> half_open -> closed walk per direction, the
    board must end closed (recovery, not wedge), and EVERY delivered
    result must be bit-exact against the numpy oracle."""
    svc = make_service(dcf, bundles, None, retries=1, breaker_failures=3,
                       breaker_cooldown_s=0.05, max_batch=64)
    counter = {"n": 0}  # fired from the single worker thread only

    def flapping(*_args):
        counter["n"] += 1
        if counter["n"] % 24 < 6:
            raise faults.InjectedFault("flap window")

    stop = threading.Event()
    lock = threading.Lock()
    delivered = []  # (name, xs, y) for post-hoc oracle verification
    failures = {"typed": 0, "injected": 0, "other": 0}

    def client(i):
        crng = np.random.default_rng(1000 + i)
        names = sorted(bundles)
        prio = ["critical", "normal", "batch"]
        while not stop.is_set():
            name = names[int(crng.integers(0, len(names)))]
            xs = crng.integers(0, 256, (int(crng.integers(1, 9)), NB),
                               dtype=np.uint8)
            try:
                fut = svc.submit(name, xs, priority=prio[i % 3])
                y = fut.result(30)
            except (QueueFullError, CircuitOpenError):
                with lock:
                    failures["typed"] += 1
                continue
            except faults.InjectedFault:
                with lock:
                    failures["injected"] += 1
                continue
            except Exception:  # noqa: BLE001 — counted and asserted 0
                with lock:
                    failures["other"] += 1
                continue
            with lock:
                delivered.append((name, xs, y))

    def flapped_enough():
        snap = svc.metrics_snapshot()
        with lock:
            n = len(delivered)
        return (snap.get("serve_breaker_transitions_total{to=open}", 0)
                >= 1
                and snap.get(
                    "serve_breaker_transitions_total{to=closed}", 0) >= 1
                and n > 50)

    with svc:
        # Warm the padded-shape ladder BEFORE arming faults: an XLA
        # compile inside the flap window would starve the batch count
        # (same rule as test_serve_soak and chaos_bench).
        m = 1
        while m <= 64:
            svc.evaluate("relu-a",
                         rng.integers(0, 256, (m, NB), dtype=np.uint8),
                         timeout=180)
            m *= 2
        with faults.inject("serve.eval", handler=flapping):
            threads = [threading.Thread(target=client, args=(i,),
                                        daemon=True) for i in range(3)]
            for t in threads:
                t.start()
            # Soak in bounded slices until the breaker really completed
            # a full flap under load (contended CI hosts fit few batches
            # per second — keep going, bounded).
            for _ in range(12):
                stop.wait(2.0)
                if flapped_enough():
                    break
            stop.set()
            for t in threads:
                t.join(30)
                assert not t.is_alive()
        # Seam clean again: drive each key until any mid-flap open
        # breaker has cooled down, probed, and closed (bounded).
        xs_post = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        for name in sorted(bundles):
            for _ in range(60):
                try:
                    svc.evaluate(name, xs_post, timeout=60)
                    break
                except CircuitOpenError:
                    threading.Event().wait(0.02)  # let the cooldown run
            else:
                pytest.fail(f"breaker for {name} never recovered")

    snap = svc.metrics_snapshot()
    assert snap["serve_breaker_transitions_total{to=open}"] >= 1
    assert snap["serve_breaker_transitions_total{to=closed}"] >= 1
    assert not svc.breakers.any_open()  # recovered, not wedged
    assert snap["serve_shed_by_class_total{priority=critical}"] == 0
    assert failures["other"] == 0, "non-chaos failures leaked to clients"
    assert len(delivered) > 50, "soak barely served anything"
    for name, xs, y in delivered:
        assert np.array_equal(y, oracle(prg, bundles[name], 0, xs)), name
