"""dcf_tpu.serve.edge: the zero-copy DCFK wire path (ISSUE 12).

Covers the acceptance contract — wire-path two-party reconstruction
bit-exact vs the numpy oracle, the bytes-ingest entry as the ONLY
batcher feed (zero per-point Python objects on ingest), tenant->class
mapping with the per-tenant token bucket, typed retry-after hints on
every refusal class — plus the wire-frame fuzz (seeded byte flips,
truncations, oversized length prefixes, mid-frame disconnects all die
as typed PER-CONNECTION errors that never kill the accept loop or
another tenant's connection), the ``edge.accept``/``edge.read`` fault
seams, the slow-client walk on the fake clock (a stalled sender trips
the existing deadline path instead of wedging the worker), and the
open-loop (Poisson) loadgen mode with its metric reconciliation.  The
8-connection soak under injected read faults rides the serial slow
leg.
"""

import pathlib
import socket
import struct
import threading

import numpy as np
import pytest

from dcf_tpu import Dcf
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.errors import (
    CircuitOpenError,
    QueueFullError,
    ShapeError,
)
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.serve import DcfService, ServeConfig, TenantSpec
from dcf_tpu.serve.batcher import ingest_points
from dcf_tpu.serve.edge import (
    E_DEADLINE,
    E_RATE_LIMITED,
    E_WIRE,
    EdgeClient,
    EdgeServer,
    T_ERROR,
    T_SHARE,
    TokenBucket,
    decode_response,
    encode_request,
)
from dcf_tpu.testing import faults
from dcf_tpu.testing.faults import FakeClock

pytestmark = pytest.mark.edge

NB, LAM = 2, 16


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0xED6E)


@pytest.fixture(scope="module")
def ck(rng):
    return [rng.bytes(32), rng.bytes(32)]


@pytest.fixture(scope="module")
def dcf(ck):
    return Dcf(NB, LAM, ck, backend="bitsliced")


@pytest.fixture(scope="module")
def prg(ck):
    return HirosePrgNp(LAM, ck)


@pytest.fixture(scope="module")
def bundles(dcf, rng):
    out = {}
    for name, k in (("edge-a", 1), ("edge-b", 1)):
        alphas = rng.integers(0, 256, (k, NB), dtype=np.uint8)
        betas = rng.integers(0, 256, (k, LAM), dtype=np.uint8)
        out[name] = dcf.gen(alphas, betas, rng=rng)
    return out


def oracle(prg, bundle, b, xs):
    return eval_batch_np(prg, b, bundle.for_party(b), xs)


def recon_oracle(prg, bundle, xs):
    return oracle(prg, bundle, 0, xs) ^ oracle(prg, bundle, 1, xs)


def make_service(dcf, bundles, **knobs):
    knobs.setdefault("max_batch", 32)
    knobs.setdefault("max_delay_ms", 1.0)
    svc = dcf.serve(**knobs)
    for name, bundle in bundles.items():
        svc.register_key(name, bundle)
    return svc


def started_edge(dcf, bundles, **knobs):
    svc = make_service(dcf, bundles, **knobs)
    svc.start()
    server = EdgeServer(svc).start()
    return svc, server


def _read_frames(sock) -> list:
    """Drain one raw socket to EOF and strictly decode every response
    frame on it.  A reset counts as EOF: the server hanging up on a
    mangled frame (with our unread bytes still in its receive buffer)
    surfaces as RST — the typed-containment outcome, not a failure."""
    data = b""
    while True:
        try:
            chunk = sock.recv(1 << 16)
        except ConnectionResetError:
            break
        if not chunk:
            break
        data += chunk
    frames = []
    off = 0
    while off < len(data):
        (body_len,) = struct.unpack_from("<I", data, off)
        body = data[off + 4:off + 4 + body_len]
        frames.append(decode_response(body))
        off += 4 + body_len
    return frames


# --------------------------------------------------------- acceptance


def test_wire_roundtrip_parity_vs_oracle(dcf, bundles, prg, rng):
    """Ragged requests, both parties, through a real TCP connection:
    every reconstruction bit-exact vs the numpy oracle."""
    svc, server = started_edge(dcf, bundles)
    try:
        with EdgeClient(*server.address, n_bytes=NB) as c:
            for i in range(6):
                name = sorted(bundles)[i % 2]
                m = int(rng.integers(1, 40)) if i != 3 else 1
                xs = rng.integers(0, 256, (m, NB), dtype=np.uint8)
                y0 = c.evaluate(name, xs, b=0, timeout=60)
                y1 = c.evaluate(name, xs, b=1, timeout=60)
                assert np.array_equal(
                    y0 ^ y1, recon_oracle(prg, bundles[name], xs)), name
    finally:
        server.close()
        svc.close()


def test_ingest_points_zero_copy_contract(rng):
    """The bytes-ingest entry aliases the caller's buffer — no copy,
    no per-point objects — and enforces the geometry strictly."""
    buf = bytearray(rng.integers(0, 256, 12, dtype=np.uint8).tobytes())
    arr = ingest_points(buf, 3)  # m derived: 12 / 3
    assert arr.shape == (4, 3) and arr.dtype == np.uint8
    assert np.shares_memory(arr, np.frombuffer(buf, dtype=np.uint8))
    buf[0] ^= 0xFF  # a view sees the mutation; a copy would not
    assert arr[0, 0] == buf[0]
    assert ingest_points(memoryview(buf), 3, m=4).shape == (4, 3)
    with pytest.raises(ShapeError):
        ingest_points(buf, 5)  # 12 % 5 != 0
    with pytest.raises(ShapeError):
        ingest_points(buf, 3, m=5)  # wrong m
    with pytest.raises(ShapeError):
        ingest_points(b"", 3)  # empty
    with pytest.raises(ShapeError):
        ingest_points(buf, 0)


def test_ingest_entry_is_the_only_batcher_feed(dcf, bundles, prg, rng,
                                               monkeypatch):
    """Both ingest paths — in-process ``submit`` and the wire path —
    route every request through ``batcher.ingest_points`` exactly
    once, and the array each request evaluates is a VIEW of the
    ingested buffer (the zero-per-point-object claim, asserted at the
    single feed)."""
    import dcf_tpu.serve.service as service_mod

    calls = []
    real = service_mod.ingest_points

    def counting(data, n_bytes, m=None):
        out = real(data, n_bytes, m)
        assert out.base is not None  # a view, never a fresh copy
        calls.append(out.shape[0])
        return out

    monkeypatch.setattr(service_mod, "ingest_points", counting)
    svc, server = started_edge(dcf, bundles)
    try:
        xs = rng.integers(0, 256, (9, NB), dtype=np.uint8)
        y_in = svc.evaluate("edge-a", xs, timeout=60)
        with EdgeClient(*server.address, n_bytes=NB) as c:
            y_wire = c.evaluate("edge-a", xs, timeout=60)
        assert calls == [9, 9]  # one ingest per request, either path
        assert np.array_equal(y_in, y_wire)
        assert np.array_equal(y_in, oracle(prg, bundles["edge-a"], 0,
                                           xs))
    finally:
        server.close()
        svc.close()


# ------------------------------------------------- tenants + buckets


def test_token_bucket_exact_refill_schedule():
    clk = FakeClock(100.0)
    tb = TokenBucket(10.0, 20, clk())
    assert tb.admit(20, clk()) == 0.0  # the burst drains
    retry = tb.admit(5, clk())
    assert retry == pytest.approx(0.5)  # 5 tokens at 10/s
    clk.advance(0.5)
    assert tb.admit(5, clk()) == 0.0  # the hint was exact
    # a request above capacity can never pass — the hint is the
    # (unreachable) time-to-points, always positive
    retry = tb.admit(100, clk())
    assert retry == pytest.approx(10.0)
    # ... INCLUDING against a FULL bucket: clamping the hint at
    # capacity would return 0.0 here, which the edge reads as
    # "admitted" — a zero-token rate-limit bypass for any oversized
    # request (and the tokens must stay untouched by the refusal)
    full = TokenBucket(10.0, 20, clk())
    assert full.admit(1000, clk()) == pytest.approx(98.0)
    assert full.admit(20, clk()) == 0.0  # nothing was drained
    assert TokenBucket(0.0, 0, clk()).admit(10 ** 9, clk()) == 0.0


def test_tenant_classes_and_rate_limit_hints(dcf, bundles, rng):
    """The tenant table maps onto the EXISTING classes: a bronze
    (BATCH) tenant is brownout-refused where silver (NORMAL) serves;
    a request can self-demote but never self-promote above its tenant
    class; bucket refusals carry the exact time-to-refill."""
    svc, server = started_edge(
        dcf, bundles,
        tenants=(TenantSpec("gold", "critical"),
                 TenantSpec("silver", "normal"),
                 TenantSpec("bronze", "batch"),
                 TenantSpec("capped", "normal", points_per_sec=50.0,
                            burst_points=8)))
    try:
        xs = rng.integers(0, 256, (8, NB), dtype=np.uint8)
        host, port = server.address
        with EdgeClient(host, port, n_bytes=NB, tenant="capped") as c:
            assert c.evaluate("edge-a", xs, timeout=60).shape == \
                (1, 8, LAM)
            with pytest.raises(QueueFullError) as ei:  # bucket empty
                c.evaluate("edge-a", xs, timeout=60)
            assert ei.value.retry_after_s == pytest.approx(8 / 50.0,
                                                           rel=0.5)
        # Brownout: BATCH refused at the door; the tenant class — not
        # the frame's claimed priority — decides.
        svc.queue.set_brownout(True)
        with EdgeClient(host, port, n_bytes=NB, tenant="gold") as gold, \
                EdgeClient(host, port, n_bytes=NB,
                           tenant="silver") as silver, \
                EdgeClient(host, port, n_bytes=NB,
                           tenant="bronze") as bronze:
            assert silver.evaluate("edge-a", xs,
                                   timeout=60).shape == (1, 8, LAM)
            with pytest.raises(QueueFullError) as ei:
                # self-promotion is capped at the tenant class: the
                # frame claims CRITICAL, the bronze table row says
                # BATCH, brownout refuses BATCH
                bronze.evaluate("edge-a", xs, timeout=60,
                                priority="critical")
            assert ei.value.retry_after_s == pytest.approx(
                svc.config.brownout_clear_s)
            with pytest.raises(QueueFullError):
                # self-DEMOTION works: gold may mark its own traffic
                # BATCH and eat the brownout refusal
                gold.evaluate("edge-a", xs, timeout=60,
                              priority="batch")
            assert gold.evaluate("edge-a", xs,
                                 timeout=60).shape == (1, 8, LAM)
        svc.queue.set_brownout(False)
        snap = svc.metrics_snapshot()
        assert snap["edge_tenant_refusals_total{tenant=capped}"] == 1
        assert snap["edge_tenant_refusals_total{tenant=bronze}"] == 0
        assert snap["edge_tenant_requests_total{tenant=silver}"] == 1
    finally:
        server.close()
        svc.close()


def test_unknown_tenant_refused_typed(dcf, bundles, rng):
    svc, server = started_edge(
        dcf, bundles, tenants=(TenantSpec("gold", "critical"),))
    try:
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        host, port = server.address
        with EdgeClient(host, port, n_bytes=NB, tenant="nobody") as c:
            with pytest.raises(ValueError, match="unknown tenant"):
                c.evaluate("edge-a", xs, timeout=60)
        # the refusal was request-level: the accept loop still serves
        with EdgeClient(host, port, n_bytes=NB, tenant="gold") as c:
            assert c.evaluate("edge-a", xs, timeout=60).shape == \
                (1, 4, LAM)
    finally:
        server.close()
        svc.close()


# --------------------------------------------------- retry-after (in-process)


def test_circuit_open_carries_cooldown_retry_after(dcf, bundles, rng):
    """An open breaker's CircuitOpenError carries the REMAINING
    cooldown, ticking down on the injectable clock."""
    clk = FakeClock(50.0)
    svc = DcfService(dcf, ServeConfig(
        max_batch=32, retries=0, breaker_failures=1,
        breaker_cooldown_s=4.0), clock=clk)
    for name, bundle in bundles.items():
        svc.register_key(name, bundle)
    xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
    with faults.inject("serve.eval"):
        fut = svc.submit("edge-a", xs)
        svc.pump()
    with pytest.raises(faults.InjectedFault):
        fut.result(1)
    fut = svc.submit("edge-a", xs)
    svc.pump()
    with pytest.raises(CircuitOpenError) as ei:
        fut.result(1)
    assert ei.value.retry_after_s == pytest.approx(4.0)
    clk.advance(1.5)
    fut = svc.submit("edge-a", xs)
    svc.pump()
    with pytest.raises(CircuitOpenError) as ei:
        fut.result(1)
    assert ei.value.retry_after_s == pytest.approx(2.5)
    assert svc.breakers.retry_after("edge-a",
                                    dcf.backend_name) == \
        pytest.approx(2.5)
    assert svc.breakers.retry_after("edge-b", dcf.backend_name) is None
    svc.close(drain=False)


def test_overload_and_brownout_retry_after(dcf, bundles, rng):
    """Queue-full sheds advise ~two coalescing windows; brownout
    refusals advise brownout_clear_s; draining advises nothing."""
    svc = make_service(dcf, bundles, max_queued_points=8,
                       max_delay_ms=3.0, brownout_clear_s=2.5)
    xs = rng.integers(0, 256, (6, NB), dtype=np.uint8)
    svc.submit("edge-a", xs)
    with pytest.raises(QueueFullError) as ei:  # 6 + 6 > 8
        svc.submit("edge-a", xs)
    assert ei.value.retry_after_s == pytest.approx(2 * 3.0 / 1e3)
    svc.queue.set_brownout(True)
    with pytest.raises(QueueFullError) as ei:
        svc.submit("edge-a", xs, priority="batch")
    assert ei.value.retry_after_s == pytest.approx(2.5)
    svc.queue.set_brownout(False)
    svc.close()
    with pytest.raises(QueueFullError) as ei:
        svc.submit("edge-a", xs)
    assert ei.value.retry_after_s is None


def test_eviction_carries_evicted_flag_across_the_wire(dcf, bundles,
                                                       rng):
    """Post-acceptance evictions are marked ``evicted`` (the request
    WAS counted in serve_requests_total) and the marker survives the
    wire as its own code — load accounting must not retract a 'sent'
    for them."""
    from dcf_tpu.serve.edge import (
        E_EVICTED,
        E_QUEUE_FULL,
        _code_for,
        _raise_wire,
    )

    svc = make_service(dcf, bundles, max_queued_points=8)
    xs = rng.integers(0, 256, (6, NB), dtype=np.uint8)
    f_batch = svc.submit("edge-a", xs, priority="batch")
    svc.submit("edge-a", xs, priority="critical")  # evicts the batch
    with pytest.raises(QueueFullError) as ei:
        f_batch.result(1)
    assert ei.value.evicted is True
    assert ei.value.retry_after_s is not None
    # submit-time sheds stay unmarked
    with pytest.raises(QueueFullError) as ei:
        svc.submit("edge-a", xs, priority="batch")
    assert ei.value.evicted is False
    svc.close(drain=False)
    # the wire mapping round-trips the marker
    e = QueueFullError("x", retry_after_s=1.0, evicted=True)
    assert _code_for(e) == E_EVICTED
    back = _raise_wire(E_EVICTED, 1.0, "x")
    assert isinstance(back, QueueFullError)
    assert back.evicted is True and back.retry_after_s == 1.0
    assert _raise_wire(E_QUEUE_FULL, None, "y").evicted is False


# --------------------------------------------------------- wire fuzz


def _valid_request_frame(key_id: str, xs) -> bytes:
    return encode_request(7, "", key_id, 0, 255, None, xs.data,
                          xs.shape[1], xs.shape[0])


def test_request_frame_byte_flips_die_typed(dcf, bundles, rng):
    """Seeded byte flips of a valid request frame: every mutation dies
    as a typed PER-CONNECTION outcome (an ERROR frame and/or a closed
    connection) — never a SHARE of corrupt provenance, never a dead
    accept loop.  A healthy connection keeps serving throughout."""
    svc, server = started_edge(dcf, bundles)
    try:
        host, port = server.address
        xs = rng.integers(0, 256, (5, NB), dtype=np.uint8)
        frame = _valid_request_frame("edge-a", xs)
        healthy = EdgeClient(host, port, n_bytes=NB)
        offsets = rng.integers(0, len(frame), 40)
        xors = rng.integers(1, 256, 40)
        for i, (off, xor) in enumerate(zip(offsets, xors)):
            mutated = faults.corrupt(frame, int(off), int(xor))
            s = socket.create_connection((host, port), timeout=10)
            s.sendall(mutated)
            s.shutdown(socket.SHUT_WR)  # a short frame = disconnect
            s.settimeout(10)
            try:
                frames = _read_frames(s)
            finally:
                s.close()
            for f in frames:
                assert f[0] == "error", \
                    f"flip #{i} (offset {off}, xor {xor:#04x}) " \
                    f"produced a SHARE from a corrupt frame"
            # the accept loop and the other connection survive
            assert healthy.evaluate(
                "edge-a", xs, timeout=60).shape == (1, 5, LAM)
        healthy.close()
    finally:
        server.close()
        svc.close()


def test_truncations_and_oversized_prefix_die_typed(dcf, bundles, rng):
    """Truncated frames are mid-frame disconnects (contained, counted);
    an oversized length prefix is refused typed without allocating or
    reading the claimed body."""
    svc, server = started_edge(dcf, bundles)
    server.max_frame_bytes = 1 << 16
    try:
        host, port = server.address
        xs = rng.integers(0, 256, (5, NB), dtype=np.uint8)
        frame = _valid_request_frame("edge-a", xs)
        for cut in sorted({int(c)
                           for c in rng.integers(1, len(frame), 10)}):
            s = socket.create_connection((host, port), timeout=10)
            s.sendall(frame[:cut])
            s.shutdown(socket.SHUT_WR)
            s.settimeout(10)
            frames = _read_frames(s)
            s.close()
            assert all(f[0] == "error" for f in frames)
        errors_before = svc.metrics_snapshot()[
            "edge_wire_errors_total"]
        s = socket.create_connection((host, port), timeout=10)
        s.sendall(struct.pack("<I", (1 << 20)))  # over the 64 KB bound
        s.settimeout(10)
        frames = _read_frames(s)
        s.close()
        assert len(frames) == 1
        kind, req_id, code, retry, msg = frames[0]
        assert (kind, code) == ("error", E_WIRE)
        assert "length prefix" in msg
        deadline = 200
        while svc.metrics_snapshot()[
                "edge_wire_errors_total"] <= errors_before:
            deadline -= 1
            assert deadline > 0, "wire error never counted"
        # still serving
        with EdgeClient(host, port, n_bytes=NB) as c:
            assert c.evaluate("edge-a", xs, timeout=60).shape == \
                (1, 5, LAM)
    finally:
        server.close()
        svc.close()


def test_edge_read_fault_kills_one_connection_only(dcf, bundles, rng):
    """An armed edge.read fault ends exactly the connection whose read
    fired — typed at the client, with every other connection and the
    accept loop untouched."""
    svc, server = started_edge(dcf, bundles)
    try:
        host, port = server.address
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        a = EdgeClient(host, port, n_bytes=NB)
        b = EdgeClient(host, port, n_bytes=NB)
        assert a.evaluate("edge-a", xs, timeout=60).shape == (1, 4, LAM)
        assert b.evaluate("edge-a", xs, timeout=60).shape == (1, 4, LAM)
        from dcf_tpu.errors import DcfError

        with faults.inject_schedule("edge.read", window_evals=1):
            with pytest.raises(DcfError):
                # the next read on A's connection dies; the pending
                # future fails typed — the connection-level wire error
                # (DcfError carrying the injected cause) or, if EOF
                # wins the race, BackendUnavailableError (a subclass)
                a.evaluate("edge-a", xs, timeout=60)
        # B never noticed; a reconnect of A serves again
        assert b.evaluate("edge-a", xs, timeout=60).shape == (1, 4, LAM)
        a.close()
        with EdgeClient(host, port, n_bytes=NB) as a2:
            assert a2.evaluate("edge-a", xs,
                               timeout=60).shape == (1, 4, LAM)
        b.close()
    finally:
        server.close()
        svc.close()


def test_edge_accept_fault_loop_survives(dcf, bundles, rng):
    """A raising edge.accept fault is counted and the loop keeps
    accepting — the next connection serves."""
    svc, server = started_edge(dcf, bundles)
    try:
        host, port = server.address
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        with EdgeClient(host, port, n_bytes=NB) as c1:
            assert c1.evaluate("edge-a", xs,
                               timeout=60).shape == (1, 4, LAM)
            with faults.inject_schedule("edge.accept",
                                        window_evals=1) as sched:
                # c2 may be accepted by the loop iteration already
                # parked in accept(); the armed fire kills a LATER
                # iteration — c3 proves the loop outlived it.
                with EdgeClient(host, port, n_bytes=NB) as c2:
                    assert c2.evaluate(
                        "edge-a", xs, timeout=60).shape == (1, 4, LAM)
                with EdgeClient(host, port, n_bytes=NB) as c3:
                    assert c3.evaluate(
                        "edge-a", xs, timeout=60).shape == (1, 4, LAM)
                assert sched.failed == 1  # the window was consumed
        assert svc.metrics_snapshot()["edge_accept_errors_total"] >= 1
    finally:
        server.close()
        svc.close()


def test_read_timeout_bounds_slow_loris(dcf, bundles, rng):
    """``read_timeout_s``: a peer stalling mid-frame costs at most the
    bound before its connection dies typed and counted — a healthy
    connection is untouched."""
    svc = make_service(dcf, bundles)
    svc.start()
    server = EdgeServer(svc, read_timeout_s=0.2).start()
    try:
        host, port = server.address
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        loris = socket.create_connection((host, port), timeout=10)
        loris.sendall(_valid_request_frame("edge-a", xs)[:9])  # stall
        spins = 200
        while svc.metrics_snapshot()[
                "edge_connection_errors_total"] < 1:
            spins -= 1
            assert spins > 0, "stalled reader never timed out"
            threading.Event().wait(0.02)
        loris.close()
        with EdgeClient(host, port, n_bytes=NB) as c:
            assert c.evaluate("edge-a", xs, timeout=60).shape == \
                (1, 4, LAM)
    finally:
        server.close()
        svc.close()
    with pytest.raises(ValueError, match="read_timeout_s"):
        EdgeServer(svc, read_timeout_s=-1)


# ------------------------------------------------- slow-client walk


def test_slow_client_trips_deadline_not_worker(dcf, bundles, prg, rng):
    """The slow-client seam: ``latency`` armed at edge.read advances
    the injectable clock on every server recv, so a sender stalling
    mid-frame expires its own QUEUED request through the existing
    deadline path (typed DEADLINE error frame) while another
    connection keeps serving — the worker never wedges on the stalled
    socket."""
    clk = FakeClock(1000.0)
    svc = DcfService(dcf, ServeConfig(max_batch=32, max_delay_ms=0.0),
                     clock=clk)
    for name, bundle in bundles.items():
        svc.register_key(name, bundle)
    server = EdgeServer(svc).start()
    try:
        host, port = server.address
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        req1 = encode_request(1, "", "edge-a", 0, 255, 50.0, xs.data,
                              NB, 4)  # 50 ms deadline on the fake clock
        req2 = encode_request(2, "", "edge-a", 0, 255, None, xs.data,
                              NB, 4)
        with faults.inject("edge.read",
                           handler=faults.latency(clk, 0.2)):
            slow = socket.create_connection((host, port), timeout=30)
            slow.sendall(req1)
            slow.sendall(req2[:7])  # ... and stall mid-frame
            # wait until the server's reads have advanced the clock
            # well past req1's deadline (each recv fire adds 0.2 s;
            # reaching +0.95 needs the post-submit req2 fires, so
            # req1 is guaranteed both SUBMITTED and expired)
            spins = 2000
            while clk.t < 1000.0 + 0.95:
                spins -= 1
                assert spins > 0, "edge.read latency never advanced " \
                    "the clock"
                threading.Event().wait(0.005)
            # the worker is NOT wedged: another connection round-trips
            # while the slow one stalls (pump() drives the service and
            # expires req1 on the way)
            with EdgeClient(host, port, n_bytes=NB) as healthy:
                fut = healthy.submit("edge-b", xs)
                spins = 2000  # pump until the server thread has queued
                while not fut.done():  # the request (no worker thread
                    svc.pump()         # in this fake-clock setup)
                    spins -= 1
                    assert spins > 0, "healthy request never served"
                    threading.Event().wait(0.005)
                assert np.array_equal(
                    fut.result(60),
                    oracle(prg, bundles["edge-b"], 0, xs))
            # req1 expired typed through the queue's deadline sweep
            slow.sendall(req2[7:])  # un-stall: req2 completes normally
            # Pump-and-poll: the service has no worker thread here, so
            # a pump may be needed AFTER the server thread queues req2
            # — never block in recv without pumping again.
            slow.settimeout(0.2)
            got = {}
            buf = b""
            deadline = 300
            while len(got) < 2:
                deadline -= 1
                assert deadline > 0, f"responses never arrived ({got})"
                svc.pump()
                try:
                    chunk = slow.recv(1 << 16)
                except TimeoutError:
                    continue
                assert chunk, "server hung up before both responses"
                buf += chunk
                while len(buf) >= 4:
                    (body_len,) = struct.unpack_from("<I", buf, 0)
                    if len(buf) < 4 + body_len:
                        break
                    frame = decode_response(buf[4:4 + body_len])
                    got[frame[1]] = frame
                    buf = buf[4 + body_len:]
            slow.close()
        kind1, _, code1, _, _ = got[1]
        assert (kind1, code1) == ("error", E_DEADLINE)
        kind2, _, y2 = got[2]
        assert kind2 == "share"
        assert np.array_equal(y2, oracle(prg, bundles["edge-a"], 0, xs))
        assert svc.metrics_snapshot()[
            "serve_deadline_expired_total"] >= 1
    finally:
        server.close()
        svc.close(drain=False)


# ------------------------------------------------- open-loop loadgen


def test_open_loop_reconciles_and_drains(dcf, bundles, prg, rng):
    from dcf_tpu.serve.loadgen import open_loop

    svc = make_service(dcf, bundles, max_delay_ms=0.5)
    svc.start()
    base = svc.metrics_snapshot()
    res = open_loop(svc, sorted(bundles), rate_rps=250.0,
                    duration_s=0.6, min_points=1, max_points=8,
                    seed=11)
    snap = svc.metrics_snapshot()
    svc.close()
    assert res.attempts == res.shed + res.ok + res.expired + res.failed
    assert res.ok > 0 and res.failed == 0
    assert res.sent == snap["serve_requests_total"] \
        - base["serve_requests_total"]
    assert res.shed == snap["serve_shed_total"] - base["serve_shed_total"]
    assert res.expired == snap["serve_deadline_expired_total"] \
        - base["serve_deadline_expired_total"]
    q = res.latency_quantiles()
    assert set(q) == {"p50_s", "p90_s", "p99_s"}
    assert "normal" in res.by_class


def test_open_loop_counts_expiries_and_hinted_sheds(dcf, bundles, rng):
    """Against a stopped service every accepted request expires
    through the deadline path, and overload sheds carry their hints —
    both visible in the open-loop result."""
    from dcf_tpu.serve.loadgen import open_loop

    svc = make_service(dcf, bundles, max_queued_points=64)
    done = {}

    def run():
        done["res"] = open_loop(
            svc, sorted(bundles), rate_rps=400.0, duration_s=0.4,
            min_points=4, max_points=8, seed=13, deadline_ms=1.0)

    t = threading.Thread(target=run, daemon=True)
    t.start()  # the service is NOT pumping: the queue fills, sheds,
    t.join(0.5)  # and queued requests outlive their 1 ms deadlines
    while t.is_alive():
        svc.pump()  # expire + drain so open_loop's collectors finish
        t.join(0.05)
    res = done["res"]
    svc.close()
    assert res.expired > 0
    assert res.shed > 0
    assert res.shed_hinted == res.shed  # every shed carried its hint
    assert res.attempts == res.shed + res.ok + res.expired + res.failed


def test_open_loop_validates_flags():
    from dcf_tpu.serve.loadgen import open_loop

    with pytest.raises(ValueError, match="rate_rps"):
        open_loop(None, ["k"], rate_rps=0, duration_s=1,
                  min_points=1, max_points=2)
    with pytest.raises(ValueError, match="request-size"):
        open_loop(None, ["k"], rate_rps=10, duration_s=1,
                  min_points=3, max_points=2)
    with pytest.raises(ValueError, match="skew"):
        open_loop(None, ["k"], rate_rps=10, duration_s=1,
                  min_points=1, max_points=2, skew=-1)


# --------------------------------------------------------- the soak


@pytest.mark.slow
def test_edge_soak_8_connections_bit_exact(dcf, bundles, prg, rng):
    """The serial-leg soak: 8 concurrent connections under an
    every-12th-recv edge.read fault — connections die typed and
    reconnect, every delivered two-party reconstruction is bit-exact
    vs the numpy oracle, every refusal carries a hint, and the accept
    loop outlives all of it."""
    svc, server = started_edge(dcf, bundles, max_batch=64,
                               max_delay_ms=1.0)
    host, port = server.address
    names = sorted(bundles)
    # Warm every padded shape for BOTH parties — the soak measures the
    # failure/recovery loop, not first-compile latency.
    xs_warm = rng.integers(0, 256, (64, NB), dtype=np.uint8)
    m_warm = 1
    while m_warm <= 64:
        for b in (0, 1):
            svc.evaluate(names[0], xs_warm[:m_warm], b=b, timeout=120)
        m_warm *= 2
    stats = {"ok": 0, "bad": 0, "reconnects": 0}
    lock = threading.Lock()
    stop = threading.Event()
    fires = {"n": 0}

    def every_nth(*_a):
        fires["n"] += 1
        if fires["n"] % 12 == 0:
            raise faults.InjectedFault("edge.read soak fault")

    def client(i):
        crng = np.random.default_rng(0x50AC + i)
        conn = None
        while not stop.is_set():
            if conn is None:
                try:
                    conn = EdgeClient(host, port, n_bytes=NB)
                except OSError:
                    continue
            name = names[int(crng.integers(0, len(names)))]
            m = int(crng.integers(1, 33))
            xs = crng.integers(0, 256, (m, NB), dtype=np.uint8)
            try:
                f0 = conn.submit(name, xs, b=0)
                f1 = conn.submit(name, xs, b=1)
                got = f0.result(120) ^ f1.result(120)
            except Exception:  # noqa: BLE001 — the injected kill path
                with lock:
                    stats["reconnects"] += 1
                try:
                    conn.close()
                except Exception:  # noqa: BLE001 — best-effort close
                    pass
                conn = None
                continue
            ok = np.array_equal(got, recon_oracle(prg, bundles[name],
                                                  xs))
            with lock:
                stats["ok" if ok else "bad"] += 1
        if conn is not None:
            conn.close()

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(8)]
    try:
        with faults.inject("edge.read", handler=every_nth):
            for t in threads:
                t.start()
            stop.wait(4.0)
            stop.set()
            for t in threads:
                t.join(120)
    finally:
        server.close()
        svc.close()
    assert stats["bad"] == 0
    assert stats["ok"] >= 16
    assert stats["reconnects"] >= 1  # the fault path was exercised
    assert not any(t.is_alive() for t in threads)


# ----------------------------------------------------------- config


def test_serveconfig_tenant_table_validation():
    with pytest.raises(ValueError, match="TenantSpec"):
        ServeConfig(tenants=({"name": "x"},))
    with pytest.raises(ValueError, match="duplicate"):
        ServeConfig(tenants=(TenantSpec("a"), TenantSpec("a")))
    with pytest.raises(ValueError, match="non-empty"):
        TenantSpec("")
    with pytest.raises(ValueError, match="priority"):
        TenantSpec("a", "platinum")
    with pytest.raises(ValueError, match="points_per_sec"):
        TenantSpec("a", points_per_sec=-1)
    cfg = ServeConfig(tenants=(TenantSpec("a", "batch"),))
    from dcf_tpu.serve import Priority

    assert cfg.tenants[0].priority is Priority.BATCH


def test_wire_error_frame_decodes_typed(dcf, bundles, rng):
    """A raw look at the ERROR frame: the rate-limit refusal carries
    its code and hint on the wire itself, not just in the client's
    re-raise."""
    svc, server = started_edge(
        dcf, bundles,
        tenants=(TenantSpec("t", "normal", points_per_sec=10.0,
                            burst_points=4),))
    try:
        host, port = server.address
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        s = socket.create_connection((host, port), timeout=30)
        s.sendall(encode_request(5, "t", "edge-a", 0, 255, None,
                                 xs.data, NB, 4))
        s.sendall(encode_request(6, "t", "edge-a", 0, 255, None,
                                 xs.data, NB, 4))
        s.shutdown(socket.SHUT_WR)
        s.settimeout(30)
        deadline = 400
        frames = []
        while len(frames) < 2 and deadline:
            deadline -= 1
            svc.pump()
            try:
                frames = _read_frames(s)
            except OSError:
                break
        s.close()
        by_id = {f[1]: f for f in frames}
        assert by_id[5][0] == "share"
        kind, _, code, retry, _ = by_id[6]
        assert (kind, code) == ("error", E_RATE_LIMITED)
        assert retry == pytest.approx(4 / 10.0, rel=0.5)
        assert {T_SHARE, T_ERROR} == {2, 3}  # layout pins
    finally:
        server.close()
        svc.close()


# --------------------------------------------------------------- tls


TLS_DIR = pathlib.Path(__file__).parent / "data" / "tls"


def test_tls_loopback_parity_and_plaintext_refused(dcf, bundles, prg,
                                                   rng):
    """ISSUE 13 TLS satellite: the edge socket behind stdlib ``ssl``
    — a CA-pinned TLS client round-trips bit-exact vs the numpy
    oracle, a PLAINTEXT client against the same port dies typed as a
    per-connection failure, and the accept loop survives to serve the
    next TLS client."""
    svc, server = started_edge(
        dcf, bundles, tls_cert=str(TLS_DIR / "server.pem"),
        tls_key=str(TLS_DIR / "server.key"))
    try:
        xs = rng.integers(0, 256, (6, NB), dtype=np.uint8)
        with EdgeClient(*server.address, n_bytes=NB, tls=True,
                        tls_ca=str(TLS_DIR / "ca.pem")) as c:
            got = c.evaluate("edge-a", xs, b=0, timeout=60) ^ \
                c.evaluate("edge-a", xs, b=1, timeout=60)
        assert np.array_equal(got,
                              recon_oracle(prg, bundles["edge-a"], xs))
        # Plaintext against the TLS port: the deferred handshake fails
        # on the reader thread — this connection dies typed, counted.
        from dcf_tpu.errors import BackendUnavailableError

        before = svc.metrics_snapshot().get(
            "edge_connection_errors_total", 0)
        with pytest.raises((BackendUnavailableError, OSError)):
            plain = EdgeClient(*server.address, n_bytes=NB)
            try:
                plain.evaluate("edge-a", xs, b=0, timeout=10)
            finally:
                plain.close()
        # ...and the accept loop is alive for the next TLS peer.
        with EdgeClient(*server.address, n_bytes=NB, tls=True,
                        tls_ca=str(TLS_DIR / "ca.pem")) as c:
            c.evaluate("edge-a", xs, b=0, timeout=60)
        assert svc.metrics_snapshot().get(
            "edge_connection_errors_total", 0) > before
    finally:
        server.close()
        svc.close()


def test_tls_client_cert_pinning_for_router_links(dcf, bundles, rng):
    """``tls_client_ca`` pins the router<->shard link: a TLS client
    WITHOUT the pinned cert fails the handshake typed; one presenting
    the CA-signed client cert serves."""
    from dcf_tpu.errors import BackendUnavailableError

    svc, server = started_edge(
        dcf, bundles, tls_cert=str(TLS_DIR / "server.pem"),
        tls_key=str(TLS_DIR / "server.key"),
        tls_client_ca=str(TLS_DIR / "ca.pem"))
    try:
        xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
        with pytest.raises((BackendUnavailableError, OSError)):
            c = EdgeClient(*server.address, n_bytes=NB, tls=True,
                           tls_ca=str(TLS_DIR / "ca.pem"))
            try:
                c.evaluate("edge-a", xs, b=0, timeout=10)
            finally:
                c.close()
        with EdgeClient(*server.address, n_bytes=NB, tls=True,
                        tls_ca=str(TLS_DIR / "ca.pem"),
                        tls_cert=str(TLS_DIR / "client.pem"),
                        tls_key=str(TLS_DIR / "client.key")) as c:
            y = c.evaluate("edge-a", xs, b=0, timeout=60)
            assert y.shape == (1, 3, LAM)
    finally:
        server.close()
        svc.close()


def test_tls_config_validation():
    with pytest.raises(ValueError, match="BOTH"):
        ServeConfig(tls_cert="cert.pem")
    with pytest.raises(ValueError, match="BOTH"):
        ServeConfig(tls_key="key.pem")
    with pytest.raises(ValueError, match="tls_client_ca"):
        ServeConfig(tls_client_ca="ca.pem")
    # The client validates its keypair BEFORE dialing anything.
    with pytest.raises(ValueError, match="BOTH"):
        EdgeClient("127.0.0.1", 1, n_bytes=2, tls=True,
                   tls_cert="c.pem")


def test_open_edge_honors_explicit_class_verbatim(dcf, bundles, rng,
                                                  monkeypatch):
    """ISSUE 13 review fix: the OPEN edge (no tenant table) must not
    clamp an explicit priority byte to the default tenant's NORMAL —
    that clamp silently demoted every router-forwarded CRITICAL
    request at its shard.  No table = no policy: the frame's class
    reaches the service verbatim (a CONFIGURED table still enforces
    the never-promote cap — pinned elsewhere)."""
    from dcf_tpu.serve import Priority

    svc, server = started_edge(dcf, bundles)
    seen = []
    real = svc.submit_bytes

    def spying(key_id, data, b=0, deadline_ms=None,
               priority=Priority.NORMAL):
        seen.append(priority)
        return real(key_id, data, b=b, deadline_ms=deadline_ms,
                    priority=priority)

    monkeypatch.setattr(svc, "submit_bytes", spying)
    try:
        xs = rng.integers(0, 256, (2, NB), dtype=np.uint8)
        with EdgeClient(*server.address, n_bytes=NB) as c:
            c.evaluate("edge-a", xs, priority="critical", timeout=60)
            c.evaluate("edge-a", xs, timeout=60)  # no byte: NORMAL
            c.evaluate("edge-a", xs, priority="batch", timeout=60)
        assert seen == [Priority.CRITICAL, Priority.NORMAL,
                        Priority.BATCH]
    finally:
        server.close()
        svc.close()
