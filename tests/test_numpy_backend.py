"""Parity: numpy keygen/eval/PRG vs the pure-Python spec model."""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.aes import aes256_encrypt_np, expand_key_np
from dcf_tpu.ops.prg import HirosePrgNp
from tests.vectors import ALPHAS, BETA, KEYS


def rand_bytes(rng: random.Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


def test_aes_np_matches_spec():
    rng = random.Random(11)
    key = rand_bytes(rng, 32)
    rk_np = expand_key_np(key)
    rk = spec.aes256_expand_key(key)
    blocks = np.random.default_rng(0).integers(0, 256, (33, 16), dtype=np.uint8)
    out = aes256_encrypt_np(rk_np, blocks)
    for i in range(blocks.shape[0]):
        assert out[i].tobytes() == spec.aes256_encrypt_block(rk, blocks[i].tobytes())


@pytest.mark.parametrize("lam,nkeys", [(16, 2), (32, 18), (144, 18)])
def test_prg_np_matches_spec(lam, nkeys):
    rng = random.Random(12)
    keys = [rand_bytes(rng, 32) for _ in range(nkeys)]
    prg_spec = spec.HirosePrgSpec(lam, keys)
    prg_np = HirosePrgNp(lam, keys)
    seeds = np.random.default_rng(1).integers(0, 256, (7, lam), dtype=np.uint8)
    out = prg_np.gen(seeds)
    for i in range(seeds.shape[0]):
        (s_l, v_l, t_l), (s_r, v_r, t_r) = prg_spec.gen(seeds[i].tobytes())
        assert out.s_l[i].tobytes() == s_l
        assert out.v_l[i].tobytes() == v_l
        assert out.s_r[i].tobytes() == s_r
        assert out.v_r[i].tobytes() == v_r
        assert bool(out.t_l[i]) == t_l
        assert bool(out.t_r[i]) == t_r


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_gen_batch_matches_spec(bound):
    rng = random.Random(13)
    prg_spec = spec.HirosePrgSpec(16, KEYS)
    prg_np = HirosePrgNp(16, KEYS)
    k_num, n_bytes, lam = 3, 2, 16
    nprng = np.random.default_rng(2)
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, lam), dtype=np.uint8)
    s0s = random_s0s(k_num, lam, nprng)
    bundle = gen_batch(prg_np, alphas, betas, s0s, bound)
    for i in range(k_num):
        share = spec.gen(
            prg_spec,
            spec.CmpFn(alphas[i].tobytes(), betas[i].tobytes()),
            [s0s[i, 0].tobytes(), s0s[i, 1].tobytes()],
            bound,
        )
        got = bundle.to_shares()[i]
        assert got.s0s == share.s0s
        assert got.cws == share.cws
        assert got.cw_np1 == share.cw_np1


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_eval_np_matches_spec_and_reconstructs(bound):
    rng = random.Random(14)
    prg_spec = spec.HirosePrgSpec(16, KEYS)
    prg_np = HirosePrgNp(16, KEYS)
    k_num, n_bytes, lam, m = 2, 2, 16, 9
    nprng = np.random.default_rng(3)
    alphas = nprng.integers(0, 256, (k_num, n_bytes), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k_num, lam), dtype=np.uint8)
    s0s = random_s0s(k_num, lam, nprng)
    bundle = gen_batch(prg_np, alphas, betas, s0s, bound)
    xs = nprng.integers(0, 256, (m, n_bytes), dtype=np.uint8)
    xs[0] = alphas[0]  # include the boundary point
    y0 = eval_batch_np(prg_np, 0, bundle.for_party(0), xs)
    y1 = eval_batch_np(prg_np, 1, bundle.for_party(1), xs)
    for i in range(k_num):
        k0 = bundle.to_shares()[i].for_party(0)
        for j in range(m):
            expect = spec.eval_point(prg_spec, False, k0, xs[j].tobytes())
            assert y0[i, j].tobytes() == expect
    # Reconstruction against the plain comparison function.
    recon = y0 ^ y1
    for i in range(k_num):
        a = alphas[i].tobytes()
        for j in range(m):
            x = xs[j].tobytes()
            lt = x < a if bound is spec.Bound.LT_BETA else x > a
            expect = betas[i].tobytes() if lt else bytes(lam)
            assert recon[i, j].tobytes() == expect


def test_eval_np_reference_vectors():
    # The reference's own end-to-end vectors through the numpy path.
    prg_np = HirosePrgNp(16, KEYS)
    nprng = np.random.default_rng(4)
    alphas = np.frombuffer(ALPHAS[2], dtype=np.uint8)[None, :]
    betas = np.frombuffer(BETA, dtype=np.uint8)[None, :]
    s0s = random_s0s(1, 16, nprng)
    bundle = gen_batch(prg_np, alphas, betas, s0s, spec.Bound.LT_BETA)
    xs = np.stack([np.frombuffer(a, dtype=np.uint8) for a in ALPHAS])
    y0 = eval_batch_np(prg_np, 0, bundle.for_party(0), xs)
    y1 = eval_batch_np(prg_np, 1, bundle.for_party(1), xs)
    recon = y0 ^ y1
    expect = [BETA, BETA, bytes(16), bytes(16), bytes(16)]
    assert [recon[0, j].tobytes() for j in range(5)] == expect


def test_keybundle_codec_roundtrip(tmp_path):
    prg_np = HirosePrgNp(16, KEYS)
    nprng = np.random.default_rng(5)
    alphas = nprng.integers(0, 256, (4, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (4, 16), dtype=np.uint8)
    bundle = gen_batch(prg_np, alphas, betas, random_s0s(4, 16, nprng), spec.Bound.LT_BETA)
    # flat binary
    rt = KeyBundle.from_bytes(bundle.to_bytes())
    for name in ("s0s", "cw_s", "cw_v", "cw_t", "cw_np1"):
        assert np.array_equal(getattr(rt, name), getattr(bundle, name))
    # file codecs
    for fname in ("b.dcfk", "b.npz"):
        p = str(tmp_path / fname)
        bundle.save(p)
        loaded = KeyBundle.load(p)
        assert np.array_equal(loaded.cw_s, bundle.cw_s)
    # corrupt magic
    with pytest.raises(ValueError):
        KeyBundle.from_bytes(b"XXXX" + bundle.to_bytes()[4:])
