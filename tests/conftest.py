"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding tests
can run without TPU hardware.  In this environment a sitecustomize module
imports jax at interpreter start with JAX_PLATFORMS=axon (the TPU tunnel), so
setting env vars here is too late for jax's config defaults — we override the
live config instead, before any backend initializes.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
