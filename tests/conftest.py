"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding tests
can run without TPU hardware.  In this environment a sitecustomize module
imports jax at interpreter start with JAX_PLATFORMS=axon (the TPU tunnel), so
setting env vars here is too late for jax's config defaults — we override the
live config instead, before any backend initializes.

``DCF_TPU_TESTS=1`` flips the suite onto the real accelerator instead: use
it with ``-m tpu`` to run the on-hardware lane (tests/test_tpu.py), which
exercises the COMPILED Mosaic kernels — the code the headline numbers come
from — rather than the interpreter graphs the CPU lane checks.
"""

import os

ON_TPU_LANE = os.environ.get("DCF_TPU_TESTS") == "1"

if not ON_TPU_LANE:
    from dcf_tpu.utils.provision import force_cpu_devices

    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        force_cpu_devices(os.environ, 8)
    else:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

# Persistent compile cache (both lanes): the CPU lane's interpret-mode
# Pallas graphs cost minutes of XLA compile per run; caching them cuts
# repeat suite runs by ~15-20 min on this host.  Machine-local by design
# (.jax_cache/ is gitignored) — see provision.enable_compile_cache.
from dcf_tpu.utils.provision import enable_compile_cache  # noqa: E402

enable_compile_cache()


# --------------------------------------------------------------- lockwatch

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _lockwatch_armed(request):
    """Arm the TSan-lite lock-order watchdog for tests carrying the
    ``lockwatch`` marker (ISSUE 17).  Arming patches the
    ``threading.Lock``/``RLock`` factories, so every lock the test (and
    the system it constructs) creates is order-checked: an inversion
    raises ``LockOrderError`` with the offending cycle and stacks
    instead of deadlocking under the right interleave.  The patch is
    process-global — the marker rides the SERIAL CI legs (chaos/serve
    soaks), never a parallel runner."""
    if request.node.get_closest_marker("lockwatch") is None:
        yield None
        return
    from dcf_tpu.testing import lockwatch

    watch = lockwatch.arm()
    try:
        yield watch
    finally:
        lockwatch.disarm(watch)
