"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding tests
can run without TPU hardware.  This must happen before the first `import jax`
anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
