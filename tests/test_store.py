"""Durable key store, warm restart, and hung-batch watchdog (ISSUE 8).

Three clusters, all deterministic (fake clock + ``pump()``, injected
fault seams, tmp-dir stores):

* **Store mechanics** — DCFK v2/v3 frames published write-fsync-rename
  under a CRC'd manifest: roundtrips bit-exact for plain AND protocol
  bundles, files ``0o600``, crash-pre-rename keeps the old state
  (``store.write``/``store.manifest`` seams), a torn write made durable
  (``faults.torn_write``) quarantines typed, orphan sweep.
* **Warm restart** — the acceptance scenario: a service with durable
  keys is killed mid-stage, a fresh service restores from the store,
  every key comes back with its GENERATION preserved and zero
  re-keygen, and serves bit-exact two-party reconstructions vs the
  numpy oracle AND the C++ host core; a corrupt frame at restore time
  quarantines exactly that key and the rest still serve; post-restore
  hot-swaps mint generations past every restored one (no aliasing of
  pre-crash snapshots).
* **Hung-batch watchdog** — a wedged backend (latency fault past
  ``batch_timeout_s`` on the injectable clock) yields
  ``BatchTimeoutError`` + a breaker outcome against the dispatched
  family + a successful retry on the (demoted) family; and the
  dispatch-time deadline satellite: a request whose deadline passed
  while its batch sat in the dispatch-ahead slot fails
  ``DeadlineExceededError`` without burning an eval.
"""

import os
import warnings

import numpy as np
import pytest

import dcf_tpu.api as api
from dcf_tpu import Dcf
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.errors import (
    BatchTimeoutError,
    DeadlineExceededError,
    KeyQuarantinedError,
    ShapeError,
    StaleStateError,
)
from dcf_tpu.native import NativeDcf
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.protocols.oracle import mic_oracle
from dcf_tpu.serve import DcfService, ServeConfig
from dcf_tpu.serve.store import KeyStore, _frame_name
from dcf_tpu.testing import faults
from dcf_tpu.testing.faults import FakeClock

pytestmark = pytest.mark.durability

NB, LAM = 2, 16
MIC_INTERVALS = [(10, 200), (300, 1000), (60000, 2000)]


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0xD0_12AB)


@pytest.fixture(scope="module")
def ck(rng):
    return [rng.bytes(32), rng.bytes(32)]


@pytest.fixture(scope="module")
def dcf(ck):
    return Dcf(NB, LAM, ck, backend="bitsliced")


@pytest.fixture(scope="module")
def prg(ck):
    return HirosePrgNp(LAM, ck)


@pytest.fixture(scope="module")
def native(ck):
    return NativeDcf(LAM, ck)


def gen_one(dcf, rng):
    alphas = rng.integers(0, 256, (1, NB), dtype=np.uint8)
    betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
    return dcf.gen(alphas, betas, rng=rng)


def oracle(prg, bundle, b, xs):
    return eval_batch_np(prg, b, bundle.for_party(b), xs)


def corrupt_file(path, offset=40, xor=0xFF):
    data = bytearray(open(path, "rb").read())
    data[offset] ^= xor
    fd = os.open(path, os.O_WRONLY)
    try:
        os.write(fd, bytes(data))
    finally:
        os.close(fd)


# ------------------------------------------------------- store mechanics


def test_store_roundtrip_plain_and_protocol(dcf, rng, tmp_path):
    """Both wire formats through the store, bit-exact, generations and
    proto flags preserved."""
    store = KeyStore(str(tmp_path))
    kb = gen_one(dcf, rng)
    betas = rng.integers(0, 256, (len(MIC_INTERVALS), LAM),
                         dtype=np.uint8)
    pb = dcf.mic(MIC_INTERVALS, betas, rng=rng)
    store.put("plain", kb, generation=3)
    store.put("proto", pb.keys, protocol=pb, generation=7)
    assert store.key_ids() == ["plain", "proto"]
    got_kb, got_proto, gen = store.load("plain")
    assert gen == 3 and got_proto is None
    assert np.array_equal(got_kb.s0s, kb.s0s)
    assert np.array_equal(got_kb.cw_np1, kb.cw_np1)
    got_kb2, got_pb, gen2 = store.load("proto")
    assert gen2 == 7 and got_pb is not None
    assert np.array_equal(got_pb.combine_masks, pb.combine_masks)
    assert np.array_equal(got_kb2.cw_s, pb.keys.cw_s)
    assert store.generation_of("proto") == 7


def test_store_files_are_0600(dcf, rng, tmp_path):
    store = KeyStore(str(tmp_path))
    store.put("k", gen_one(dcf, rng), generation=1)
    for f in os.listdir(tmp_path):
        mode = os.stat(tmp_path / f).st_mode & 0o777
        assert mode == 0o600, (f, oct(mode))


def test_store_put_validation(dcf, rng, tmp_path):
    store = KeyStore(str(tmp_path))
    kb = gen_one(dcf, rng)
    with pytest.raises(ShapeError, match="two-party"):
        store.put("half", kb.for_party(0))
    with pytest.raises(ValueError, match="non-empty"):
        store.put("", kb)
    betas = rng.integers(0, 256, (len(MIC_INTERVALS), LAM),
                         dtype=np.uint8)
    pb = dcf.mic(MIC_INTERVALS, betas, rng=rng)
    with pytest.raises(ShapeError, match="desync"):
        store.put("mismatch", kb, protocol=pb)
    with pytest.raises(ValueError, match="no durable frame"):
        store.load("nope")
    assert store.delete("nope") is False


def test_crash_before_rename_keeps_old_state(dcf, rng, tmp_path):
    """The atomic-publish discipline: a crash between fsync and rename
    (the ``store.write``/``store.manifest`` seams raising) leaves the
    previous frame AND the previous manifest fully intact."""
    store = KeyStore(str(tmp_path))
    old, new = gen_one(dcf, rng), gen_one(dcf, rng)
    store.put("k", old, generation=1)
    for seam in ("store.write", "store.manifest"):
        with pytest.raises(faults.InjectedFault):
            with faults.inject(seam):
                store.put("k", new, generation=2)
        kb, _, gen = store.load("k")
        assert gen == 1, seam
        assert np.array_equal(kb.s0s, old.s0s), seam
    # the interrupted publishes left debris the sweep removes
    assert store.sweep_orphans() >= 1
    assert store.key_ids() == ["k"]


def test_torn_write_quarantined_typed(dcf, rng, tmp_path):
    """A partial write made durable (truncated temp file, rename
    proceeds — what a power cut mid-flush leaves) dies typed at read
    time: ``KeyQuarantinedError``, file renamed aside, counter bumped,
    and the OTHER stored key untouched."""
    store = KeyStore(str(tmp_path))
    store.put("good", gen_one(dcf, rng), generation=1)
    with faults.inject("store.write", handler=faults.torn_write(25)):
        store.put("torn", gen_one(dcf, rng), generation=2)
    with pytest.raises(KeyQuarantinedError, match="torn"):
        store.load("torn")
    assert len(store.quarantined_files()) == 1
    assert store.key_ids() == ["good"]  # manifest entry dropped
    kb, _, gen = store.load("good")
    assert gen == 1
    snap = store._metrics.snapshot()
    assert snap["serve_store_quarantined_total"] == 1


def test_hot_swap_lands_in_new_file_no_gen_aliasing(dcf, rng, tmp_path):
    """A durable hot-swap writes a NEW generation-suffixed file and
    flips the manifest after — no crash window can pair new frame
    bytes with an old generation."""
    store = KeyStore(str(tmp_path))
    old, new = gen_one(dcf, rng), gen_one(dcf, rng)
    store.put("k", old, generation=1)
    f1 = _frame_name("k", 1)
    store.put("k", new, generation=2)
    f2 = _frame_name("k", 2)
    assert f1 != f2
    assert not (tmp_path / f1).exists()  # superseded frame removed
    kb, _, gen = store.load("k")
    assert gen == 2 and np.array_equal(kb.s0s, new.s0s)


def test_stale_put_cannot_roll_back_newer_generation(dcf, rng,
                                                     tmp_path):
    """Review regression: durable publishes are monotonic per key —
    two concurrent hot-swaps serialize on the store lock in arbitrary
    order, and the OLDER generation landing last must not roll the
    stored key back (a restart would silently restore superseded key
    material with regen_count == 0)."""
    store = KeyStore(str(tmp_path))
    old, new = gen_one(dcf, rng), gen_one(dcf, rng)
    store.put("k", new, generation=5)
    store.put("k", old, generation=4)  # the stale write-through: no-op
    kb, _, gen = store.load("k")
    assert gen == 5 and np.array_equal(kb.s0s, new.s0s)
    store.put("k", old, generation=6)  # a genuinely newer one still wins
    assert store.load("k")[2] == 6


def test_put_many_one_flip_and_per_key_monotonic(dcf, rng, tmp_path):
    """ISSUE 11 batched publish: N frames, ONE manifest flip; the
    per-key monotonic guard skips stale items without touching the
    rest of the batch; delete_many drops many entries in one flip."""
    store = KeyStore(str(tmp_path))
    store.put("b", gen_one(dcf, rng), generation=9)
    items = [(f"k{i}", gen_one(dcf, rng), None, i + 1)
             for i in range(4)]
    flips = []
    with faults.inject("store.manifest",
                       handler=lambda *a: flips.append(a)):
        assert store.put_many(items) == 4
    assert len(flips) == 1
    # stale item ("b" at gen 3 < stored 9) skipped, fresh one lands
    old_b = store.load("b")[0]
    assert store.put_many([("b", gen_one(dcf, rng), None, 3),
                           ("k9", gen_one(dcf, rng), None, 9)]) == 1
    assert store.load("b")[0].to_bytes() == old_b.to_bytes()
    assert store.load("b")[2] == 9
    flips.clear()
    with faults.inject("store.manifest",
                       handler=lambda *a: flips.append(a)):
        assert store.delete_many(["k0", "k1", "gone", "k0"]) == 2
    assert len(flips) == 1
    assert store.key_ids() == ["b", "k2", "k3", "k9"]
    with pytest.raises(ShapeError, match="two-party"):
        store.put_many([("p", gen_one(dcf, rng).for_party(0), None, 1)])


def test_put_many_crash_fuzz_never_tears_the_batch(dcf, rng, tmp_path):
    """The ISSUE 11 acceptance fuzz: kill a batched publish at EVERY
    frame write and at the manifest flip — after each kill the
    manifest is readable and consistent (the OLD state, exactly),
    every referenced frame loads, and the debris sweeps.  Then a torn
    frame write that survives to the flip quarantines exactly its own
    key at read time."""
    store = KeyStore(str(tmp_path))
    base = [(f"base{i}", gen_one(dcf, rng), None, i + 1)
            for i in range(2)]
    store.put_many(base)
    before = store.key_ids()
    batch = [(f"n{i}", gen_one(dcf, rng), None, 10 + i)
             for i in range(4)]
    for kill_at in range(1, 5):  # die on the kill_at-th frame write

        def kill_nth(*_a, n=[0], k=kill_at):
            n[0] += 1
            if n[0] == k:
                raise faults.InjectedFault(f"kill at frame {k}")

        with pytest.raises(faults.InjectedFault):
            with faults.inject("store.write", handler=kill_nth):
                store.put_many(batch)
        assert store.key_ids() == before, kill_at  # OLD state, whole
        for key_id in before:  # every referenced frame still loads
            store.load(key_id)
        # kill_at - 1 published frames + the killed write's temp file
        assert store.sweep_orphans() == kill_at
    # kill at the manifest flip: all frames written, still OLD state
    with pytest.raises(faults.InjectedFault):
        with faults.inject("store.manifest"):
            store.put_many(batch)
    assert store.key_ids() == before
    assert store.sweep_orphans() == 5  # 4 frames + the manifest tmp
    # a torn FRAME made durable: the flip lands, the torn key (and
    # only it) quarantines at read time
    torn = {"n": 0}

    def tear_second(_key_id, path):
        torn["n"] += 1
        if torn["n"] == 2:
            with open(path, "r+b") as fh:
                fh.truncate(30)

    with faults.inject("store.write", handler=tear_second):
        assert store.put_many(batch) == 4
    with pytest.raises(KeyQuarantinedError):
        store.load("n1")
    for key_id in ("n0", "n2", "n3"):
        store.load(key_id)
    assert store._metrics.snapshot()[
        "serve_store_quarantined_total"] == 1


def test_quarantine_survives_manifest_publish_failure(dcf, rng,
                                                      tmp_path):
    """Review regression: the quarantine path must never raise — if
    the manifest publish inside it dies (disk full, armed seam), the
    typed KeyQuarantinedError still reaches the caller instead of an
    untyped escape aborting restore for EVERY key."""
    store = KeyStore(str(tmp_path))
    store.put("bad", gen_one(dcf, rng), generation=1)
    store.put("good", gen_one(dcf, rng), generation=2)
    corrupt_file(tmp_path / _frame_name("bad", 1))
    with faults.inject("store.manifest"):
        with pytest.raises(KeyQuarantinedError):
            store.load("bad")
    # the stale manifest entry points at the renamed-away file; the
    # next read re-quarantines it typed (vanished-file path) and the
    # other key is untouched throughout
    with pytest.raises(KeyQuarantinedError, match="vanished"):
        store.load("bad")
    assert store.load("good")[2] == 2


def test_transient_read_errors_do_not_quarantine(dcf, rng, tmp_path,
                                                 monkeypatch):
    """Review regression: a transient OSError (fd pressure, EACCES)
    while reading a frame must PROPAGATE, not destroy a valid durable
    key via the quarantine rename — the condition clears on retry."""
    import builtins

    store = KeyStore(str(tmp_path))
    kb = gen_one(dcf, rng)
    store.put("k", kb, generation=1)
    real_open = builtins.open

    def flaky_open(path, *a, **kw):
        if str(path).endswith(".dcfk"):
            raise OSError(24, "Too many open files")
        return real_open(path, *a, **kw)

    monkeypatch.setattr(builtins, "open", flaky_open)
    with pytest.raises(OSError, match="Too many open files"):
        store.load("k")
    monkeypatch.setattr(builtins, "open", real_open)
    # nothing was quarantined; the key still loads once pressure clears
    assert store.quarantined_files() == []
    kb2, _, gen = store.load("k")
    assert gen == 1 and np.array_equal(kb2.s0s, kb.s0s)


# --------------------------------------------------- service write-through


def make_service(dcf, clock=None, **knobs):
    knobs.setdefault("max_batch", 32)
    kwargs = {} if clock is None else {"clock": clock}
    return DcfService(dcf, ServeConfig(**knobs), **kwargs)


def test_register_durable_writes_through_before_ack(dcf, rng, tmp_path):
    svc = make_service(dcf, store_dir=str(tmp_path))
    kb = gen_one(dcf, rng)
    svc.register_key("k", kb, durable=True)
    # acked => already on disk, under the registry's generation
    kb2, _, gen = svc.store.load("k")
    assert gen == svc.registry.snapshot("k")[2]
    assert np.array_equal(kb2.s0s, kb.s0s)
    # non-durable registration persists nothing
    svc.register_key("volatile", gen_one(dcf, rng))
    assert svc.store.key_ids() == ["k"]
    # unregister forgets the durable frame too
    svc.unregister_key("k")
    assert svc.store.key_ids() == []


def test_register_durable_without_store_fails_loudly(dcf, rng):
    svc = make_service(dcf)
    with pytest.raises(ValueError, match="store_dir"):
        svc.register_key("k", gen_one(dcf, rng), durable=True)
    with pytest.raises(ValueError, match="store_dir"):
        svc.restore_keys()


def test_fresh_process_durable_register_without_restore(dcf, rng,
                                                        tmp_path):
    """Review regression: a fresh process on an EXISTING store that
    registers durably before (or without) restoring must not mint a
    generation the manifest already records — the store's monotonic
    guard would silently drop the write-through, un-acking an acked
    durable registration.  The service floors its registry counter on
    the store's max generation at construction."""
    svc = make_service(dcf, store_dir=str(tmp_path))
    svc.register_key("a", gen_one(dcf, rng), durable=True)
    svc.register_key("a", gen_one(dcf, rng), durable=True)  # gen 2
    svc.register_key("b", gen_one(dcf, rng), durable=True)  # gen 3
    del svc

    svc2 = make_service(dcf, store_dir=str(tmp_path))  # NO restore_keys
    fresh = gen_one(dcf, rng)
    svc2.register_key("a", fresh, durable=True)  # must actually persist
    kb, _, gen = svc2.store.load("a")
    assert gen > 3  # past everything the manifest recorded
    assert np.array_equal(kb.s0s, fresh.s0s)  # the new bundle, on disk
    # and a later restart restores the fresh registration, not a
    # silently-kept stale one
    svc3 = make_service(dcf, store_dir=str(tmp_path))
    report = svc3.restore_keys()
    assert report.restored["a"] == gen
    kb3 = svc3.registry.snapshot("a")[0]
    assert np.array_equal(kb3.s0s, fresh.s0s)


def test_restore_quarantines_party_restricted_frame_for_real(
        dcf, rng, tmp_path):
    """Review regression: the defense-in-depth party check at restore
    must route through the REAL quarantine (file renamed aside,
    manifest entry dropped, counter bumped) — a lingering manifest
    entry would make every later restore re-report the key forever."""
    store = KeyStore(str(tmp_path))
    store.put("good", gen_one(dcf, rng), generation=1)
    # hand-craft the damage put() refuses: a 1-party frame with a
    # manifest entry claiming parties=1 (so the codec-level mismatch
    # check cannot see it)
    half = gen_one(dcf, rng).for_party(0)
    with store._lock:
        entries = store._read_manifest()
        fname = _frame_name("half", 9)
        store._publish(fname, half.to_bytes(), "store.write", "half")
        entries["half"] = {"file": fname, "generation": 9,
                          "proto": False, "parties": 1}
        store._write_manifest(entries)

    svc = make_service(dcf, store_dir=str(tmp_path))
    report = svc.restore_keys()
    assert sorted(report.restored) == ["good"]
    assert "party-restricted" in report.quarantined["half"]
    # REALLY quarantined: entry gone, file set aside, counter bumped
    assert svc.store.key_ids() == ["good"]
    assert len(svc.store.quarantined_files()) == 1
    assert svc.metrics_snapshot()["serve_store_quarantined_total"] == 1
    # a second restore is clean — nothing re-reports forever
    report2 = svc.restore_keys()
    assert report2.quarantined == {}
    # and the floor covered the doctored gen 9: a new durable register
    # for the same name persists instead of being silently dropped
    svc.register_key("half", gen_one(dcf, rng), durable=True)
    assert svc.store.load("half")[2] > 9


# ----------------------------------------------------------- warm restart


def test_crash_restart_bit_exact_zero_regen(dcf, prg, native, rng,
                                            tmp_path):
    """THE acceptance scenario, deterministic on the fake clock: a
    service with durable keys (plain + protocol) killed mid-stage, a
    fresh service restores — every key back at its pre-crash
    generation, zero re-keygen, quarantine empty — and serves
    bit-exact two-party reconstructions vs the numpy oracle AND the
    C++ host core."""
    clock = FakeClock()
    svc = make_service(dcf, clock, store_dir=str(tmp_path), retries=0)
    plain = {f"key-{i}": gen_one(dcf, rng) for i in range(3)}
    for name, kb in plain.items():
        svc.register_key(name, kb, durable=True)
    betas = rng.integers(0, 256, (len(MIC_INTERVALS), LAM),
                         dtype=np.uint8)
    pb = dcf.mic(MIC_INTERVALS, betas, rng=rng)
    svc.register_key("mic-0", pb, durable=True)
    gens_pre = {k: svc.registry.snapshot(k)[2]
                for k in (*plain, "mic-0")}
    xs = rng.integers(0, 256, (9, NB), dtype=np.uint8)
    # the mid-stage kill: staging dies, the service is abandoned undrained
    with faults.inject("serve.stage"):
        doomed = svc.submit("key-0", xs)
        svc.pump()
    with pytest.raises(faults.InjectedFault):
        doomed.result(1)
    svc.queue.close()  # the crash: no drain, no clean unregister
    del svc

    svc2 = make_service(dcf, FakeClock(), store_dir=str(tmp_path))
    report = svc2.restore_keys()
    assert sorted(report.restored) == sorted(gens_pre)  # zero re-keygen
    assert report.quarantined == {}
    assert report.restored == gens_pre  # generations preserved exactly
    snap = svc2.metrics_snapshot()
    assert snap["serve_store_restored_total"] == len(gens_pre)
    # plain keys: both parties, vs numpy oracle AND the C++ core
    for name, kb in plain.items():
        f0 = svc2.submit(name, xs, b=0)
        f1 = svc2.submit(name, xs, b=1)
        svc2.pump()
        y = f0.result(1) ^ f1.result(1)
        assert np.array_equal(
            y, oracle(prg, kb, 0, xs) ^ oracle(prg, kb, 1, xs)), name
        assert np.array_equal(
            y, native.eval(0, kb, xs) ^ native.eval(1, kb, xs)), name
    # the protocol key: combined per-interval rows vs the MIC oracle
    f0 = svc2.submit("mic-0", xs, b=0)
    f1 = svc2.submit("mic-0", xs, b=1)
    svc2.pump()
    assert np.array_equal(f0.result(1) ^ f1.result(1),
                          mic_oracle(xs, MIC_INTERVALS, betas))


def test_restore_quarantines_only_the_damaged_frame(dcf, prg, rng,
                                                    tmp_path):
    """The corrupt-store acceptance clause: restore quarantines exactly
    the damaged frames typed and serves the rest."""
    svc = make_service(dcf, store_dir=str(tmp_path))
    bundles = {f"key-{i}": gen_one(dcf, rng) for i in range(3)}
    for name, kb in bundles.items():
        svc.register_key(name, kb, durable=True)
    gens = {k: svc.registry.snapshot(k)[2] for k in bundles}
    del svc
    corrupt_file(tmp_path / _frame_name("key-1", gens["key-1"]))

    svc2 = make_service(dcf, store_dir=str(tmp_path))
    report = svc2.restore_keys()
    assert sorted(report.restored) == ["key-0", "key-2"]
    assert sorted(report.quarantined) == ["key-1"]
    assert "quarantined" in report.quarantined["key-1"]
    snap = svc2.metrics_snapshot()
    assert snap["serve_store_quarantined_total"] == 1
    assert len(svc2.store.quarantined_files()) == 1
    xs = rng.integers(0, 256, (5, NB), dtype=np.uint8)
    for name in ("key-0", "key-2"):  # the rest still serve, bit-exact
        fut = svc2.submit(name, xs)
        svc2.pump()
        assert np.array_equal(fut.result(1),
                              oracle(prg, bundles[name], 0, xs)), name
    with pytest.raises(ValueError, match="no bundle registered"):
        svc2.submit("key-1", xs)


def test_restore_preserves_generations_no_aliasing(dcf, rng, tmp_path):
    """The PR 5 guard across process death: restored keys keep their
    generations, and a post-restore hot-swap mints one strictly past
    every restored generation — a pre-crash snapshot can never alias
    post-restore key content."""
    svc = make_service(dcf, store_dir=str(tmp_path))
    kb1, kb2 = gen_one(dcf, rng), gen_one(dcf, rng)
    svc.register_key("a", kb1, durable=True)
    svc.register_key("b", gen_one(dcf, rng), durable=True)
    svc.register_key("a", kb2, durable=True)  # durable hot-swap: gen 3
    gen_a = svc.registry.snapshot("a")[2]
    assert gen_a == 3
    del svc

    svc2 = make_service(dcf, store_dir=str(tmp_path))
    report = svc2.restore_keys()
    assert report.restored == {"a": 3, "b": 2}
    # the restored content is the hot-swapped bundle, not the original
    kb, _, _ = svc2.store.load("a")
    assert np.array_equal(kb.s0s, kb2.s0s)
    # a new register can never reuse a restored generation
    gen_new = svc2.registry.register("c", gen_one(dcf, rng))
    assert gen_new > 3
    # and the in-flight staleness guard still bites across a hot-swap
    snap_gen = svc2.registry.snapshot("a")[2]
    svc2.register_key("a", gen_one(dcf, rng))
    with pytest.raises(StaleStateError):
        svc2.registry.resident("a", 0, snap_gen)


# ------------------------------------------------- hung-batch watchdog


def test_watchdog_times_out_wedged_batch_typed(dcf, rng):
    """retries=0: a dispatch that eats the clock past batch_timeout_s
    fails the future with BatchTimeoutError and records a breaker
    failure against the dispatched family."""
    clock = FakeClock()
    svc = make_service(dcf, clock, batch_timeout_s=1.0, retries=0)
    svc.register_key("k", gen_one(dcf, rng))
    xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
    with faults.inject("serve.eval", handler=faults.latency(clock, 5.0)):
        fut = svc.submit("k", xs)
        svc.pump()
    with pytest.raises(BatchTimeoutError, match="wall deadline"):
        fut.result(1)
    snap = svc.metrics_snapshot()
    assert snap["serve_batch_timeouts_total"] == 1
    assert snap["serve_batch_failures_total"] == 1
    fam = dcf.backend_name
    assert svc.breakers._breakers[("k", fam)].failures == 1


def test_watchdog_retry_serves_after_timeout(dcf, prg, rng):
    """retries=1: the timed-out batch takes the shared retry/
    invalidation path and the retry (backend healthy again) serves
    bit-exactly."""
    clock = FakeClock()
    svc = make_service(dcf, clock, batch_timeout_s=1.0, retries=1)
    kb = gen_one(dcf, rng)
    svc.register_key("k", kb)
    calls = {"n": 0}

    def slow_once(*_args):
        calls["n"] += 1
        if calls["n"] == 1:
            clock.advance(5.0)  # only the first dispatch wedges

    xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
    with faults.inject("serve.eval", handler=slow_once):
        fut = svc.submit("k", xs)
        svc.pump()
    assert np.array_equal(fut.result(1), oracle(prg, kb, 0, xs))
    snap = svc.metrics_snapshot()
    assert snap["serve_batch_timeouts_total"] == 1
    assert snap["serve_retries_total"] == 1
    assert calls["n"] == 2  # timeout + the successful retry


def test_watchdog_demotes_auto_facade_retry_on_new_family(ck, prg, rng,
                                                          monkeypatch):
    """The acceptance walk: a wedged pallas backend times out typed,
    the final-retry reset_backend_health demotes the auto facade, and
    the retry succeeds on the demoted family — a backend that hangs
    degrades exactly like one that crashes."""
    monkeypatch.setattr(api, "_default_backend", lambda lam: "pallas")
    api.reset_backend_health()
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            dcf_auto = Dcf(NB, LAM, ck, backend="auto",
                           backend_opts={"interpret": True})
        assert dcf_auto.backend_name == "pallas"
        clock = FakeClock()
        svc = make_service(dcf_auto, clock, batch_timeout_s=1.0,
                           retries=1)
        kb = gen_one(dcf_auto, rng)
        svc.register_key("k", kb)
        wedged = {"n": 0}
        lowers = {"n": 0}

        def wedge_pallas(*_args):
            # the pallas instance is wedged; the demoted family is not
            if wedged["n"] == 0:
                wedged["n"] += 1
                clock.advance(5.0)

        def canary_dies(*_args):
            # fire 1 = the wedged dispatch itself (let it run — the
            # WATCHDOG must be what fails it); fire 2 = the post-reset
            # canary re-probing the wedged backend, which dies like a
            # wedged backend's canary would — that is the demotion.
            lowers["n"] += 1
            if lowers["n"] >= 2:
                raise faults.InjectedFault("wedged backend canary")

        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        with faults.inject("serve.eval", handler=wedge_pallas), \
                faults.inject("pallas.lowering", handler=canary_dies):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                fut = svc.submit("k", xs)
                svc.pump()
                y = fut.result(1)
        assert wedged["n"] >= 1
        assert dcf_auto.backend_name == "bitsliced"  # demoted
        assert np.array_equal(y, oracle(prg, kb, 0, xs))
        snap = svc.metrics_snapshot()
        assert snap["serve_batch_timeouts_total"] >= 1
        assert snap["serve_retries_total"] >= 1
    finally:
        api.reset_backend_health()


def test_watchdog_disabled_by_default(dcf, prg, rng):
    """batch_timeout_s=0 (the default): a slow batch still serves —
    PR 6 semantics exactly."""
    clock = FakeClock()
    svc = make_service(dcf, clock)
    kb = gen_one(dcf, rng)
    svc.register_key("k", kb)
    xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
    with faults.inject("serve.eval",
                       handler=faults.latency(clock, 3600.0)):
        fut = svc.submit("k", xs)
        svc.pump()
    assert np.array_equal(fut.result(1), oracle(prg, kb, 0, xs))
    assert svc.metrics_snapshot()["serve_batch_timeouts_total"] == 0


def test_config_rejects_negative_batch_timeout():
    with pytest.raises(ValueError, match="batch_timeout_s"):
        ServeConfig(batch_timeout_s=-1.0)


# ------------------------------------- deadline expiry at dispatch time


def test_deadline_expiry_in_dispatch_ahead_slot(dcf, prg, rng):
    """The satellite regression: batch formation took the request while
    its deadline was live, but the deadline passes while its later
    plans wait in the dispatch-ahead slot behind a slow eval — those
    plans must never dispatch (no evals burnt on a share the caller
    already abandoned) and the request fails DeadlineExceededError."""
    clock = FakeClock()
    svc = make_service(dcf, clock, max_batch=4, retries=0)
    kb = gen_one(dcf, rng)
    svc.register_key("k", kb)
    fires = {"n": 0}

    def slow_each(*_args):
        fires["n"] += 1
        clock.advance(1.0)  # each dispatched eval costs a second

    # Control: an oversized live request runs all three of its plans.
    xs = rng.integers(0, 256, (12, NB), dtype=np.uint8)
    f_live = svc.submit("k", xs)
    with faults.inject("serve.eval", handler=slow_each):
        svc.pump()
    assert fires["n"] == 3
    assert np.array_equal(f_live.result(1), oracle(prg, kb, 0, xs))

    # The regression: same shape, 100ms deadline — live at formation
    # AND at the first dispatch, expired by the time plans 2 and 3
    # reach the dispatch-ahead slot.
    fires["n"] = 0
    f_dead = svc.submit("k", xs, deadline_ms=100.0)
    with faults.inject("serve.eval", handler=slow_each):
        svc.pump()
    with pytest.raises(DeadlineExceededError, match="dispatch-ahead"):
        f_dead.result(1)
    assert fires["n"] == 1  # plans 2 and 3 were skipped, not evaluated
    snap = svc.metrics_snapshot()
    assert snap["serve_deadline_expired_total"] == 1


def test_deadline_still_enforced_at_formation(dcf, rng):
    """The PR 4 path rides along: queue-time expiry is unchanged."""
    clock = FakeClock()
    svc = make_service(dcf, clock)
    svc.register_key("k", gen_one(dcf, rng))
    fut = svc.submit("k", rng.integers(0, 256, (3, NB), dtype=np.uint8),
                     deadline_ms=10.0)
    clock.advance(0.05)
    svc.pump()
    with pytest.raises(DeadlineExceededError):
        fut.result(1)
