"""dcf_tpu.serve: the online evaluation service.

Covers the acceptance contract end to end — bit-exact parity vs the
numpy/spec oracle for every request of a mixed workload (3 registered
bundles incl. a multi-key one, ragged request sizes, both parties,
reconstruction checked), including under injected ``serve.eval`` faults
with retries — plus each serving mechanism in isolation: admission
shedding, deadline expiry (fake clock), LRU residency eviction under a
device-bytes budget, re-registration staleness eviction, graceful vs
hard shutdown, the worker thread, metrics snapshot shape, and the
``pallas.lowering`` mid-serve backend-fallback regression (satellite:
``Dcf.reset_backend_health`` and the serve cache share one invalidation
path).
"""

import warnings

import numpy as np
import pytest

import dcf_tpu.api as api
from dcf_tpu import Dcf
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.errors import (
    BackendUnavailableError,
    DeadlineExceededError,
    QueueFullError,
    ShapeError,
)
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.serve import DcfService, ServeConfig
from dcf_tpu.serve.registry import device_image_bytes
from dcf_tpu.testing import faults
from dcf_tpu.testing.faults import FakeClock

pytestmark = pytest.mark.serve

NB, LAM = 2, 16


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0x5E12)


@pytest.fixture(scope="module")
def ck(rng):
    return [rng.bytes(32), rng.bytes(32)]


@pytest.fixture(scope="module")
def dcf(ck):
    return Dcf(NB, LAM, ck, backend="bitsliced")


@pytest.fixture(scope="module")
def prg(ck):
    return HirosePrgNp(LAM, ck)


@pytest.fixture(scope="module")
def bundles(dcf, rng):
    """Three named bundles; 'multi' holds K=2 keys."""
    out = {}
    for name, k in (("relu-a", 1), ("relu-b", 1), ("multi", 2)):
        alphas = rng.integers(0, 256, (k, NB), dtype=np.uint8)
        betas = rng.integers(0, 256, (k, LAM), dtype=np.uint8)
        out[name] = dcf.gen(alphas, betas, rng=rng)
    return out


def make_service(dcf, bundles, **knobs):
    knobs.setdefault("max_batch", 32)
    svc = dcf.serve(**knobs)
    for name, bundle in bundles.items():
        svc.register_key(name, bundle)
    return svc


def oracle(prg, bundle, b, xs):
    return eval_batch_np(prg, b, bundle.for_party(b), xs)


# ------------------------------------------------------------ acceptance


def test_mixed_workload_bit_exact_vs_oracle(dcf, bundles, prg, rng):
    """The acceptance workload: >= 3 bundles, ragged sizes, both
    parties, every request's reconstruction bit-exact vs the oracle —
    WITH a serve.eval fault injected mid-run and retried."""
    svc = make_service(dcf, bundles, retries=1)
    names = list(bundles)
    reqs = []
    for i in range(14):
        name = names[i % len(names)]
        m = int(rng.integers(1, 11)) if i != 5 else 1  # single-point too
        xs = rng.integers(0, 256, (m, NB), dtype=np.uint8)
        reqs.append((name, xs))
    calls = {"n": 0}

    def fail_first(*_args):
        calls["n"] += 1
        if calls["n"] == 1:
            raise faults.InjectedFault("injected mid-batch eval failure")

    with faults.inject("serve.eval", handler=fail_first):
        futs = [(svc.submit(name, xs, b=0), svc.submit(name, xs, b=1))
                for name, xs in reqs]
        svc.pump()
    assert calls["n"] >= 2  # the fault fired and the retry re-dispatched
    snap = svc.metrics_snapshot()
    assert snap["serve_retries_total"] >= 1
    for (name, xs), (f0, f1) in zip(reqs, futs):
        y0, y1 = f0.result(1), f1.result(1)
        want = oracle(prg, bundles[name], 0, xs) ^ \
            oracle(prg, bundles[name], 1, xs)
        assert np.array_equal(y0 ^ y1, want), name
    assert snap["serve_queue_depth"] == 0
    assert snap["serve_batches_total"] >= 1


def test_oversized_request_spans_batches(dcf, bundles, prg, rng):
    """A request bigger than max_batch splits, scatters back in order."""
    svc = make_service(dcf, bundles, max_batch=32)
    xs = rng.integers(0, 256, (70, NB), dtype=np.uint8)
    fut = svc.submit("relu-a", xs)
    svc.pump()
    y0 = fut.result(1)
    assert y0.shape == (1, 70, LAM)
    assert np.array_equal(y0, oracle(prg, bundles["relu-a"], 0, xs))


def test_worker_thread_end_to_end(dcf, bundles, prg, rng):
    svc = make_service(dcf, bundles, max_delay_ms=1.0)
    xs = rng.integers(0, 256, (6, NB), dtype=np.uint8)
    with svc:
        y0 = svc.evaluate("relu-b", xs, b=0, timeout=60)
        y1 = svc.evaluate("relu-b", xs, b=1, timeout=60)
    want = oracle(prg, bundles["relu-b"], 0, xs) ^ \
        oracle(prg, bundles["relu-b"], 1, xs)
    assert np.array_equal(y0 ^ y1, want)
    with pytest.raises(QueueFullError):  # context exit closed admission
        svc.submit("relu-b", xs)


def test_host_path_numpy_backend(ck, bundles, prg, rng):
    """The no-device path: a numpy-backed service still serves batches
    (through the facade's host dispatch) bit-exactly."""
    dcf_np = Dcf(NB, LAM, ck, backend="numpy")
    svc = make_service(dcf_np, bundles)
    xs = rng.integers(0, 256, (5, NB), dtype=np.uint8)
    fut = svc.submit("multi", xs, b=1)
    svc.pump()
    assert np.array_equal(fut.result(1),
                          oracle(prg, bundles["multi"], 1, xs))


# ------------------------------------------------------ admission control


def test_queue_full_sheds(dcf, bundles, rng):
    svc = make_service(dcf, bundles, max_queued_points=8)
    xs = rng.integers(0, 256, (5, NB), dtype=np.uint8)
    svc.submit("relu-a", xs)
    with pytest.raises(QueueFullError):
        svc.submit("relu-a", xs)  # 5 + 5 > 8
    # A request bigger than the bound OUTRIGHT can never be admitted:
    # that is a size-contract ShapeError, not a retriable QueueFull.
    with pytest.raises(ShapeError, match="split the request"):
        svc.submit("relu-a", rng.integers(0, 256, (9, NB),
                                          dtype=np.uint8))
    snap = svc.metrics_snapshot()
    assert snap["serve_shed_total"] == 1
    assert snap["serve_queue_points"] == 5
    svc.pump()  # leave nothing queued for later tests


def test_take_group_fifo_no_queue_jumping():
    """Once a same-group request does not fit, the group closes: a
    later-submitted smaller request must not be served ahead of it."""
    from dcf_tpu.serve.admission import AdmissionQueue, Request

    q = AdmissionQueue(100_000)

    def mk(m):
        return Request("k", 0, np.zeros((m, NB), dtype=np.uint8),
                       None, 0.0)

    a, b, c = mk(3000), mk(2000), mk(1000)
    for r in (a, b, c):
        q.put(r)
    assert q.take_group(4096) == [a]  # b does not fit -> c may not jump
    assert q.take_group(4096) == [b, c]


def test_shed_counter_covers_shutdown_rejections(dcf, bundles, rng):
    """QueueFullError from a closed queue counts in serve_shed_total so
    the snapshot agrees with loadgen's requests_shed."""
    svc = make_service(dcf, bundles)
    svc.close(drain=True)
    with pytest.raises(QueueFullError):
        svc.submit("relu-a", np.zeros((1, NB), dtype=np.uint8))
    assert svc.metrics_snapshot()["serve_shed_total"] == 1


def test_submit_validation(dcf, bundles, rng):
    svc = make_service(dcf, bundles)
    with pytest.raises(ValueError, match="no bundle registered"):
        svc.submit("nope", np.zeros((1, NB), dtype=np.uint8))
    with pytest.raises(ShapeError):
        svc.submit("relu-a", np.zeros((1, NB + 1), dtype=np.uint8))
    with pytest.raises(ShapeError):
        svc.submit("relu-a", np.zeros((0, NB), dtype=np.uint8))
    with pytest.raises(ValueError, match="party"):
        svc.submit("relu-a", np.zeros((1, NB), dtype=np.uint8), b=2)


def test_deadline_expiry_fake_clock(dcf, bundles, rng):
    clock = FakeClock()
    svc = DcfService(dcf, ServeConfig(max_batch=32), clock=clock)
    svc.register_key("relu-a", bundles["relu-a"])
    xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
    f_dead = svc.submit("relu-a", xs, deadline_ms=10.0)
    f_live = svc.submit("relu-a", xs, deadline_ms=10_000.0)
    clock.advance(0.05)  # 50ms > 10ms deadline
    svc.pump()
    with pytest.raises(DeadlineExceededError):
        f_dead.result(1)
    assert f_live.result(1).shape == (1, 3, LAM)
    assert svc.metrics_snapshot()["serve_deadline_expired_total"] == 1


def test_close_drain_serves_queued(dcf, bundles, prg, rng):
    svc = make_service(dcf, bundles)
    xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
    fut = svc.submit("relu-a", xs)
    svc.close(drain=True)  # no worker ever started: drains inline
    assert np.array_equal(fut.result(1),
                          oracle(prg, bundles["relu-a"], 0, xs))
    with pytest.raises(QueueFullError):
        svc.submit("relu-a", xs)


def test_close_without_drain_fails_queued(dcf, bundles, rng):
    svc = make_service(dcf, bundles)
    fut = svc.submit("relu-a", rng.integers(0, 256, (4, NB),
                                            dtype=np.uint8))
    svc.close(drain=False)
    with pytest.raises(BackendUnavailableError):
        fut.result(1)


def test_close_no_drain_during_inflight_sync_retry(dcf, bundles, rng):
    """ISSUE 6 regression: ``close(drain=False)`` while the worker is
    MID ``_retry_sync`` must resolve every pending future typed and
    promptly — queued requests with ``BackendUnavailableError`` the
    moment admission closes (not after the retry unblocks), the
    in-flight group with the retry's final error once its bounded loop
    ends — and the close itself must not hang (the join is bounded by
    the retry budget)."""
    import threading

    in_retry = threading.Event()
    release = threading.Event()
    fires = {"n": 0}

    def handler(*_args):
        fires["n"] += 1
        if fires["n"] == 1:  # dispatch attempt of the in-flight group
            raise BackendUnavailableError("injected: dispatch dies")
        in_retry.set()  # the sync retry is now in flight...
        assert release.wait(60), "close() never released the retry"
        raise BackendUnavailableError("injected: retry dies too")

    svc = make_service(dcf, bundles, retries=1, breaker_failures=0,
                       max_delay_ms=0.0)
    xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
    with faults.inject("serve.eval", handler=handler):
        svc.start()
        f_inflight = svc.submit("relu-a", xs)
        assert in_retry.wait(60)  # worker holds the group, mid-retry
        f_queued = svc.submit("relu-b", xs)  # stays queued behind it
        closer = threading.Thread(
            target=lambda: svc.close(drain=False), daemon=True)
        closer.start()
        # The queued future resolves typed WHILE the retry is still
        # blocked — close must not gate fail_all on the worker join.
        with pytest.raises(BackendUnavailableError, match="closed"):
            f_queued.result(30)
        assert closer.is_alive()  # still joining the blocked worker
        release.set()
        closer.join(60)
        assert not closer.is_alive(), "close() hung on the worker join"
    with pytest.raises(BackendUnavailableError, match="retry dies"):
        f_inflight.result(30)


# ----------------------------------------------------- residency / cache


def test_lru_eviction_under_device_budget(dcf, bundles, rng):
    """Budget sized for ~2 images: serving 3 keys round-robin must evict
    LRU, and every result stays correct (re-staging is transparent)."""
    probe = make_service(dcf, bundles)
    xs = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    probe.submit("relu-a", xs)
    probe.pump()
    one = device_image_bytes(probe.registry.resident("relu-a", 0))
    assert one > 0
    svc = make_service(dcf, bundles, device_bytes_budget=int(2.5 * one))
    for name in ("relu-a", "relu-b", "relu-a", "relu-b", "relu-a"):
        fut = svc.submit(name, xs)
        svc.pump()
        fut.result(1)
    snap = svc.metrics_snapshot()
    assert snap["serve_resident_device_bytes"] <= int(2.5 * one)
    assert snap["serve_resident_images"] <= 2
    # 'multi' is colder and bigger (K=2): staging it evicts the LRU one
    fut = svc.submit("multi", xs)
    svc.pump()
    fut.result(1)
    assert svc.metrics_snapshot()["serve_evictions_total"] >= 1


def test_reregistration_evicts_stale_residency(dcf, bundles, prg, rng):
    """The staleness guard: hot-swapping a key id must evict the old
    device image — the next request serves the NEW function."""
    svc = make_service(dcf, bundles)
    xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
    fut = svc.submit("relu-a", xs)
    svc.pump()
    fut.result(1)
    assert svc.metrics_snapshot()["serve_resident_images"] >= 1
    alphas = rng.integers(0, 256, (1, NB), dtype=np.uint8)
    betas = rng.integers(0, 256, (1, LAM), dtype=np.uint8)
    fresh = dcf.gen(alphas, betas, rng=rng)
    svc.register_key("relu-a", fresh)
    assert svc.metrics_snapshot()["serve_evictions_total"] >= 1
    fut = svc.submit("relu-a", xs)
    svc.pump()
    assert np.array_equal(fut.result(1), oracle(prg, fresh, 0, xs))


def test_idempotent_reregistration_keeps_residency(dcf, bundles, rng):
    """Re-registering the SAME bundle object is a no-op: device images
    stay resident and nothing counts as an eviction."""
    svc = make_service(dcf, bundles)
    xs = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    fut = svc.submit("relu-a", xs)
    svc.pump()
    fut.result(1)
    before = svc.metrics_snapshot()
    svc.register_key("relu-a", bundles["relu-a"])
    after = svc.metrics_snapshot()
    assert after["serve_resident_images"] == before["serve_resident_images"]
    assert after["serve_evictions_total"] == before["serve_evictions_total"]


def test_unregister_between_submit_and_pump_fails_only_that_group(
        dcf, bundles, prg, rng):
    """The worker must outlive a key vanishing mid-queue: the stranded
    group's futures fail typed, other groups still serve."""
    svc = make_service(dcf, bundles)
    xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
    doomed = svc.submit("relu-b", xs)
    alive = svc.submit("relu-a", xs)
    svc.unregister_key("relu-b")
    svc.pump()
    with pytest.raises(ValueError, match="no bundle registered"):
        doomed.result(1)
    assert np.array_equal(alive.result(1),
                          oracle(prg, bundles["relu-a"], 0, xs))


def test_reset_backend_health_shares_invalidation_path(dcf, bundles, rng):
    """Both spellings of reset evict the serve registry's residencies."""
    svc = make_service(dcf, bundles)
    xs = rng.integers(0, 256, (2, NB), dtype=np.uint8)
    for entry, reset in ((0, dcf.reset_backend_health),
                         (1, api.reset_backend_health)):
        fut = svc.submit("relu-a", xs)
        svc.pump()
        fut.result(1)
        assert svc.metrics_snapshot()["serve_resident_images"] >= 1
        reset()
        assert svc.metrics_snapshot()["serve_resident_images"] == 0, entry


# ------------------------------------------------------- fault injection


def test_serve_stage_fault_exhausts_retries(dcf, bundles, rng):
    svc = make_service(dcf, bundles, retries=1)
    xs = rng.integers(0, 256, (3, NB), dtype=np.uint8)
    with faults.inject("serve.stage"):
        fut = svc.submit("relu-a", xs)
        svc.pump()
        with pytest.raises(faults.InjectedFault):
            fut.result(1)
    snap = svc.metrics_snapshot()
    assert snap["serve_batch_failures_total"] >= 1
    assert snap["serve_retries_total"] >= 1
    # the service survives: the next request serves normally
    fut = svc.submit("relu-a", xs)
    svc.pump()
    assert fut.result(1).shape == (1, 3, LAM)


def test_pallas_lowering_fallback_mid_serve(ck, bundles, prg, rng,
                                            monkeypatch):
    """The satellite regression: a pallas backend dying mid-serve (the
    ``pallas.lowering`` seam) must fall over to a healthy backend via
    the SHARED invalidation path — staged device state is evicted, the
    auto facade re-selects, and the retried requests reconstruct
    bit-exactly on the fallback backend."""
    monkeypatch.setattr(api, "_default_backend", lambda lam: "pallas")
    api.reset_backend_health()
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            dcf_auto = Dcf(NB, LAM, ck, backend="auto",
                           backend_opts={"interpret": True})
        assert dcf_auto.backend_name == "pallas"
        svc = make_service(dcf_auto, bundles, retries=1)
        xs = rng.integers(0, 256, (4, NB), dtype=np.uint8)
        fut = svc.submit("relu-a", xs)
        svc.pump()
        fut.result(1)  # serving on pallas (interpret)
        stagings_before = svc.metrics_snapshot()[
            "serve_key_stagings_total"]
        with faults.inject("pallas.lowering"):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                f0 = svc.submit("relu-a", xs, b=0)
                f1 = svc.submit("relu-a", xs, b=1)
                svc.pump()
                y0, y1 = f0.result(1), f1.result(1)
        assert dcf_auto.backend_name == "bitsliced"  # fell over
        want = oracle(prg, bundles["relu-a"], 0, xs) ^ \
            oracle(prg, bundles["relu-a"], 1, xs)
        assert np.array_equal(y0 ^ y1, want)
        snap = svc.metrics_snapshot()
        assert snap["serve_retries_total"] >= 1
        # the dead backend's staged image was evicted and re-staged on
        # the fallback — never served from the dead instance's cache
        assert snap["serve_key_stagings_total"] > stagings_before
    finally:
        api.reset_backend_health()


# --------------------------------------------------------- observability


def test_metrics_snapshot_is_deterministic_and_jsonable(dcf, bundles,
                                                        rng):
    import json

    svc = make_service(dcf, bundles)
    fut = svc.submit("relu-a", rng.integers(0, 256, (3, NB),
                                            dtype=np.uint8))
    svc.pump()
    fut.result(1)
    snap = svc.metrics_snapshot()
    assert list(snap) == sorted(snap)
    json.dumps(snap)  # JSON-basic values only
    for name in ("serve_requests_total", "serve_points_total",
                 "serve_batches_total", "serve_batch_occupancy_count",
                 "serve_stage_s_count", "serve_eval_s_count",
                 "serve_queue_wait_s_count", "serve_queue_depth",
                 "serve_resident_device_bytes", "serve_evictions_total",
                 "serve_shed_total", "serve_registered_keys"):
        assert name in snap, name
    assert snap["serve_requests_total"] == 1
    assert snap["serve_points_total"] == 3
    # occupancy of the one 3-point batch: 3/4 bucketed under 0.75
    assert snap["serve_batch_occupancy_count"] == 1


def test_unregister(dcf, bundles, rng):
    svc = make_service(dcf, bundles)
    assert svc.key_ids() == sorted(bundles)
    svc.unregister_key("multi")
    assert "multi" not in svc.key_ids()
    with pytest.raises(ValueError, match="no bundle registered"):
        svc.submit("multi", np.zeros((1, NB), dtype=np.uint8))


def test_register_rejects_party_restricted_and_mismatched(dcf, bundles):
    svc = make_service(dcf, bundles)
    with pytest.raises(ShapeError, match="two-party"):
        svc.register_key("half", bundles["relu-a"].for_party(0))
