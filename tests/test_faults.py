"""Fault-injection suite: every typed error and every fallback edge.

Exercises the ``dcf_tpu.errors`` taxonomy deterministically under
``JAX_PLATFORMS=cpu`` via the ``dcf_tpu.testing.faults`` seams — no real
toolchain breakage, dead accelerator, or corrupted artifact required:

* DCFK ingestion: truncated / wrong-magic / bad-version / bit-flipped
  (CRC) / oversized frames each rejected with ``KeyFormatError`` naming
  the offending field; v1 frames still read.
* Auto backend selection: a forced Pallas failure degrades to bitsliced
  with a ``BackendFallbackWarning`` and bit-exact spec parity.
* Staged-prefix staleness: a staged dict that outlives its bundle raises
  ``StaleStateError`` instead of an opaque Pallas shape error.
* Native core: build exit != 0 and CDLL load failure degrade AES-NI ->
  portable (warned); persistent failure raises ``NativeBuildError``.
* Mesh provisioning failure raises ``BackendUnavailableError``.
* The exception-hygiene static gate (the dcflint exception-hygiene pass).
"""

import struct
import subprocess
import sys
import warnings
import zlib

import numpy as np
import pytest

from dcf_tpu import errors, spec
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.testing import faults

pytestmark = pytest.mark.faults

KEYS = [bytes(range(32)), bytes(range(1, 33))]


@pytest.fixture(scope="module")
def bundle():
    prg = HirosePrgNp(16, KEYS)
    rng = np.random.default_rng(7)
    alphas = rng.integers(0, 256, (2, 2), dtype=np.uint8)
    betas = rng.integers(0, 256, (2, 16), dtype=np.uint8)
    return gen_batch(prg, alphas, betas, random_s0s(2, 16, rng),
                     spec.Bound.LT_BETA)


# -- taxonomy ---------------------------------------------------------------


def test_error_taxonomy():
    """Every typed error is a DcfError AND the builtin its pre-taxonomy
    call sites raised, so old `except ValueError` handlers keep working."""
    for cls in (errors.KeyFormatError, errors.ShapeError):
        assert issubclass(cls, errors.DcfError)
        assert issubclass(cls, ValueError)
    for cls in (errors.BackendUnavailableError, errors.StaleStateError,
                errors.NativeBuildError):
        assert issubclass(cls, errors.DcfError)
        assert issubclass(cls, RuntimeError)
    w = errors.BackendFallbackWarning("a", "b", OSError("x"))
    assert w.failed == "a" and w.fallback == "b"
    assert "falling back" in str(w)


def test_facade_shape_error(bundle):
    from dcf_tpu import Dcf

    dcf = Dcf(2, 16, KEYS, backend="numpy")
    with pytest.raises(errors.ShapeError, match="alphas"):
        dcf.gen(np.zeros((1, 3), dtype=np.uint8),
                np.zeros((1, 16), dtype=np.uint8))


# -- DCFK ingestion ---------------------------------------------------------


def test_dcfk_roundtrip_and_v1_compat(bundle):
    data = bundle.to_bytes()
    rt = KeyBundle.from_bytes(data)
    for name in ("s0s", "cw_s", "cw_v", "cw_t", "cw_np1"):
        assert np.array_equal(getattr(rt, name), getattr(bundle, name))
    # v1 frame: no CRC trailer, version field 1 — still readable.
    v1 = bytearray(data[:-4])
    struct.pack_into("<H", v1, 4, 1)
    rt1 = KeyBundle.from_bytes(bytes(v1))
    assert np.array_equal(rt1.cw_np1, bundle.cw_np1)


def _oversized(data: bytes) -> bytes:
    # Junk between the last section and the trailer, CRC recomputed so the
    # size check (not the CRC) is what must catch it.
    body = data[:-4] + b"\x00\x00"
    return body + struct.pack("<I", zlib.crc32(body))


@pytest.mark.parametrize(
    "mutate, field",
    [
        (lambda d: b"XXXK" + d[4:], "magic"),
        (lambda d: d[:12], "header"),
        (lambda d: faults.corrupt(d, 4, 0x7F), "version"),
        (lambda d: d[: len(d) // 2], "truncated frame"),
        (lambda d: faults.corrupt(d, 40), "crc32"),  # payload bit flip
        (lambda d: faults.corrupt(d, len(d) - 1), "crc32"),  # trailer flip
        (_oversized, "oversized"),
    ],
    ids=["magic", "header", "version", "truncated", "payload-flip",
         "trailer-flip", "oversized"],
)
def test_dcfk_corruption_rejected(bundle, mutate, field):
    data = bundle.to_bytes()
    with pytest.raises(errors.KeyFormatError, match=field):
        KeyBundle.from_bytes(mutate(data))


def test_dcfk_truncation_names_section(bundle):
    """A frame cut mid-payload names the section where it ran out."""
    data = bundle.to_bytes()
    with pytest.raises(errors.KeyFormatError, match="cw_np1"):
        KeyBundle.from_bytes(data[:-24])  # inside the last section


# -- auto backend fallback chain --------------------------------------------


def test_canary_fallback_pallas_to_bitsliced(monkeypatch):
    """Forced Pallas failure at Dcf(backend='auto') degrades to bitsliced
    with a structured warning, and the fallen-back facade is bit-exact
    against the spec."""
    import dcf_tpu.api as api
    from dcf_tpu import Dcf

    monkeypatch.setattr(api, "_default_backend", lambda lam: "pallas")
    api.reset_backend_health()
    with faults.inject("pallas.lowering"):
        with pytest.warns(errors.BackendFallbackWarning) as rec:
            dcf = Dcf(2, 16, KEYS, backend="auto")
    assert dcf.backend_name == "bitsliced"
    fb = [r.message for r in rec
          if isinstance(r.message, errors.BackendFallbackWarning)]
    assert fb and fb[0].failed == "pallas" and fb[0].fallback == "bitsliced"
    assert isinstance(fb[0].cause, faults.InjectedFault)
    # Spec parity through the degraded facade.
    rng = np.random.default_rng(9)
    alphas = rng.integers(0, 256, (1, 2), dtype=np.uint8)
    betas = rng.integers(0, 256, (1, 16), dtype=np.uint8)
    kb = dcf.gen(alphas, betas, rng=rng)
    xs = rng.integers(0, 256, (6, 2), dtype=np.uint8)
    xs[0] = alphas[0]
    recon = dcf.eval(0, kb, xs) ^ dcf.eval(1, kb, xs)
    a = alphas[0].tobytes()
    for j in range(6):
        want = betas[0].tobytes() if xs[j].tobytes() < a else bytes(16)
        assert recon[0, j].tobytes() == want


def test_canary_verdict_cached(monkeypatch):
    """A passed canary is cached per (backend, lam): the second auto
    construction must not re-run it (no second fallback warning storm)."""
    import dcf_tpu.api as api
    from dcf_tpu import Dcf

    dcf0 = Dcf(2, 16, KEYS, backend="auto")
    assert dcf0.backend_name == "bitsliced"
    assert dcf0._health_key("bitsliced") in api._HEALTHY
    canary_calls = []
    monkeypatch.setattr(
        Dcf, "_canary",
        lambda self, name: canary_calls.append(name))
    assert Dcf(2, 16, KEYS, backend="auto").backend_name == "bitsliced"
    assert canary_calls == []


def test_explicit_backend_no_canary():
    """Explicitly named backends stay strict: no canary, and a Pallas
    failure surfaces instead of silently substituting a backend."""
    from dcf_tpu import Dcf
    from dcf_tpu.backends.pallas_backend import PallasBackend

    with faults.inject("pallas.lowering"):
        dcf = Dcf(2, 16, KEYS, backend="pallas")  # construction is lazy
        assert dcf.backend_name == "pallas"
        be = PallasBackend(16, KEYS)
        with pytest.raises(faults.InjectedFault):
            be.eval(0, np.zeros((2, 2), dtype=np.uint8))


# -- staged-prefix staleness -------------------------------------------------


def test_stale_prefix_staged_dict(bundle):
    """A staged dict cut at prefix depth k over an n-level domain must be
    rejected once put_bundle ships a bundle with different geometry
    (ADVICE.md finding 3) — BEFORE any kernel dispatch can hit an opaque
    shape error.  A new bundle with the SAME (k, n) keeps old staged
    dicts valid (they are pure functions of xs, k and n)."""
    from dcf_tpu.backends.pallas_prefix import PrefixPallasBackend

    rng = np.random.default_rng(11)
    one_key = KeyBundle(
        s0s=bundle.s0s[:1], cw_s=bundle.cw_s[:1], cw_v=bundle.cw_v[:1],
        cw_t=bundle.cw_t[:1], cw_np1=bundle.cw_np1[:1])
    be = PrefixPallasBackend(16, KEYS, interpret=True, tile_words=2)
    be.put_bundle(one_key.for_party(0))
    xs = rng.integers(0, 256, (32, 2), dtype=np.uint8)
    staged = be.stage(xs)
    assert staged["k"] == be._k() and staged["n"] == 16
    # Same geometry, more keys: staged dict stays valid (k unchanged).
    be.put_bundle(bundle.for_party(0))
    be._check_staged_fresh(staged)  # must not raise
    # Geometry drift: a deeper domain changes _k() (8 -> 16 here); the
    # old dict's idx/x_mask_rem were cut at k=8 and must be rejected.
    prg = HirosePrgNp(16, KEYS)
    alphas3 = rng.integers(0, 256, (1, 3), dtype=np.uint8)
    betas3 = rng.integers(0, 256, (1, 16), dtype=np.uint8)
    deep = gen_batch(prg, alphas3, betas3, random_s0s(1, 16, rng),
                     spec.Bound.LT_BETA)
    be.put_bundle(deep.for_party(0))
    with pytest.raises(errors.StaleStateError, match="k=8"):
        be.eval_staged(0, staged)
    # Re-staging against the live bundle passes the freshness check
    # (kernel-level parity of the staged path is test_prefix.py's job).
    staged2 = be.stage(rng.integers(0, 256, (32, 3), dtype=np.uint8))
    assert staged2["k"] == 16 and staged2["n"] == 24
    be._check_staged_fresh(staged2)  # must not raise


def test_prefix_cross_instance_staging_still_works(bundle):
    """The party-0/party-1 pattern (stage once, eval on both parties'
    backends) stays valid — even when the instances' put_bundle counts
    differ — because freshness is geometry, not instance history."""
    from dcf_tpu.backends.pallas_prefix import PrefixPallasBackend

    one_key = KeyBundle(
        s0s=bundle.s0s[:1], cw_s=bundle.cw_s[:1], cw_v=bundle.cw_v[:1],
        cw_t=bundle.cw_t[:1], cw_np1=bundle.cw_np1[:1])
    rng = np.random.default_rng(12)
    bes = {}
    for b in (0, 1):
        bes[b] = PrefixPallasBackend(16, KEYS, interpret=True, tile_words=2)
        bes[b].put_bundle(one_key.for_party(b))
    bes[0].put_bundle(one_key.for_party(0))  # asymmetric ship counts
    staged = bes[0].stage(rng.integers(0, 256, (32, 2), dtype=np.uint8))
    bes[1]._check_staged_fresh(staged)  # must not raise


# -- native core fallback ----------------------------------------------------


def test_native_build_failure_raises_typed():
    from dcf_tpu import native

    with faults.inject("native.build"):
        with pytest.raises(errors.NativeBuildError, match="2 attempts"):
            native.build(portable=False)


def test_native_build_failure_falls_back_portable(monkeypatch):
    from dcf_tpu import native

    monkeypatch.setattr(native, "_LIBS", {})
    monkeypatch.setattr(native, "_FAILED", set())
    aesni_only = faults.fail_unless(lambda portable: portable)
    with faults.inject("native.build", handler=aesni_only):
        with pytest.warns(errors.BackendFallbackWarning, match="portable"):
            lib = native.load(portable=False)
    assert lib is native._LIBS[(True, False)]  # the portable core now serves
    assert (False, False) not in native._LIBS  # not cached as AES-NI
    # Negative cache: the next load(False) goes straight to portable —
    # no second warning storm, no re-spawned make subprocesses.
    assert (False, False) in native._FAILED
    with warnings.catch_warnings():
        warnings.simplefilter("error", errors.BackendFallbackWarning)
        assert native.load(portable=False) is lib


def test_native_cdll_failure_falls_back_portable(monkeypatch):
    from dcf_tpu import native

    monkeypatch.setattr(native, "_LIBS", {})
    monkeypatch.setattr(native, "_FAILED", set())
    aesni_only = faults.fail_unless(lambda portable: portable)
    with faults.inject("native.load", handler=aesni_only):
        with pytest.warns(errors.BackendFallbackWarning, match="portable"):
            lib = native.load(portable=False)
    assert lib is native._LIBS[(True, False)]
    assert lib.dcf_prg_sizeof() > 0  # the degraded core is live


def test_native_portable_failure_is_final(monkeypatch):
    from dcf_tpu import native

    monkeypatch.setattr(native, "_LIBS", {})
    monkeypatch.setattr(native, "_FAILED", set())
    with faults.inject("native.load"):
        with pytest.warns(errors.BackendFallbackWarning):
            with pytest.raises(
                    (errors.NativeBuildError,
                     errors.BackendUnavailableError)):
                native.load(portable=False)


# -- mesh provisioning -------------------------------------------------------


def test_mesh_provision_failure_typed():
    from dcf_tpu.parallel import make_mesh

    with faults.inject("mesh.provision",
                       exc=RuntimeError("TPU driver gone")):
        with pytest.raises(errors.BackendUnavailableError,
                           match="mesh provisioning failed"):
            make_mesh(8)


# -- harness hygiene ---------------------------------------------------------


def test_inject_rejects_unknown_point():
    with pytest.raises(ValueError, match="unknown fault point"):
        with faults.inject("no.such.seam"):
            pass


def test_fire_is_noop_when_unarmed():
    faults.fire("pallas.lowering")  # must not raise
    assert not faults.is_armed("pallas.lowering")


def test_corrupt_helper_bounds(bundle):
    data = bundle.to_bytes()
    assert faults.corrupt(data, 0) != data
    with pytest.raises(ValueError):
        faults.corrupt(data, len(data))
    with pytest.raises(ValueError):
        faults.corrupt(data, 0, 0)


def test_exception_hygiene_gate():
    """No blanket handlers in dcf_tpu/ outside marked fallback sites
    (the dcflint exception-hygiene pass; the old standalone script was
    deleted in PR 4)."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dcflint", "dcf_tpu",
         "--pass", "exception-hygiene"],
        capture_output=True, text=True, cwd=root)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- secret hygiene: key-class repr redaction --------------------------------


def test_key_class_reprs_redact(bundle):
    """KeyBundle/Share/Cw reprs show shapes/geometry, never seed or CW
    bytes (the dcflint secret-hygiene pass enforces that the __repr__s
    EXIST; this proves what they emit).  A dataclass default repr here
    would hand the other party the function via any log line or
    traceback that formats a bundle."""
    r = repr(bundle)
    assert r == ("KeyBundle(K=2, n_bits=16, lam=16, parties=2, "
                 "group=xor, <1184 key-material bytes redacted>)")
    # no array/bytes content: every byte value of the actual key material
    # is absent from the repr
    assert bundle.s0s.tobytes() not in r.encode()
    assert bundle.cw_s.tobytes()[:8].hex() not in r
    share = bundle.to_shares()[0]
    rs = repr(share)
    assert "redacted" in rs and share.cw_np1 not in rs.encode()
    rc = repr(share.cws[0])
    assert "redacted" in rc and share.cws[0].s not in rc.encode()
    # the restricted (per-party) form discloses its geometry too
    assert "parties=1" in repr(bundle.for_party(0))
