"""On-device key factory: pools, claims, refill policy, durability
(ISSUE 11).

The contract under test, clustered:

* **Pools + claims** — a declared pool fills to target via batched
  mints, a claim registers a pre-minted key that serves BIT-EXACT
  two-party reconstructions, and pool exhaustion falls back to a
  synchronous host mint that is counted AND warned AND still bit-exact
  (the silent path must never be what passes parity — the miss counter
  is pinned on the parity assertion itself).
* **Refill policy** — priority order (CRITICAL pools first), brownout
  pausing BATCH refill, and the ``keyfactory.refill`` fault seam
  driving the factory's own breaker: repeated failures open it, claims
  keep serving (pool then fallback), the cooldown's probe closes it.
* **Durability** — refill batches publish with ONE manifest flip
  (``KeyStore.put_many``); a kill between the frame writes and the
  flip leaves the previous pool (never torn); warm restart re-pools
  un-claimed supply with generations preserved and ZERO re-keygen.
* **Plane handoff** — on the hybrid family a claimed key's registry
  residency stages straight from the keygen kernel's plane dict
  (``gen_on_device_with_planes`` -> ``put_bundle(dev_planes=...)``),
  no host bit-plane expansion.

All deterministic: seeded rngs, ``pump()`` driving (no worker threads
except the slow soak), fake clocks for breaker cooldowns.
"""

import threading
import warnings

import numpy as np
import pytest

from dcf_tpu import Dcf
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.errors import BackendFallbackWarning, ShapeError
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.protocols.oracle import mic_oracle
from dcf_tpu.serve import DcfService, PoolSpec, Priority, ServeConfig
from dcf_tpu.serve.keyfactory import parse_pool_store_id, pool_store_id
from dcf_tpu.testing import faults
from dcf_tpu.testing.faults import FakeClock

pytestmark = pytest.mark.keyfactory

NB, LAM = 2, 16


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0xFAC7)


@pytest.fixture(scope="module")
def ck(rng):
    return [rng.bytes(32), rng.bytes(32)]


@pytest.fixture(scope="module")
def dcf(ck):
    return Dcf(NB, LAM, ck, backend="bitsliced")


@pytest.fixture(scope="module")
def prg(ck):
    return HirosePrgNp(LAM, ck)


ALPHAS = np.array([[0x42, 0x10]], dtype=np.uint8)


def make_betas(rng):
    return rng.integers(1, 256, (1, LAM), dtype=np.uint8)


def make_spec(rng, name="p", **kw):
    base = dict(name=name, alphas=ALPHAS, betas=make_betas(rng),
                target_depth=6, low_water=2, refill_batch=3)
    return PoolSpec(**{**base, **kw})


def serve_and_check(svc, key_id, spec, rng, points=8):
    """Evaluate ``key_id`` for both parties through the service and
    check the XOR reconstruction against the pool's comparison
    function, including x = alpha."""
    xs = rng.integers(0, 256, (points, NB), dtype=np.uint8)
    xs[0] = spec.alphas[0]
    f0 = svc.submit(key_id, xs, b=0)
    f1 = svc.submit(key_id, xs, b=1)
    svc.pump()
    recon = f0.result() ^ f1.result()
    a = spec.alphas[0].tobytes()
    for j in range(points):
        want = (spec.betas[0].tobytes() if xs[j].tobytes() < a
                else bytes(LAM))
        assert recon[0, j].tobytes() == want, j


# ------------------------------------------------------ spec validation


def test_pool_spec_validation(rng):
    betas = make_betas(rng)
    with pytest.raises(ValueError, match="'/'-free"):
        PoolSpec(name="a/b", alphas=ALPHAS, betas=betas)
    with pytest.raises(ShapeError, match="exactly one of"):
        PoolSpec(name="x", betas=betas)
    with pytest.raises(ShapeError, match="exactly one of"):
        PoolSpec(name="x", alphas=ALPHAS, intervals=((1, 2),),
                 betas=betas)
    with pytest.raises(ValueError, match="low_water"):
        PoolSpec(name="x", alphas=ALPHAS, betas=betas,
                 target_depth=4, low_water=5)
    with pytest.raises(ValueError, match="refill_batch"):
        PoolSpec(name="x", alphas=ALPHAS, betas=betas, refill_batch=0)
    with pytest.raises(ShapeError, match="alphas"):
        PoolSpec(name="x", alphas=ALPHAS, betas=betas[:, :8][None][0]
                 .reshape(2, 4))
    # the spec repr never prints the function
    s = PoolSpec(name="x", alphas=ALPHAS, betas=betas)
    assert "redacted" in repr(s) and "4" not in repr(s.betas[0, 0])


def test_add_pool_validates_against_facade(dcf, rng):
    svc = DcfService(dcf, ServeConfig())
    with pytest.raises(ShapeError, match="lam"):
        svc.add_pool(PoolSpec(
            name="bad-lam", alphas=ALPHAS,
            betas=rng.integers(0, 256, (1, LAM + 16), dtype=np.uint8)))
    with pytest.raises(ShapeError, match="domain"):
        svc.add_pool(PoolSpec(
            name="bad-nb",
            alphas=rng.integers(0, 256, (1, NB + 1), dtype=np.uint8),
            betas=make_betas(rng)))
    spec = svc.add_pool(make_spec(rng, name="dup"))
    with pytest.raises(ValueError, match="already declared"):
        svc.add_pool(spec)


def test_pool_store_id_roundtrip():
    assert parse_pool_store_id(pool_store_id("sess", 17)) == ("sess", 17)
    assert parse_pool_store_id("user-key") is None
    assert parse_pool_store_id("~pool/sess/not-a-seq") is None


# ------------------------------------------------- pools, claims, parity


def test_refill_fills_and_pool_hit_serves_bit_exact(dcf, rng):
    svc = DcfService(dcf, ServeConfig())
    spec = svc.add_pool(make_spec(rng, name="relu"))
    report = svc.keyfactory.pump()
    assert report.minted == {"relu": 6}
    assert svc.keyfactory.depth("relu") == 6
    snap0 = svc.metrics_snapshot()
    assert snap0["keyfactory_pool_depth{pool=relu}"] == 6
    registered = svc.register_key("sess-1", pool="relu")
    assert registered.s0s.shape[1] == 2  # the dealer's two-party copy
    serve_and_check(svc, "sess-1", spec, rng)
    assert svc.keyfactory.depth("relu") == 5
    snap = svc.metrics_snapshot()
    assert snap["keyfactory_pool_hits_total"] == 1
    assert snap["keyfactory_pool_misses_total"] == 0
    assert snap["keyfactory_minted_keys_total"] == 6
    # fresh seeds per entry: two claims never share key material
    other = svc.register_key("sess-2", pool="relu")
    assert other.s0s.tobytes() != registered.s0s.tobytes()


def test_exhaustion_falls_back_counted_warned_bit_exact(dcf, rng):
    """The acceptance satellite: the fallback path is what serves the
    parity assertion here, PROVEN by the pinned miss counter — and it
    is counted and warned, never silent."""
    svc = DcfService(dcf, ServeConfig())
    spec = svc.add_pool(make_spec(rng, name="dry"))
    svc.keyfactory.pump()
    while svc.keyfactory.depth("dry"):
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # draining is all hits
            svc.register_key("drain", pool="dry")
    with pytest.warns(BackendFallbackWarning, match="keyfactory-pool"):
        svc.register_key("fb-sess", pool="dry")
    snap = svc.metrics_snapshot()
    assert snap["keyfactory_pool_misses_total"] == 1
    serve_and_check(svc, "fb-sess", spec, rng)


def test_register_key_pool_contract(dcf, rng):
    svc = DcfService(dcf, ServeConfig())
    svc.add_pool(make_spec(rng, name="p"))
    svc.keyfactory.pump()
    with pytest.raises(ValueError, match="needs a bundle or a pool"):
        svc.register_key("nope")
    with pytest.raises(ValueError, match="not both"):
        kb = svc.register_key("ok", pool="p")
        svc.register_key("both", kb, pool="p")
    with pytest.raises(ValueError, match="no key pool"):
        svc.register_key("x", pool="unknown")


def test_mic_pool_claims_serve_protocol_keys(dcf, rng):
    intervals = ((100, 2000), (3000, 50000))
    betas = rng.integers(0, 256, (2, LAM), dtype=np.uint8)
    svc = DcfService(dcf, ServeConfig())
    svc.add_pool(PoolSpec(name="mic", intervals=intervals, betas=betas,
                          target_depth=3, low_water=1, refill_batch=3))
    svc.keyfactory.pump()
    pb = svc.register_key("mic-sess", pool="mic")
    from dcf_tpu.protocols import ProtocolBundle

    assert isinstance(pb, ProtocolBundle)
    xs = rng.integers(0, 256, (16, NB), dtype=np.uint8)
    f0 = svc.submit("mic-sess", xs, b=0)
    f1 = svc.submit("mic-sess", xs, b=1)
    svc.pump()
    got = f0.result() ^ f1.result()
    assert np.array_equal(got, mic_oracle(xs, list(intervals), betas))
    # the MIC fallback path mints protocol keys too
    while svc.keyfactory.depth("mic"):
        svc.register_key("drain", pool="mic")
    with pytest.warns(BackendFallbackWarning):
        pb_fb = svc.register_key("mic-fb", pool="mic")
    assert isinstance(pb_fb, ProtocolBundle)
    f0 = svc.submit("mic-fb", xs, b=0)
    f1 = svc.submit("mic-fb", xs, b=1)
    svc.pump()
    assert np.array_equal(f0.result() ^ f1.result(),
                          mic_oracle(xs, list(intervals), betas))


# ------------------------------------------------------- refill policy


def test_refill_priority_order_and_brownout(dcf, rng):
    svc = DcfService(dcf, ServeConfig())
    svc.add_pool(make_spec(rng, name="bulk", priority=Priority.BATCH))
    svc.add_pool(make_spec(rng, name="vip",
                           priority=Priority.CRITICAL))
    svc.add_pool(make_spec(rng, name="mid", priority=Priority.NORMAL))
    svc.queue.set_brownout(True)
    report = svc.keyfactory.pump()
    # CRITICAL refills first; BATCH refill is PAUSED under brownout
    assert list(report.minted) == ["vip", "mid"]
    assert report.skipped == ["bulk"]
    assert svc.keyfactory.depth("bulk") == 0
    svc.queue.set_brownout(False)
    report = svc.keyfactory.pump()
    assert report.minted == {"bulk": 6}
    # hysteresis: nothing refills until a pool drops below low_water
    assert svc.keyfactory.pump().minted == {}
    for _ in range(5):  # depth 6 -> 1 < low_water=2
        svc.register_key("d", pool="mid")
    assert svc.keyfactory.pump().minted == {"mid": 5}


def test_refill_fault_takes_breaker_path(dcf, rng):
    """The ``keyfactory.refill`` seam: armed failures are contained
    and counted, repeated failures open the factory's own breaker
    (claims keep serving from pool/fallback, the SERVING board is
    untouched), and the cooldown probe closes it after recovery."""
    clk = FakeClock()
    svc = DcfService(dcf, ServeConfig(breaker_failures=3,
                                      breaker_cooldown_s=5.0),
                     clock=clk)
    spec = svc.add_pool(make_spec(rng, name="flaky"))
    board_key = "~pool/flaky"
    with faults.inject_schedule("keyfactory.refill",
                                window_evals=3) as sched:
        for i in range(3):
            report = svc.keyfactory.pump()
            assert "flaky" in report.failed
        assert sched.recovered
        assert svc.keyfactory.breakers.state(
            board_key, "keyfactory") == "open"
        # open breaker: the next sweep SKIPS the pool (fails fast)
        report = svc.keyfactory.pump()
        assert report.skipped == ["flaky"] and not report.failed
    snap = svc.metrics_snapshot()
    assert snap["keyfactory_refill_failures_total"] == 3
    # the serving breaker board never saw the provisioning failure
    assert not svc.breakers.any_open()
    # claims still serve: the counted fallback path
    with pytest.warns(BackendFallbackWarning):
        svc.register_key("during-open", pool="flaky")
    serve_and_check(svc, "during-open", spec, rng)
    # cooldown elapses -> the half-open probe refill succeeds + closes
    clk.advance(5.5)
    report = svc.keyfactory.pump()
    assert report.minted == {"flaky": 6}
    assert svc.keyfactory.breakers.state(
        board_key, "keyfactory") == "closed"


# ----------------------------------------------- durability + restart


def test_batched_publish_is_one_manifest_flip(dcf, rng, tmp_path):
    svc = DcfService(dcf, ServeConfig(store_dir=str(tmp_path)))
    svc.add_pool(make_spec(rng, name="d", target_depth=5, low_water=5,
                           refill_batch=5))
    flips = []
    with faults.inject("store.manifest",
                       handler=lambda *a: flips.append(a)):
        svc.keyfactory.pump()
    assert len(flips) == 1  # 5 frames, ONE manifest flip
    assert len(svc.store.key_ids()) == 5


def test_kill_between_frames_and_flip_never_tears_the_pool(
        dcf, rng, tmp_path):
    """The acceptance criterion: a kill between the frame writes and
    the manifest flip leaves OLD state — the pool the manifest knew,
    plus unreferenced orphan frames, never a torn entry."""
    svc = DcfService(dcf, ServeConfig(store_dir=str(tmp_path)))
    svc.add_pool(make_spec(rng, name="k", target_depth=4, low_water=4,
                           refill_batch=4))
    svc.keyfactory.pump()
    before = sorted(svc.store.key_ids())
    for _ in range(4):
        svc.register_key("drain", pool="k")
    report = None
    try:
        with faults.inject("store.manifest"):
            report = svc.keyfactory.pump()
    except faults.InjectedFault:
        pass  # the spent-frame reclaim flip died too — a full crash
    # the refill batch died before its flip: manifest unchanged, pool
    # NOT extended (publish-to-servable ordering), frames orphaned
    assert sorted(svc.store.key_ids()) == before
    assert svc.keyfactory.depth("k") == 0
    assert svc.store.sweep_orphans() >= 4
    # the retry (healthy store) publishes cleanly, and the re-queued
    # spent reclaim rides the same sweep's single flip
    report = svc.keyfactory.pump()
    assert report.minted == {"k": 4}
    assert sorted(svc.store.key_ids()) == sorted(
        svc.keyfactory.pool_manifest("k"))
    assert svc.metrics_snapshot()[
        "keyfactory_spent_reclaimed_total"] == 4


def test_warm_restart_repools_with_generations_zero_rekeygen(
        dcf, rng, tmp_path):
    svc = DcfService(dcf, ServeConfig(store_dir=str(tmp_path)))
    spec = svc.add_pool(make_spec(rng, name="wr"))
    svc.keyfactory.pump()
    pre = svc.keyfactory.pool_manifest("wr")
    assert len(pre) == 6
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        svc.register_key("claimed-0", pool="wr")  # spent, unreclaimed
    del svc  # the kill: nothing flushed, nothing closed

    svc2 = DcfService(dcf, ServeConfig(store_dir=str(tmp_path)))
    svc2.add_pool(spec)
    report = svc2.restore_keys()
    assert report.quarantined == {}
    assert report.restored == {}  # pool frames are NOT servable keys
    post = svc2.keyfactory.pool_manifest("wr")
    # zero re-keygen: every entry came from disk, generation preserved
    assert svc2.metrics_snapshot()["keyfactory_minted_keys_total"] == 0
    assert all(post[k] == pre[k] for k in post)
    # the un-flushed claim resurrected (the documented reclaim window):
    # supply hygiene, never a torn entry — and it still serves
    assert set(post) == set(pre)
    assert sorted(report.repooled) == sorted(pre)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        svc2.register_key("post-sess", pool="wr")
    serve_and_check(svc2, "post-sess", spec, rng)
    # post-restore registrations mint generations past every pooled one
    gen = svc2.registry.register(
        "fresh", svc2.registry.bundle("post-sess"))
    assert gen > max(pre.values())


def test_durable_claim_reclaims_pool_frame_atomically(
        dcf, rng, tmp_path):
    """Review regression (cross-session reuse): a DURABLE pool claim
    must fold the spent ``~pool/...`` frame's delete into the session
    frame's own manifest flip — a crash right after the claim (before
    any lazy reclaim flush) must NEVER leave both entries restorable,
    or a second session would be handed key material the restored
    first session already serves."""
    svc = DcfService(dcf, ServeConfig(store_dir=str(tmp_path)))
    spec = svc.add_pool(make_spec(rng, name="dur"))
    svc.keyfactory.pump()
    pre = svc.keyfactory.pool_manifest("dur")
    flips = []
    with faults.inject("store.manifest",
                       handler=lambda *a: flips.append(a)):
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # a pool hit, not a mint
            kb = svc.register_key("dur-sess", bundle=None,
                                  durable=True, pool="dur")
    assert len(flips) == 1  # publish + spent-frame drop: ONE flip
    ids = svc.store.key_ids()
    assert "dur-sess" in ids
    assert len([k for k in ids if k.startswith("~pool/")]) == 5
    del svc  # crash: nothing flushed, nothing closed

    svc2 = DcfService(dcf, ServeConfig(store_dir=str(tmp_path)))
    svc2.add_pool(spec)
    report = svc2.restore_keys()
    # the session key restored as servable; its pool frame did NOT
    # resurrect — the same key material is never claimable twice
    assert sorted(report.restored) == ["dur-sess"]
    assert len(report.repooled) == 5
    stored, _proto, _gen = svc2.store.load("dur-sess")
    assert stored.to_bytes() == kb.to_bytes()
    claimed_ids = {m for m in report.repooled}
    assert all(pre[k] == report.repooled[k] for k in claimed_ids)
    serve_and_check(svc2, "dur-sess", spec, rng)


def test_restore_before_add_pool_stashes_orphans(dcf, rng, tmp_path):
    svc = DcfService(dcf, ServeConfig(store_dir=str(tmp_path)))
    spec = svc.add_pool(make_spec(rng, name="late"))
    svc.keyfactory.pump()
    pre = svc.keyfactory.pool_manifest("late")
    del svc

    svc2 = DcfService(dcf, ServeConfig(store_dir=str(tmp_path)))
    report = svc2.restore_keys()  # pool not declared yet
    assert sorted(report.repooled) == sorted(pre)
    with pytest.raises(ValueError, match="no key pool"):
        svc2.register_key("x", pool="late")
    svc2.add_pool(spec)  # adoption happens here
    assert svc2.keyfactory.pool_manifest("late") == pre
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        svc2.register_key("adopted", pool="late")
    serve_and_check(svc2, "adopted", spec, rng)


def test_fresh_process_seq_never_reuses_live_pool_ids(
        dcf, rng, tmp_path):
    """A fresh factory on an existing store advances each pool's seq
    past every stored frame, so a refill BEFORE restore cannot
    overwrite un-claimed supply."""
    svc = DcfService(dcf, ServeConfig(store_dir=str(tmp_path)))
    svc.add_pool(make_spec(rng, name="s", target_depth=3, low_water=3,
                           refill_batch=3))
    svc.keyfactory.pump()
    del svc
    svc2 = DcfService(dcf, ServeConfig(store_dir=str(tmp_path)))
    svc2.add_pool(make_spec(rng, name="s", target_depth=3, low_water=3,
                            refill_batch=3))
    svc2.keyfactory.pump()  # refills WITHOUT restoring first
    ids = svc2.store.key_ids()
    assert len(ids) == 6  # 3 restored-on-disk + 3 fresh, no overwrite
    assert {parse_pool_store_id(k)[1] for k in ids} == set(range(6))


# ------------------------------------------------------ plane handoff


def test_hybrid_claim_stages_from_keygen_planes(rng):
    """ISSUE 11 zero-round-trip staging: on the hybrid family a pool
    entry carries both parties' kernel plane dicts, and the registry
    residency stages them verbatim (`_dev` holds the SAME arrays —
    no host bit-plane expansion ran)."""
    lam = 48
    ck48 = [rng.bytes(32) for _ in range(18)]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dcf48 = Dcf(NB, lam, ck48, backend="hybrid",
                    backend_opts={"interpret": True})
        svc = DcfService(dcf48, ServeConfig())
        betas = rng.integers(1, 256, (1, lam), dtype=np.uint8)
        spec = svc.add_pool(PoolSpec(
            name="hyb", alphas=ALPHAS, betas=betas, target_depth=2,
            low_water=1, refill_batch=2))
        svc.keyfactory.pump()
        svc.register_key("hsess", pool="hyb")
        entry = svc.registry._entries["hsess"]
        assert entry.planes is not None and set(entry.planes) == {0, 1}
        be0 = svc.registry.resident("hsess", 0)
        assert be0._dev["cs0"] is entry.planes[0]["cs0"]
        assert be0._dev["s0a"] is entry.planes[0]["s0a"]
        # and the staged image evaluates bit-exactly, both parties
        prg48 = HirosePrgNp(lam, ck48)
        xs = rng.integers(0, 256, (8, NB), dtype=np.uint8)
        xs[0] = ALPHAS[0]
        f0 = svc.submit("hsess", xs, b=0)
        f1 = svc.submit("hsess", xs, b=1)
        svc.pump()
        recon = f0.result() ^ f1.result()
        a = ALPHAS[0].tobytes()
        for j in range(8):
            want = (betas[0].tobytes() if xs[j].tobytes() < a
                    else bytes(lam))
            assert recon[0, j].tobytes() == want, j
        # a failure eviction drops the planes: the re-stage must not
        # re-feed device state from the path that just died
        svc.registry.evict_key("hsess")
        assert entry.planes is None
        assert spec.keys_per_session == 1


# ------------------------------------------------------------ the soak


@pytest.mark.slow
def test_keyfactory_churn_soak(dcf, prg, rng):
    """Serial-leg soak: 3 threads of fresh-key-per-session churn
    against a worker-driven factory while every 9th refill batch
    fails at the ``keyfactory.refill`` seam — every delivered session
    must reconstruct its OWN minted key bit-exactly vs the numpy
    oracle (pool hits AND counted fallbacks alike), and the factory
    must keep refilling through the fault pattern."""
    svc = DcfService(dcf, ServeConfig(
        max_batch=256, keyfactory_refill_interval_s=0.01))
    spec = svc.add_pool(make_spec(rng, name="soak", target_depth=24,
                                  low_water=12, refill_batch=6))
    fails = {"n": 0}

    def every_9th(*_a):
        fails["n"] += 1
        if fails["n"] % 9 == 0:
            raise faults.InjectedFault("scheduled refill fault")

    # Warm the padded eval shape BEFORE the timed window: the first
    # XLA compile takes longer than the whole soak, and this test
    # measures churn under faults, not compile latency (the soak must
    # also pass when the slow lane runs it without warm predecessors).
    svc.keyfactory.pump()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        svc.register_key("warm", pool="soak")
    xs_w = rng.integers(0, 256, (16, NB), dtype=np.uint8)
    fw0 = svc.submit("warm", xs_w, b=0)
    fw1 = svc.submit("warm", xs_w, b=1)
    svc.pump()
    fw0.result(120)
    fw1.result(120)
    svc.unregister_key("warm")

    stop = threading.Event()
    errors: list = []
    checked = {"n": 0}

    def session_thread(tid):
        trng = np.random.default_rng(100 + tid)
        i = 0
        while not stop.is_set():
            key_id = f"soak/{tid}/{i}"
            i += 1
            xs = trng.integers(0, 256, (16, NB), dtype=np.uint8)
            try:
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    kb = svc.register_key(key_id, pool="soak")
                f0 = svc.submit(key_id, xs, b=0)
                f1 = svc.submit(key_id, xs, b=1)
                got = f0.result(30) ^ f1.result(30)
                want = (eval_batch_np(prg, 0, kb.for_party(0), xs)
                        ^ eval_batch_np(prg, 1, kb.for_party(1), xs))
                if not np.array_equal(got, want):
                    errors.append((key_id, "reconstruction mismatch"))
                svc.unregister_key(key_id)
                checked["n"] += 1
            except Exception as e:  # fallback-ok: the soak records
                # every failure for the assertion below instead of
                # dying silently in a thread
                errors.append((key_id, repr(e)))

    with faults.inject("keyfactory.refill", handler=every_9th):
        with svc:
            threads = [threading.Thread(target=session_thread,
                                        args=(t,), daemon=True)
                       for t in range(3)]
            for t in threads:
                t.start()
            stop.wait(4.0)
            stop.set()
            for t in threads:
                t.join()
    assert errors == []
    assert checked["n"] >= 6  # the churn actually ran
    snap = svc.metrics_snapshot()
    assert snap["keyfactory_refills_total"] >= 2
    assert spec.keys_per_session == 1
