"""2-server PIR workload (ISSUE 19): retrieval, serving, faults.

The workload contract end to end: a client's DPF query keys, shipped
as DCFK v3 ``proto=2`` frames through the serving tier's registry
plumbing, must retrieve every probed record BIT-EXACTLY from two
servers that each saw only a pseudorandom key — at byte-granular AND
non-byte-granular database domains (the prefix-depth contract), with
the ``serve.eval`` fault seam honouring the same retry-then-evict
discipline as the point-batch service.
"""

import warnings

import numpy as np
import pytest

from dcf_tpu.backends.evalall import DpfEvalAll
from dcf_tpu.errors import ShapeError
from dcf_tpu.gen import random_s0s
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.serve.metrics import Metrics
from dcf_tpu.serve.registry import KeyRegistry
from dcf_tpu.serve.replicate import apply_frame, sync_frames
from dcf_tpu.testing import faults
from dcf_tpu.workloads.pir import (
    PirDatabase,
    PirServer,
    pir_answer_share,
    pir_query_bundle,
    pir_reconstruct,
)

pytestmark = pytest.mark.pir

LAM = 32


def _cipher_keys(rng) -> list:
    return [bytes(rng.integers(0, 256, 32, dtype=np.uint8))
            for _ in range(18)]


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0x919)


@pytest.fixture(scope="module")
def ck(rng):
    return _cipher_keys(rng)


@pytest.fixture(scope="module")
def prg(ck):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return HirosePrgNp(LAM, ck)


@pytest.fixture(scope="module")
def evaluator(ck):
    return DpfEvalAll(LAM, ck, interpret=True)


def _db(rng, n_bits, record_bytes=8):
    records = rng.integers(0, 256, (1 << n_bits, record_bytes),
                           dtype=np.uint8)
    return records, PirDatabase(records, n_bits)


def test_database_validation(rng):
    good = rng.integers(0, 256, (256, 4), dtype=np.uint8)
    with pytest.raises(ShapeError, match="uint8"):
        PirDatabase(good.astype(np.int32), 8)
    with pytest.raises(ShapeError, match="do not fill"):
        PirDatabase(good[:100], 8)
    with pytest.raises(ValueError, match="must be >= 5"):
        PirDatabase(good[:16], 4)


def test_direct_retrieval_byte_domain(rng, prg, evaluator):
    """The bare construction, no serving tier: both parties EvalAll
    their key share, inner-product against the packed database, and
    the XOR of the answer shares is the record — including the first
    and last records of the domain."""
    n = 8
    records, db = _db(rng, n)
    idx = [0, 255, 77]
    bundle = pir_query_bundle(prg, idx, n, random_s0s(len(idx), LAM, rng))
    staged_cw, fronts, parts = evaluator._staged_for(bundle, n)
    shares = []
    for b in (0, 1):
        _y0, _y1, t = evaluator.eval_party(b, parts[b], n, staged_cw,
                                           fronts[b])
        shares.append(pir_answer_share(t, db))
    got = pir_reconstruct(shares[0], shares[1])
    np.testing.assert_array_equal(got, records[idx])
    evaluator.invalidate()


def test_served_retrieval_non_byte_domain_via_frames(rng, prg, evaluator):
    """The full served path at a NON-byte domain (n=9): the query key
    is generated over the next byte-granular domain (16 bits, index in
    the top 9), ships as a proto=2 frame through ``apply_frame``, and
    the server's depth-9 prefix evaluation retrieves bit-exactly."""
    n = 9
    records, db = _db(rng, n)
    idx = [0, 511, 300]
    bundle = pir_query_bundle(prg, idx, n, random_s0s(len(idx), LAM, rng))
    assert bundle.n_bits == 16  # padded to the wire's byte granularity
    registry = KeyRegistry(None)
    gen = apply_frame(registry, "q", bundle.to_bytes(), 7, True,
                      lam=LAM, n_bytes=2, metrics=Metrics())
    assert gen == 7
    server = PirServer(evaluator, db, registry)
    got = pir_reconstruct(server.answer("q", 0), server.answer("q", 1))
    np.testing.assert_array_equal(got, records[idx])
    # repeat queries under the same key ride the selection cache
    np.testing.assert_array_equal(
        pir_reconstruct(server.answer("q", 0), server.answer("q", 1)),
        records[idx])
    # and the anti-entropy half re-ships it flagged as a proto frame
    entries = sync_frames(registry, {})
    assert [(e[0], e[1], e[2]) for e in entries] == [("q", 7, True)]
    assert entries[0][3] == bundle.to_bytes()
    evaluator.invalidate()


def test_query_index_range_refused(rng, prg):
    with pytest.raises(ValueError, match="outside the 2\\^9-record"):
        pir_query_bundle(prg, [1 << 9], 9, random_s0s(1, LAM, rng))


def test_server_refuses_wrong_key_kinds(rng, prg, evaluator):
    """A plain DCF bundle and a too-shallow DPF key both die typed at
    the serve edge, before any kernel runs."""
    records, db = _db(rng, 16, record_bytes=1)
    registry = KeyRegistry(None)
    server = PirServer(evaluator, db, registry)
    shallow = pir_query_bundle(prg, [3], 8, random_s0s(1, LAM, rng))
    registry.register("shallow", shallow)
    with pytest.raises(ShapeError, match="too shallow"):
        server.answer("shallow", 0)
    from dcf_tpu.gen import gen_batch
    from dcf_tpu.spec import Bound

    plain = gen_batch(prg, np.zeros((1, 2), dtype=np.uint8),
                      np.zeros((1, LAM), dtype=np.uint8),
                      random_s0s(1, LAM, rng), Bound.LT_BETA)
    registry.register("plain", plain)
    with pytest.raises(ShapeError, match="not the.*DpfBundle"):
        server.answer("plain", 0)
    with pytest.raises(ValueError, match="party must be 0 or 1"):
        server.answer("shallow", 2)
    with pytest.raises(ValueError, match="retries"):
        PirServer(evaluator, db, registry, retries=-1)


def test_eval_fault_retry_then_evict(rng, prg, evaluator):
    """The serve.eval discipline transplanted: a one-fault window is
    absorbed by the bounded retry (evicting the possibly-poisoned
    staged state first), a window wider than the retry budget re-raises
    the typed cause, and the server recovers after the window."""
    n = 8
    records, db = _db(rng, n)
    registry = KeyRegistry(None)
    idx = [12, 200]
    registry.register("q", pir_query_bundle(
        prg, idx, n, random_s0s(len(idx), LAM, rng)))
    server = PirServer(evaluator, db, registry, retries=1)
    with faults.inject_schedule("serve.eval", window_evals=1) as sched:
        got = pir_reconstruct(server.answer("q", 0), server.answer("q", 1))
    np.testing.assert_array_equal(got, records[idx])
    assert (sched.fired, sched.failed) == (3, 1)
    assert server.eval_faults == 1
    with faults.inject_schedule("serve.eval", window_evals=2) as sched:
        with pytest.raises(faults.InjectedFault):
            server.answer("q", 0)
        # the window is spent; the same call now serves cleanly
        got = pir_reconstruct(server.answer("q", 0), server.answer("q", 1))
    np.testing.assert_array_equal(got, records[idx])
    assert server.eval_faults == 3
    evaluator.invalidate()


def test_facade_pir_query(rng, ck, evaluator):
    """``Dcf.pir_query`` mints a servable bundle over the facade's
    domain with caller-reproducible randomness."""
    from dcf_tpu.api import Dcf

    dcf = Dcf(n_bytes=1, lam=LAM, cipher_keys=ck)
    records, db = _db(rng, 8)
    registry = KeyRegistry(None)
    registry.register("q", dcf.pir_query([42, 0],
                                         rng=np.random.default_rng(5)))
    server = PirServer(evaluator, db, registry)
    got = pir_reconstruct(server.answer("q", 0), server.answer("q", 1))
    np.testing.assert_array_equal(got, records[[42, 0]])
    # same rng seed -> same bundle bytes (reproducible queries)
    again = dcf.pir_query([42, 0], rng=np.random.default_rng(5))
    assert again.to_bytes() == registry.snapshot("q")[0].to_bytes()
    evaluator.invalidate()


def test_pir_answers_through_pod_router_door(rng, prg, evaluator):
    """ISSUE 20 satellite: PIR answers over the DCFE wire end to end —
    an EdgeClient at the POD DOOR, the router relaying the request to
    the owning shard's EdgeServer, the shard a real ``DcfService`` with
    an attached PIR context.  The DPF query key fans out through
    ``DcfRouter.register_key`` (proto=2 frames, owner + replica), the
    query itself is a one-placeholder-point REQUEST frame (the key IS
    the query), and the [K, record_bytes] answer shares ride the SHARE
    frame as [K, 1, record_bytes] — two hops, bit-exact."""
    from dcf_tpu.api import Dcf
    from dcf_tpu.serve import (
        DcfRouter,
        EdgeClient,
        EdgeServer,
        ShardMap,
        ShardSpec,
    )

    n = 9
    records, db = _db(rng, n)
    ck2 = _cipher_keys(rng)
    d = Dcf(2, LAM, ck2, backend="bitsliced")  # 16-bit wire domain
    prg2 = HirosePrgNp(LAM, ck2)
    ev = DpfEvalAll(LAM, ck2, interpret=True)
    svcs, servers, specs = [], [], []
    try:
        for i in range(2):
            svc = d.serve(max_batch=32, max_delay_ms=1.0).start()
            svc.attach_pir(db, ev)
            srv = EdgeServer(svc).start()
            svcs.append(svc)
            servers.append(srv)
            specs.append(ShardSpec(f"shard-{i}", *srv.address))
        router = DcfRouter(ShardMap(specs), n_bytes=2)
        router.start()
        try:
            idx = [0, 511, 300]
            bundle = pir_query_bundle(prg2, idx, n,
                                      random_s0s(len(idx), LAM, rng))
            router.register_key("q", bundle)
            placeholder = np.zeros((1, 2), dtype=np.uint8)
            with EdgeClient(*router.address, n_bytes=2) as c:
                a0 = c.evaluate("q", placeholder, b=0, timeout=120)
                a1 = c.evaluate("q", placeholder, b=1, timeout=120)
            assert a0.shape == (len(idx), 1, db.record_bytes)
            got = pir_reconstruct(a0[:, 0, :], a1[:, 0, :])
            np.testing.assert_array_equal(got, records[idx])
            answered = sum(
                svc.metrics.snapshot()["serve_pir_answers_total"]
                for svc in svcs)
            assert answered == 2  # both parties served THROUGH a shard
        finally:
            router.close()
    finally:
        for srv in servers:
            srv.close()
        for svc in svcs:
            svc.close(drain=False)


def test_service_pir_requires_attached_db(rng, prg):
    """A DPF registration without a database context refuses typed at
    submit — never a point batch against selection-vector material."""
    from dcf_tpu.api import Dcf

    ck2 = _cipher_keys(rng)
    d = Dcf(2, LAM, ck2, backend="bitsliced")
    svc = d.serve()
    try:
        bundle = pir_query_bundle(HirosePrgNp(LAM, ck2), [3], 9,
                                  random_s0s(1, LAM, rng))
        svc.register_key("q", bundle)
        with pytest.raises(ShapeError, match="attach_pir"):
            svc.submit("q", np.zeros((1, 2), dtype=np.uint8), b=0)
    finally:
        svc.close()


@pytest.mark.slow
def test_served_pir_soak_under_eval_faults(rng, prg, evaluator):
    """The serial-leg soak: a stream of fresh queries served while
    every third ``serve.eval`` fire faults — every reconstruction must
    stay bit-exact and every absorbed fault must be counted."""
    n = 9
    records, db = _db(rng, n)
    registry = KeyRegistry(None)
    server = PirServer(evaluator, db, registry, retries=1)
    fired = [0]

    def every_third(*args):
        fired[0] += 1
        if fired[0] % 3 == 0:
            raise faults.InjectedFault("soak fault")

    with faults.inject("serve.eval", handler=every_third):
        for q in range(10):
            idx = [int(x) for x in rng.integers(0, 1 << n, 2)]
            registry.register(f"q{q}", pir_query_bundle(
                prg, idx, n, random_s0s(len(idx), LAM, rng)))
            got = pir_reconstruct(server.answer(f"q{q}", 0),
                                  server.answer(f"q{q}", 1))
            np.testing.assert_array_equal(got, records[idx])
    assert server.eval_faults > 0
    assert server.eval_faults == fired[0] // 3
    evaluator.invalidate()


@pytest.mark.slow
def test_pir_bench_cli_smoke(capsys):
    """One single-domain pir_bench pass end to end: the gate runs, the
    line lands with the leg, the disclosure and the pinned ratio."""
    import json

    from dcf_tpu import cli

    cli.main(["pir_bench", "--n-bits=14", "--reps=1"])
    lines = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(lines[-1])
    assert rec["bench"] == "pir_bench"
    assert rec["queries_per_sec"] > 0
    assert [leg["n_bits"] for leg in rec["legs"]] == [14]
    assert rec["legs"][0]["eval_faults"] == 0
    assert "vs_baseline" in rec["legs"][0]
    assert rec["repro"].startswith("python -m dcf_tpu.cli pir_bench")
