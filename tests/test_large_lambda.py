"""Hybrid large-lambda evaluator: the narrow-walk + affine-wide split must
be bit-identical to the full-width oracle."""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.large_lambda import (
    LargeLambdaBackend,
    narrow_walk_np,
    wide_affine_np,
)
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp


def rand_bytes(rng, n):
    return bytes(rng.getrandbits(8) for _ in range(n))


def _setup(seed, lam, nb=2, m=9, bound=spec.Bound.LT_BETA):
    rng = random.Random(seed)
    ck = [rand_bytes(rng, 32) for _ in range(2 * (lam // 16))]
    prg = HirosePrgNp(lam, ck)
    nprng = np.random.default_rng(seed)
    alphas = nprng.integers(0, 256, (1, nb), dtype=np.uint8)
    betas = nprng.integers(0, 256, (1, lam), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(1, lam, nprng), bound)
    xs = nprng.integers(0, 256, (m, nb), dtype=np.uint8)
    xs[0] = alphas[0]
    return ck, prg, alphas, betas, bundle, xs


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_hybrid_numpy_matches_oracle(bound):
    """Pure-host split (narrow walk + basis-probed affine wide) == the
    full-width numpy oracle, byte for byte, lam=144."""
    ck, prg, alphas, betas, bundle, xs = _setup(95, 144, bound=bound)
    for b in (0, 1):
        kb = bundle.for_party(b)
        want = eval_batch_np(prg, b, kb, xs)[0]  # [M, 144]
        y32, traj = narrow_walk_np(ck, kb, b, xs)
        const, w = wide_affine_np(kb)
        wide = const ^ np.bitwise_xor.reduce(
            w[None] * traj[:, :, None], axis=1)
        got = np.concatenate([y32, wide], axis=1)
        assert np.array_equal(got, want), f"party {b}"


def test_large_lambda_backend_matches_oracle():
    """Device (XLA) hybrid path == oracle at lam=144, both parties,
    plus XOR reconstruction sanity."""
    ck, prg, alphas, betas, bundle, xs = _setup(96, 144)
    be = LargeLambdaBackend(144, ck)
    ys = {}
    for b in (0, 1):
        kb = bundle.for_party(b)
        want = eval_batch_np(prg, b, kb, xs)
        got = be.eval(b, xs, bundle=kb)
        assert np.array_equal(got, want), f"party {b}"
        ys[b] = got
    recon = ys[0][0] ^ ys[1][0]
    a = alphas[0].tobytes()
    for j in range(xs.shape[0]):
        want_y = betas[0].tobytes() if xs[j].tobytes() < a else bytes(144)
        assert recon[j].tobytes() == want_y


@pytest.mark.slow
def test_large_lambda_backend_lam2048():
    ck, prg, alphas, betas, bundle, xs = _setup(97, 2048, m=4)
    be = LargeLambdaBackend(2048, ck)
    for b in (0, 1):
        kb = bundle.for_party(b)
        want = eval_batch_np(prg, b, kb, xs)
        got = be.eval(b, xs, bundle=kb)
        assert np.array_equal(got, want), f"party {b}"
