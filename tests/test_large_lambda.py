"""Hybrid large-lambda evaluator: the narrow-walk + affine-wide split must
be bit-identical to the full-width oracle."""

import random

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.large_lambda import (
    LargeLambdaBackend,
    narrow_walk_np,
    wide_affine_np,
)
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp


def rand_bytes(rng, n):
    return bytes(rng.getrandbits(8) for _ in range(n))


def _setup(seed, lam, nb=2, m=9, bound=spec.Bound.LT_BETA, k=1):
    rng = random.Random(seed)
    ck = [rand_bytes(rng, 32) for _ in range(2 * (lam // 16))]
    prg = HirosePrgNp(lam, ck)
    nprng = np.random.default_rng(seed)
    alphas = nprng.integers(0, 256, (k, nb), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, lam), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(k, lam, nprng), bound)
    xs = nprng.integers(0, 256, (m, nb), dtype=np.uint8)
    xs[0] = alphas[0]
    return ck, prg, alphas, betas, bundle, xs


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_hybrid_numpy_matches_oracle(bound):
    """Pure-host split (narrow walk + basis-probed affine wide) == the
    full-width numpy oracle, byte for byte, lam=144."""
    ck, prg, alphas, betas, bundle, xs = _setup(95, 144, bound=bound)
    for b in (0, 1):
        kb = bundle.for_party(b)
        want = eval_batch_np(prg, b, kb, xs)[0]  # [M, 144]
        y32, traj = narrow_walk_np(ck, kb, b, xs)
        const, w = wide_affine_np(kb)
        wide = const ^ np.bitwise_xor.reduce(
            w[None] * traj[:, :, None], axis=1)
        got = np.concatenate([y32, wide], axis=1)
        assert np.array_equal(got, want), f"party {b}"


@pytest.mark.parametrize("narrow", ["xla", "pallas"])
def test_large_lambda_backend_matches_oracle(narrow):
    """Device hybrid path (both narrow-walk variants) == oracle at
    lam=144, both parties, plus XOR reconstruction sanity."""
    ck, prg, alphas, betas, bundle, xs = _setup(96, 144)
    be = LargeLambdaBackend(144, ck, narrow=narrow,
                            interpret=(narrow == "pallas"))
    ys = {}
    for b in (0, 1):
        kb = bundle.for_party(b)
        want = eval_batch_np(prg, b, kb, xs)
        got = be.eval(b, xs, bundle=kb)
        assert np.array_equal(got, want), f"party {b}"
        ys[b] = got
    recon = ys[0][0] ^ ys[1][0]
    a = alphas[0].tobytes()
    for j in range(xs.shape[0]):
        want_y = betas[0].tobytes() if xs[j].tobytes() < a else bytes(144)
        assert recon[j].tobytes() == want_y


def test_lane_dependent_round_keys_v3():
    """The narrow kernel's compiled path uses the v3 cipher with
    LANE-DEPENDENT round keys (rk [15, 128, L]); pin it against two
    per-half v1 encryptions so a regression in the generalized
    prep_rk_bitmajor_v3/_rk_block L>1 path is caught without hardware."""
    from dcf_tpu.ops.aes_bitsliced import (
        aes256_encrypt_planes_bitmajor,
        aes256_encrypt_planes_bitmajor_v3,
        round_key_masks_bitmajor,
    )

    rng = np.random.default_rng(11)
    rk_a = round_key_masks_bitmajor(rng.bytes(32))
    rk_b = round_key_masks_bitmajor(rng.bytes(32))
    lanes = 6
    st = rng.integers(-(2**31), 2**31, (128, 2 * lanes),
                      dtype=np.int64).astype(np.int32)
    rk_wide = np.concatenate(
        [np.broadcast_to(rk_a, (15, 128, lanes)),
         np.broadcast_to(rk_b, (15, 128, lanes))], axis=2).copy()
    got = aes256_encrypt_planes_bitmajor_v3(np, rk_wide, st, np.int32(-1))
    want_a = aes256_encrypt_planes_bitmajor(
        np, rk_a, st[:, :lanes], np.int32(-1))
    want_b = aes256_encrypt_planes_bitmajor(
        np, rk_b, st[:, lanes:], np.int32(-1))
    assert np.array_equal(got[:, :lanes], want_a)
    assert np.array_equal(got[:, lanes:], want_b)


@pytest.mark.parametrize("narrow", ["xla", "pallas"])
def test_large_lambda_backend_multikey(narrow):
    """Multi-key hybrid (K=3): batched narrow walk + batched GF(2) MXU
    matmul == the oracle for every key, both parties, plus the multi-key
    device parity counter."""
    ck, prg, alphas, betas, bundle, xs = _setup(93, 144, m=7, k=3)
    be = LargeLambdaBackend(144, ck, narrow=narrow,
                            interpret=(narrow == "pallas"))
    ys = {}
    for b in (0, 1):
        kb = bundle.for_party(b)
        want = eval_batch_np(prg, b, kb, xs)  # [3, M, 144]
        got = be.eval(b, xs, bundle=kb)
        assert got.shape == want.shape
        assert np.array_equal(got, want), f"party {b}"
        ys[b] = got
    # device parity counter over all keys/points
    be0 = LargeLambdaBackend(144, ck, narrow=narrow,
                             interpret=(narrow == "pallas"))
    be1 = LargeLambdaBackend(144, ck, narrow=narrow,
                             interpret=(narrow == "pallas"))
    be0.put_bundle(bundle.for_party(0))
    be1.put_bundle(bundle.for_party(1))
    st = be0.stage(xs)
    y0 = be0.eval_staged(0, st)
    y1 = be1.eval_staged(1, st)
    assert int(be0.points_mismatch_count(y0, y1, alphas, betas, st)) == 0


def test_hybrid_points_mismatch_count():
    """The hybrid backend's on-device full-batch parity counter: zero for
    a correct pair, nonzero under corruption (lam=144, xla narrow)."""
    ck, prg, alphas, betas, bundle, xs = _setup(94, 144)
    be0 = LargeLambdaBackend(144, ck, narrow="xla")
    be1 = LargeLambdaBackend(144, ck, narrow="xla")
    be0.put_bundle(bundle.for_party(0))
    be1.put_bundle(bundle.for_party(1))
    st = be0.stage(xs)
    y0 = be0.eval_staged(0, st)
    y1 = be1.eval_staged(1, st)
    a, b = alphas[0].tobytes(), betas[0].tobytes()
    assert int(be0.points_mismatch_count(y0, y1, a, b, st)) == 0
    import jax.numpy as jnp

    y1_bad = jnp.asarray(np.asarray(y1)).at[0, 0, 0].set(
        np.asarray(y1)[0, 0, 0] ^ 1)
    assert int(be0.points_mismatch_count(y0, y1_bad, a, b, st)) > 0


@pytest.mark.slow
def test_large_lambda_backend_lam2048():
    ck, prg, alphas, betas, bundle, xs = _setup(97, 2048, m=4)
    be = LargeLambdaBackend(2048, ck)
    for b in (0, 1):
        kb = bundle.for_party(b)
        want = eval_batch_np(prg, b, kb, xs)
        got = be.eval(b, xs, bundle=kb)
        assert np.array_equal(got, want), f"party {b}"
