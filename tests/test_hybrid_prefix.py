"""Prefix-shared hybrid (large-lambda) evaluator parity.

The top-k narrow frontier (device state walk), the 16-column row gather
with the trajectory-prefix word table, the in-kernel butterfly
transposes, the remaining-level narrow walk, and the wide tail over the
REASSEMBLED gate trajectory must compose to exactly the from-root hybrid
— bit-for-bit against the full-width numpy oracle, both parties, both
bounds, K = 1 and K = 3.  Plus the PR-1 geometry-freshness contract and
the round-6 Pallas DMA-gather probe kernel's correctness.
"""

import random
import warnings

import numpy as np
import pytest

from dcf_tpu import spec
from dcf_tpu.backends.large_lambda import LargeLambdaBackend
from dcf_tpu.backends.numpy_backend import eval_batch_np
from dcf_tpu.errors import StaleStateError
from dcf_tpu.gen import gen_batch, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp


def rand_bytes(rng: random.Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


def _setup(seed, lam, nb=2, m=9, bound=spec.Bound.LT_BETA, k=1):
    rng = random.Random(seed)
    ck = [rand_bytes(rng, 32) for _ in range(max(18, 2 * (lam // 16)))]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", spec.ReferenceContractWarning)
        prg = HirosePrgNp(lam, ck)
    nprng = np.random.default_rng(seed)
    alphas = nprng.integers(0, 256, (k, nb), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, lam), dtype=np.uint8)
    bundle = gen_batch(prg, alphas, betas, random_s0s(k, lam, nprng), bound)
    xs = nprng.integers(0, 256, (m, nb), dtype=np.uint8)
    xs[0] = alphas[0]  # boundary point
    if m > 2:
        xs[1] = 0
        xs[2] = 255
    return ck, prg, alphas, betas, bundle, xs


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_hybrid_prefix_matches_oracle(bound):
    """lam=144, ragged 37-point batch (tile padding through the gather),
    both parties, vs the full-width oracle, plus XOR reconstruction."""
    ck, prg, alphas, betas, bundle, xs = _setup(61, 144, m=37, bound=bound)
    be = LargeLambdaBackend(144, ck, prefix_levels=6, interpret=True)
    ys = {}
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = be.eval(b, xs, bundle=kb)
        want = eval_batch_np(prg, b, kb, xs)
        assert np.array_equal(got, want), f"party {b} {bound}"
        ys[b] = got
    recon = ys[0][0] ^ ys[1][0]
    a = alphas[0].tobytes()
    for j in range(xs.shape[0]):
        x = xs[j].tobytes()
        hit = x < a if bound is spec.Bound.LT_BETA else x > a
        want_y = betas[0].tobytes() if hit else bytes(144)
        assert recon[j].tobytes() == want_y


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
def test_hybrid_prefix_multikey(bound):
    """K=3 keys over shared points: per-key frontier tables stacked, the
    shared prefix indices offset per key, one flat 16-column gather —
    bit-exact per key, both parties, plus the staged device counter and
    the frontier-cached-per-party invariant."""
    ck, prg, alphas, betas, bundle, xs = _setup(62, 144, m=32, k=3,
                                                bound=bound)
    be0 = LargeLambdaBackend(144, ck, prefix_levels=6, interpret=True)
    be1 = LargeLambdaBackend(144, ck, prefix_levels=6, interpret=True)
    be0.put_bundle(bundle.for_party(0))
    be1.put_bundle(bundle.for_party(1))
    staged = be0.stage(xs)  # same-geometry dict serves both parties
    ys_dev = {0: be0.eval_staged(0, staged), 1: be1.eval_staged(1, staged)}
    for b, bk in ((0, be0), (1, be1)):
        got = bk.staged_to_bytes(ys_dev[b], staged["m"])
        want = eval_batch_np(prg, b, bundle.for_party(b), xs)
        assert np.array_equal(got, want), f"party {b} {bound}"
    # Frontier built once per (bundle, party) and reused.
    tbl = be0._frontier[0]
    y0b = be0.eval_staged(0, staged)
    assert be0._frontier[0] is tbl
    assert np.array_equal(np.asarray(ys_dev[0]), np.asarray(y0b))
    gt = bound is spec.Bound.GT_BETA
    assert int(be0.points_mismatch_count(
        ys_dev[0], ys_dev[1], alphas, betas, staged, gt=gt)) == 0
    wrong = betas ^ np.uint8(1)
    n_inside = sum(
        (xs[j].tobytes() < alphas[i].tobytes()) != gt
        and xs[j].tobytes() != alphas[i].tobytes()
        for i in range(3) for j in range(xs.shape[0]))
    assert int(be0.points_mismatch_count(
        ys_dev[0], ys_dev[1], alphas, wrong, staged, gt=gt)) == n_inside


def test_hybrid_prefix_staleness():
    """The PR-1 geometry-freshness contract: a staged dict cut at one
    (k, n) geometry is rejected once put_bundle moves it, and a
    from-root hybrid's staged dict (no prefix indices) is rejected by
    name."""
    ck, prg, _a, _b, bundle, xs = _setup(63, 144, nb=2, m=9)
    be = LargeLambdaBackend(144, ck, prefix_levels=6, interpret=True)
    be.put_bundle(bundle.for_party(0))
    staged = be.stage(xs)
    assert (staged["k"], staged["n"]) == (6, 16)
    # Same geometry re-ship stays valid.
    be.put_bundle(bundle.for_party(0))
    be.eval_staged(0, staged)
    # Domain-depth drift (n 16 -> 24) must be rejected.
    _ck3, _prg3, _a3, _b3, bundle3, _xs3 = _setup(64, 144, nb=3, m=9)
    be.put_bundle(bundle3.for_party(0))
    with pytest.raises(StaleStateError, match="re-stage"):
        be.eval_staged(0, staged)
    # A from-root backend's staged dict has no prefix indices.
    be_root = LargeLambdaBackend(144, ck, narrow="pallas", interpret=True)
    be_root.put_bundle(bundle.for_party(0))
    root_staged = be_root.stage(xs)
    be.put_bundle(bundle.for_party(0))
    with pytest.raises(ValueError, match="prefix-enabled"):
        be.eval_staged(0, root_staged)


def test_hybrid_prefix_validation():
    ck = [rand_bytes(random.Random(65), 32) for _ in range(18)]
    with pytest.raises(ValueError, match="prefix_levels"):
        LargeLambdaBackend(144, ck, prefix_levels=3, interpret=True)
    with pytest.raises(ValueError, match="narrow"):
        LargeLambdaBackend(144, ck, prefix_levels=6, narrow="xla")
    with pytest.raises(ValueError, match="host_levels"):
        LargeLambdaBackend(144, ck, prefix_levels=6, host_levels=6)
    # Too-shallow domains have no prefix to share (< 5 + 8 levels).
    ck, prg, _a, _b, bundle, _xs = _setup(66, 144, nb=1)
    be = LargeLambdaBackend(144, ck, prefix_levels=6, interpret=True)
    with pytest.raises(ValueError, match="too shallow"):
        be.put_bundle(bundle.for_party(0))


def test_hybrid_prefix_k_clamps():
    """_k() leaves >= 8 walked levels, shrinks with the key count (the
    gather-table byte cliff is on TOTAL stacked rows), and floors at 5."""
    ck, prg, _a, _b, b1, _xs = _setup(67, 144, nb=2, k=1)
    be = LargeLambdaBackend(144, ck, prefix_levels=20, interpret=True)
    be.put_bundle(b1.for_party(0))
    assert be._k() == 8  # n=16 -> n-8
    _ck, _prg, _a, _b, b9, _xs = _setup(68, 144, nb=4, k=9)
    be.put_bundle(b9.for_party(0))  # K=9 -> cap 20 - ceil(log2 9) = 16
    assert be._k() == 16


def test_sharded_hybrid_prefix_matches_oracle():
    """The prefix-shared hybrid under shard_map on a virtual 2x2 mesh:
    frontier tables key-sharded, points sharded through the gather —
    bit-exact vs the oracle (collective-free map)."""
    from dcf_tpu.parallel import ShardedLargeLambdaBackend, make_mesh

    ck, prg, _a, _b, bundle, xs = _setup(69, 144, m=9, k=2)
    mesh = make_mesh(shape=(2, 2))
    be = ShardedLargeLambdaBackend(144, ck, mesh, interpret=True,
                                   prefix_levels=6)
    for b in (0, 1):
        kb = bundle.for_party(b)
        got = be.eval(b, xs, bundle=kb)
        want = eval_batch_np(prg, b, kb, xs)
        assert np.array_equal(got, want), f"party {b}"


def test_facade_hybrid_prefix():
    """Dcf(backend="hybrid", backend_opts={"prefix_levels": ...}) routes
    to the prefix-shared hybrid (interpreter off-TPU, same facade rule
    as keylanes/prefix) and reconstructs correctly at the lam=48
    extension edge."""
    from dcf_tpu import Dcf

    ck, prg, alphas, betas, bundle, xs = _setup(72, 48, m=9)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", spec.ReferenceContractWarning)
        dcf = Dcf(2, 48, ck, backend="hybrid",
                  backend_opts={"prefix_levels": 6})
    assert dcf.eval_backend(0).prefix_levels == 6
    recon = dcf.eval(0, bundle, xs) ^ dcf.eval(1, bundle, xs)
    a = alphas[0].tobytes()
    for j in range(xs.shape[0]):
        want = betas[0].tobytes() if xs[j].tobytes() < a else bytes(48)
        assert recon[0, j].tobytes() == want


def test_pallas_dma_gather_matches_take():
    """The round-6 in-kernel gather probe (benchmarks/micro_gather.py):
    scalar-prefetched indices + per-row HBM DMAs must reproduce
    jnp.take(tbl, idx, axis=0) bit-exactly (whatever the pricing
    verdict, the probe must measure a correct program)."""
    import jax.numpy as jnp

    from benchmarks.micro_gather import pallas_dma_gather

    rng = np.random.default_rng(73)
    tbl = jnp.asarray(rng.integers(-(2 ** 31), 2 ** 31, (1 << 10, 8),
                                   dtype=np.int64).astype(np.int32))
    idx = jnp.asarray(rng.integers(0, 1 << 10, (1 << 11,))
                      .astype(np.int32))
    got = pallas_dma_gather(tbl, idx, rows_per_block=256, n_flight=4,
                            interpret=True)
    assert np.array_equal(np.asarray(got),
                          np.asarray(jnp.take(tbl, idx, axis=0)))
