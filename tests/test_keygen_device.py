"""On-device keygen (ISSUE 10): parity, integration, fallback.

The contract under test: ``gen.gen_on_device`` — the Pallas narrow
keygen kernel + affine wide tail for lam >= 48 (``ops.pallas_keygen``,
sharing the eval kernels' per-level AES core) and the keys-in-lanes XLA
generator below that — produces keys BYTE-IDENTICAL to the host
``gen_batch`` (itself pinned to the reference vectors) and to the C++
native core, across (lam, K, bound); device-generated keys evaluate
correctly on the facade backends; the MIC K=2m packing takes the device
path; and a dead device path falls back to the host walk
silent-correct, counted, and warned (seam ``keygen.device``).
"""

import random
import warnings

import numpy as np
import pytest

from dcf_tpu import Dcf, gen, spec
from dcf_tpu.errors import ShapeError
from dcf_tpu.gen import gen_batch, gen_on_device, random_s0s
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.testing import faults

pytestmark = pytest.mark.keygen


def _cipher_keys(rng: random.Random, lam: int) -> list:
    n = max(2, 2 * (lam // 16))
    if lam >= 32:
        n = max(n, 18)
    return [bytes(rng.getrandbits(8) for _ in range(32))
            for _ in range(n)]


def _prg(lam, ck):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return HirosePrgNp(lam, ck)


def _native(lam, ck):
    try:
        from dcf_tpu.native import NativeDcf

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return NativeDcf(lam, ck)
    except Exception:  # fallback-ok: toolchain-less host skips the
        # C++ anchor; the numpy parity assertions still run
        return None


@pytest.mark.parametrize("bound", [spec.Bound.LT_BETA, spec.Bound.GT_BETA])
@pytest.mark.parametrize("lam", [16, 128, 256])
def test_device_keygen_parity_fuzz(lam, bound):
    """Seeded sweep: device keys byte-identical to the host gen_batch
    AND to the C++ native path at K in {1, 3, 8}, both bounds, lam
    covering the keys-in-lanes route (16) and the Pallas narrow route
    (128, 256).  The silent-correct fallback must NOT be what passes
    this test: the fallback counter is pinned unchanged."""
    rng = random.Random(1000 + lam)
    ck = _cipher_keys(rng, lam)
    nprng = np.random.default_rng(
        31 * lam + (1 if bound is spec.Bound.GT_BETA else 0))
    prg = _prg(lam, ck)
    native = _native(lam, ck)
    before = gen.device_fallback_count()
    for k in (1, 3, 8):
        alphas = nprng.integers(0, 256, (k, 2), dtype=np.uint8)
        betas = nprng.integers(0, 256, (k, lam), dtype=np.uint8)
        s0s = random_s0s(k, lam, nprng)
        want = gen_batch(prg, alphas, betas, s0s, bound)
        got = gen_on_device(lam, ck, alphas, betas, s0s, bound,
                            interpret=True)
        assert got.to_bytes() == want.to_bytes(), (lam, k, bound)
        if native is not None:
            nat = native.gen_batch(alphas, betas, s0s, bound)
            assert nat.to_bytes() == want.to_bytes(), (lam, k, bound)
    assert gen.device_fallback_count() == before, \
        "parity came from the host fallback, not the device path"


@pytest.mark.slow
def test_device_keys_reconstruct_on_backends():
    """End to end: device-generated keys evaluated on the auto,
    bitsliced and prefix facade backends reconstruct the comparison
    function (the numpy-oracle expectation) bit-exactly, including the
    x = alpha boundary.  Serial CI leg (slow): four backend
    constructions x two parties of interpret-mode eval — the byte-level
    parity matrix above already pins the bundles identical in tier-1,
    so this adds the eval integration, not the correctness gate."""
    rng = random.Random(77)
    nprng = np.random.default_rng(77)
    k, nb, m = 3, 2, 16

    def check(dcf, bundle, alphas, betas, lam, xs):
        y0 = dcf.eval(0, bundle, xs)
        y1 = dcf.eval(1, bundle, xs)
        recon = y0 ^ y1
        for i in range(k):
            a = alphas[i].tobytes()
            for j in range(xs.shape[0]):
                want = (betas[i].tobytes() if xs[j].tobytes() < a
                        else bytes(lam))
                assert recon[i, j].tobytes() == want, (dcf.backend_name,
                                                       lam, i, j)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        # lam=16: the keys-in-lanes device route, served by auto
        # (bitsliced off-TPU) and the prefix kernel backend.
        ck16 = _cipher_keys(rng, 16)
        alphas = nprng.integers(0, 256, (k, nb), dtype=np.uint8)
        betas = nprng.integers(0, 256, (k, 16), dtype=np.uint8)
        s0s = random_s0s(k, 16, nprng)
        bundle = gen_on_device(16, ck16, alphas, betas, s0s,
                               spec.Bound.LT_BETA, interpret=True)
        xs = nprng.integers(0, 256, (m, nb), dtype=np.uint8)
        xs[0] = alphas[0]  # exact boundary
        check(Dcf(nb, 16, ck16, backend="auto"), bundle, alphas, betas,
              16, xs)
        check(Dcf(nb, 16, ck16, backend="prefix"), bundle, alphas,
              betas, 16, xs)
        # lam=128: the Pallas narrow keygen route, served by auto
        # (hybrid at lam >= 48) and bitsliced.
        ck128 = _cipher_keys(rng, 128)
        betas = nprng.integers(0, 256, (k, 128), dtype=np.uint8)
        s0s = random_s0s(k, 128, nprng)
        bundle = gen_on_device(128, ck128, alphas, betas, s0s,
                               spec.Bound.LT_BETA, interpret=True)
        check(Dcf(nb, 128, ck128, backend="auto"), bundle, alphas,
              betas, 128, xs)
        check(Dcf(nb, 128, ck128, backend="bitsliced"), bundle, alphas,
              betas, 128, xs)


def test_gen_interval_bundle_device_path_mic():
    """``Dcf.mic(..., device=True)`` routes the K=2m packed keygen
    through the device walk: the ProtocolBundle is byte-identical to
    the host path's (same rng stream), and the served-shape MIC
    evaluation reconstructs against the protocol oracle."""
    from dcf_tpu.protocols.oracle import mic_oracle

    rng = random.Random(55)
    nprng = np.random.default_rng(55)
    nb, lam = 2, 128
    ck = _cipher_keys(rng, lam)
    intervals = [(100, 2000), (3000, 50000)]
    betas = nprng.integers(0, 256, (2, lam), dtype=np.uint8)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dcf = Dcf(nb, lam, ck, backend="bitsliced")
        pb_host = dcf.mic(intervals, betas,
                          rng=np.random.default_rng(9))
        pb_dev = dcf.mic(intervals, betas,
                         rng=np.random.default_rng(9), device=True)
        assert pb_dev.to_bytes() == pb_host.to_bytes()
        xs = nprng.integers(0, 256, (16, nb), dtype=np.uint8)
        y0 = dcf.eval_mic(0, pb_dev.for_party(0), xs)
        y1 = dcf.eval_mic(1, pb_dev.for_party(1), xs)
    assert np.array_equal(y0 ^ y1, mic_oracle(xs, intervals, betas))


def test_keygen_device_fault_falls_back_counted():
    """The ``keygen.device`` seam (chaos contract): a dead device path
    must yield HOST-identical keys (silent-correct), bump the fallback
    counter, and warn structured — never crash, never alter bytes."""
    rng = random.Random(42)
    nprng = np.random.default_rng(42)
    lam, nb, k = 128, 2, 4
    ck = _cipher_keys(rng, lam)
    alphas = nprng.integers(0, 256, (k, nb), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, lam), dtype=np.uint8)
    s0s = random_s0s(k, lam, nprng)
    want = gen_batch(_prg(lam, ck), alphas, betas, s0s,
                     spec.Bound.LT_BETA)
    before = gen.device_fallback_count()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        with faults.inject("keygen.device"):
            got = gen_on_device(lam, ck, alphas, betas, s0s,
                                spec.Bound.LT_BETA, interpret=True)
    assert got.to_bytes() == want.to_bytes()
    assert gen.device_fallback_count() == before + 1
    from dcf_tpu.errors import BackendFallbackWarning

    msgs = [x for x in w if isinstance(x.message, BackendFallbackWarning)]
    assert len(msgs) == 1 and msgs[0].message.failed == "device-keygen"
    # and the facade spelling takes the same seam
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dcf = Dcf(nb, lam, ck, backend="numpy")
        with faults.inject("keygen.device"):
            fb = dcf.gen(alphas, betas, s0s=s0s, device=True)
    assert fb.to_bytes() == want.to_bytes()
    assert gen.device_fallback_count() == before + 2


def test_gen_batch_typed_dtype_validation():
    """The PR-2 typed-error sweep's missing edge: non-uint8 inputs die
    ``ShapeError`` naming the argument at the API edge, not as
    ``np.unpackbits``'s bare TypeError mid-walk — on the host walk AND
    the device router (which validates BEFORE the fallback try, so a
    caller bug is never laundered into a counted device fallback)."""
    rng = random.Random(3)
    nprng = np.random.default_rng(3)
    lam = 16
    ck = _cipher_keys(rng, lam)
    prg = _prg(lam, ck)
    alphas = nprng.integers(0, 256, (2, 2), dtype=np.uint8)
    betas = nprng.integers(0, 256, (2, lam), dtype=np.uint8)
    s0s = random_s0s(2, lam, nprng)
    before = gen.device_fallback_count()
    for bad_args in (
        (alphas.astype(np.int32), betas, s0s),
        (alphas, betas.astype(np.float64), s0s),
        (alphas, betas, s0s.tolist()),
    ):
        with pytest.raises(ShapeError, match="uint8"):
            gen_batch(prg, *bad_args, spec.Bound.LT_BETA)
        with pytest.raises(ShapeError, match="uint8"):
            gen_on_device(lam, ck, *bad_args, spec.Bound.LT_BETA,
                          interpret=True)
    with pytest.raises(ShapeError, match="mismatch"):
        gen_batch(prg, alphas, betas[:1], s0s, spec.Bound.LT_BETA)
    assert gen.device_fallback_count() == before


@pytest.mark.slow
def test_staged_planes_skip_host_round_trip():
    """The no-host-round-trip staging path: the keygen kernel's
    correction-word planes, converted on device to the hybrid
    evaluator's staged layout (``PallasKeyGen.gen_with_planes`` — ONE
    walk produces the host bundle and the party's staged dict), drive
    ``put_bundle(bundle, dev_planes=...)`` to a bit-identical eval with
    the host-staged image."""
    from dcf_tpu.backends.large_lambda import LargeLambdaBackend
    from dcf_tpu.ops.pallas_keygen import PallasKeyGen

    rng = random.Random(88)
    nprng = np.random.default_rng(88)
    lam, nb, k = 128, 2, 3
    ck = _cipher_keys(rng, lam)
    alphas = nprng.integers(0, 256, (k, nb), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, lam), dtype=np.uint8)
    s0s = random_s0s(k, lam, nprng)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        kg = PallasKeyGen(lam, ck, interpret=True)
        bundle, planes = kg.gen_with_planes(alphas, betas, s0s,
                                            spec.Bound.LT_BETA, b=1)
        xs = nprng.integers(0, 256, (8, nb), dtype=np.uint8)
        be_host = LargeLambdaBackend(lam, ck, narrow="pallas",
                                     interpret=True)
        y_host = np.asarray(
            be_host.eval(1, xs, bundle=bundle.for_party(1)))
        be_dev = LargeLambdaBackend(lam, ck, narrow="pallas",
                                    interpret=True)
        be_dev.put_bundle(bundle.for_party(1), dev_planes=planes)
        y_dev = np.asarray(be_dev.eval(1, xs))
    assert np.array_equal(y_host, y_dev)
    # geometry mismatches die typed, not as opaque kernel errors
    with pytest.raises(ShapeError, match="geometry"):
        be_dev.put_bundle(bundle.for_party(1),
                          dev_planes={**planes,
                                      "cs0": planes["cs0"][:, :8]})


def test_sharded_hybrid_rejects_dev_planes_typed():
    """The sharded hybrid backend re-places its plane image across the
    mesh; a single-device ``dev_planes`` dict has no shard placement
    and must die typed (ShapeError) at put_bundle, not as a bare
    TypeError or a silently unplaced image."""
    from dcf_tpu.parallel import ShardedLargeLambdaBackend, make_mesh

    rng = random.Random(11)
    nprng = np.random.default_rng(11)
    lam = 128
    ck = _cipher_keys(rng, lam)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        be = ShardedLargeLambdaBackend(lam, ck, make_mesh(shape=(2, 2)),
                                       interpret=True)
        alphas = nprng.integers(0, 256, (2, 2), dtype=np.uint8)
        betas = nprng.integers(0, 256, (2, lam), dtype=np.uint8)
        s0s = random_s0s(2, lam, nprng)
        bundle = gen_batch(_prg(lam, ck), alphas, betas, s0s,
                           spec.Bound.LT_BETA)
    with pytest.raises(ShapeError, match="single-device"):
        be.put_bundle(bundle.for_party(0), dev_planes={"cs0": None})


@pytest.mark.slow
def test_device_bundle_serves_and_persists(tmp_path):
    """ISSUE 10 serve integration: a device-generated bundle registers
    into ``DcfService`` (durable write-through included) exactly like a
    host-generated one — the store frame on disk is byte-identical to
    what the host keygen would have persisted, and served evaluation
    reconstructs the comparison function."""
    rng = random.Random(21)
    nprng = np.random.default_rng(21)
    lam, nb, k = 16, 2, 2
    ck = _cipher_keys(rng, lam)
    alphas = nprng.integers(0, 256, (k, nb), dtype=np.uint8)
    betas = nprng.integers(0, 256, (k, lam), dtype=np.uint8)
    s0s = random_s0s(k, lam, nprng)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dcf = Dcf(nb, lam, ck, backend="bitsliced")
        host = dcf.gen(alphas, betas, s0s=s0s)
        dev = dcf.gen(alphas, betas, s0s=s0s, device=True)
        svc = dcf.serve(store_dir=str(tmp_path / "store"))
        svc.register_key("dev-key", dev, durable=True)
        f0 = svc.submit("dev-key", alphas[:1], b=0)
        f1 = svc.submit("dev-key", alphas[:1], b=1)
        svc.pump()
        recon = f0.result() ^ f1.result()
    # x = alphas[0]: key 0 evaluates OUTSIDE its own interval (x < x is
    # false), key 1 per the comparison function
    a1 = alphas[1].tobytes()
    assert recon[0, 0].tobytes() == bytes(lam)
    assert recon[1, 0].tobytes() == (
        betas[1].tobytes() if alphas[0].tobytes() < a1 else bytes(lam))
    # the durable frame is the host pipeline's frame, byte for byte
    stored, _proto, _generation = svc.store.load("dev-key")
    assert stored.to_bytes() == host.to_bytes()
