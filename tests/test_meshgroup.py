"""dcf_tpu.serve.meshgroup + the router's co-evaluate dispatch
(ISSUE 18).

Covers the pure placement plan (32-aligned contiguous coverage,
sorted-worker determinism, zero-word-worker elision, membership
contracts), the in-process co-evaluated parity (one batch scattered
across every shard of a real-TCP mini pod, both parties, gathered
shares bit-exact vs the numpy oracle and vs the same router's
route-mode answer), the dispatch policy (threshold, never/always,
forced-mode typed refusal with ``retry_after_s``), the degradation
discipline (armed ``mesh.collective`` seam, epoch fence, dead-worker
scatter — each counted ``router_mesh_degraded_total`` + warned
``BackendFallbackWarning`` + still answering bit-exact from
route-mode), and pod-wide mesh registration.  The kill-one-mesh-
worker soak (mesh and slow) — a worker dying MID-BATCH degrades
typed with zero lost keys and zero generation regressions — rides
the serial CI leg.
"""

import threading

import numpy as np
import pytest

from dcf_tpu.errors import (
    BackendFallbackWarning,
    MeshUnavailableError,
)
from dcf_tpu.serve import EdgeServer, MeshGroup, ShardMap, ShardSpec
from dcf_tpu.serve.meshgroup import SLICE_ALIGN, MeshSlice
from dcf_tpu.testing import faults
from tests.test_pod import (  # the pod tier's shared fixtures/helpers
    LAM,
    NB,
    MiniPod,
    bundles,
    ck,
    dcf,
    prg,
    recon_oracle,
    rng,
)

pytestmark = pytest.mark.mesh

__all__ = ["bundles", "ck", "dcf", "prg", "rng"]  # re-exported fixtures


# -------------------------------------------------- the placement plan


def test_plan_covers_aligned_and_ordered():
    g = MeshGroup(["w2", "w0", "w1"], epoch=3)
    assert g.epoch == 3
    assert g.host_ids() == ["w0", "w1", "w2"]  # sorted: set, not list
    for m in (1, 31, 32, 33, 96, 97, 1000, 4096, 4097):
        plan = g.plan(m)
        # Contiguous coverage in worker order, boundaries 32-aligned
        # except the batch end.
        offset = 0
        seen = []
        for sl in plan:
            assert sl.offset == offset
            assert sl.count > 0
            if sl is not plan[-1]:
                assert (sl.offset + sl.count) % SLICE_ALIGN == 0
            offset += sl.count
            seen.append(sl.host_id)
        assert offset == m, m
        assert seen == sorted(seen)
        # Balanced: lane words per worker differ by at most one.
        words = [-(-sl.count // SLICE_ALIGN) for sl in plan]
        assert max(words) - min(words) <= 1, (m, words)


def test_plan_elides_zero_word_workers():
    g = MeshGroup([f"w{i}" for i in range(8)])
    # 17 points = one lane word: ONE slice, not seven empty scatters.
    assert g.plan(17) == [MeshSlice("w0", 0, 17)]
    # 3 words over 8 workers: exactly three slices.
    plan = g.plan(3 * SLICE_ALIGN)
    assert [sl.host_id for sl in plan] == ["w0", "w1", "w2"]


def test_meshgroup_membership_contracts():
    with pytest.raises(ValueError):
        MeshGroup([])
    with pytest.raises(ValueError):
        MeshGroup(["a", "a"])
    with pytest.raises(ValueError):
        MeshGroup(["a"]).plan(0)
    g = MeshGroup(["a", "b"])
    assert len(g) == 2 and "a" in g and "c" not in g


# ------------------------------------------- co-evaluated parity


def _mesh_pod(dcf, bundles, n=3, **router_kw):
    kw = dict(co_eval="auto", co_eval_min_points=64)
    kw.update(router_kw)
    pod = MiniPod(dcf, bundles, n=n, router_kw=kw)
    pod.router.set_mesh()
    for name, kb in sorted(bundles.items()):
        pod.router.register_mesh_key(name, kb)
    return pod


def test_co_evaluated_parity_vs_oracle_and_route(dcf, bundles, prg, rng):
    """One batch scattered across all 3 shards, both parties: the
    gathered shares are bit-exact vs the numpy oracle AND vs the same
    router's route-mode answer, and the dispatch demonstrably took the
    mesh path (co_evals counted, every worker forwarded)."""
    pod = _mesh_pod(dcf, bundles)
    try:
        name, kb = sorted(bundles.items())[0]
        xs = rng.integers(0, 256, (96, NB), dtype=np.uint8)
        got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
        # The identical batch through route-mode (below threshold per
        # request is not possible here, so force the policy off).
        pod.router.co_eval = "never"
        routed = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
            pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, routed)
        snap = pod.router.metrics_snapshot()
        assert snap["router_co_evals_total"] == 2
        assert snap["router_mesh_degraded_total"] == 0
        assert snap["router_mesh_workers"] == 3
        for s in pod.map.host_ids():
            assert snap[f"router_forwards_total{{shard={s}}}"] > 0, snap
    finally:
        pod.close()


def test_co_eval_ragged_sizes_parity(dcf, bundles, prg, rng):
    """Batch sizes straddling every alignment edge stay bit-exact
    (the gather's concatenation order and padding discipline)."""
    pod = _mesh_pod(dcf, bundles, co_eval_min_points=1)
    try:
        name, kb = sorted(bundles.items())[1]
        for m in (1, 31, 33, 64, 97):
            xs = rng.integers(0, 256, (m, NB), dtype=np.uint8)
            got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
                pod.router.evaluate(name, xs, b=1, timeout=60)
            assert np.array_equal(got, recon_oracle(prg, kb, xs)), m
    finally:
        pod.close()


# ------------------------------------------------ the dispatch policy


def test_policy_threshold_and_never(dcf, bundles, rng):
    pod = _mesh_pod(dcf, bundles)  # co_eval_min_points=64
    try:
        name = sorted(bundles)[0]
        xs_small = rng.integers(0, 256, (8, NB), dtype=np.uint8)
        xs_big = rng.integers(0, 256, (64, NB), dtype=np.uint8)
        pod.router.evaluate(name, xs_small, timeout=60)  # below: routed
        assert pod.router.metrics_snapshot()[
            "router_co_evals_total"] == 0
        pod.router.evaluate(name, xs_big, timeout=60)  # at: co-evaluated
        assert pod.router.metrics_snapshot()[
            "router_co_evals_total"] == 1
        pod.router.co_eval = "never"
        pod.router.evaluate(name, xs_big, timeout=60)
        assert pod.router.metrics_snapshot()[
            "router_co_evals_total"] == 1  # unchanged
    finally:
        pod.close()


def test_forced_mesh_without_group_refuses_typed(dcf, bundles, rng):
    """``co_eval="always"`` with no group formed: the caller gets
    ``MeshUnavailableError`` with the probe interval as the hint —
    never a silent route-mode answer they explicitly declined."""
    pod = MiniPod(dcf, bundles, n=2, router_kw=dict(co_eval="always"))
    try:
        xs = rng.integers(0, 256, (8, NB), dtype=np.uint8)
        with pytest.raises(MeshUnavailableError) as ei:
            pod.router.evaluate(sorted(bundles)[0], xs, timeout=60)
        assert ei.value.retry_after_s == pod.router.health.interval_s
    finally:
        pod.close()


def test_router_config_contracts():
    from dcf_tpu.serve import DcfRouter

    ring = ShardMap([ShardSpec("a", port=1)])
    with pytest.raises(ValueError):
        DcfRouter(ring, n_bytes=NB, co_eval="sometimes")
    with pytest.raises(ValueError):
        DcfRouter(ring, n_bytes=NB, co_eval_min_points=0)
    router = DcfRouter(ring, n_bytes=NB)
    try:
        with pytest.raises(ValueError):
            router.set_mesh(["not-a-member"])
    finally:
        router.close()


# --------------------------------------------- degradation discipline


def test_collective_fault_degrades_counted_and_warned(
        dcf, bundles, prg, rng):
    """An armed ``mesh.collective`` seam (a collective that cannot
    form): the batch is still answered bit-exact — served route-mode —
    with the degradation counted and warned, never a bare crash."""
    pod = _mesh_pod(dcf, bundles)
    try:
        name, kb = sorted(bundles.items())[2]
        xs = rng.integers(0, 256, (96, NB), dtype=np.uint8)
        with faults.inject("mesh.collective"):
            with pytest.warns(BackendFallbackWarning):
                got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
                    pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
        snap = pod.router.metrics_snapshot()
        assert snap["router_mesh_degraded_total"] == 2
        assert snap["router_co_evals_total"] == 0
        # Forced mode surfaces the same trouble typed instead.
        pod.router.co_eval = "always"
        with faults.inject("mesh.collective"):
            with pytest.raises(MeshUnavailableError):
                pod.router.evaluate(name, xs, timeout=60)
    finally:
        pod.close()


def test_epoch_fence_degrades_until_reformed(dcf, bundles, prg, rng):
    """A membership commit after formation fences the group: dispatch
    degrades (counted + warned) until ``set_mesh`` re-forms it at the
    new epoch — a scatter can never ride a stale worker set."""
    pod = _mesh_pod(dcf, bundles)
    try:
        name, kb = sorted(bundles.items())[0]
        xs = rng.integers(0, 256, (96, NB), dtype=np.uint8)
        pod.router.set_ring(pod.map, epoch=pod.router.ring_epoch + 1)
        with pytest.warns(BackendFallbackWarning):
            got = pod.router.evaluate(name, xs, b=0, timeout=60)
        assert np.array_equal(
            got ^ pod.router.evaluate(name, xs, b=1, timeout=60),
            recon_oracle(prg, kb, xs))
        assert pod.router.metrics_snapshot()[
            "router_mesh_degraded_total"] >= 1
        pod.router.set_mesh()  # re-formed at the current epoch
        pod.router.evaluate(name, xs, timeout=60)
        assert pod.router.metrics_snapshot()[
            "router_co_evals_total"] >= 1
    finally:
        pod.close()


def test_clear_mesh_returns_to_route_only(dcf, bundles, rng):
    pod = _mesh_pod(dcf, bundles)
    try:
        name = sorted(bundles)[0]
        xs = rng.integers(0, 256, (96, NB), dtype=np.uint8)
        pod.router.clear_mesh()
        pod.router.evaluate(name, xs, timeout=60)  # routed, no co-eval
        snap = pod.router.metrics_snapshot()
        assert snap["router_co_evals_total"] == 0
        assert snap["router_mesh_workers"] == 0
    finally:
        pod.close()


def test_dead_worker_scatter_degrades_zero_lost_keys(
        dcf, bundles, prg, rng):
    """A mesh worker already dead at scatter time: the dispatch
    degrades (worker marked suspect, counted, warned) and EVERY key
    still answers bit-exact — zero lost keys."""
    pod = _mesh_pod(dcf, bundles)
    try:
        # Kill a worker that is neither owner nor replica of the probe
        # key, so the degraded route walk stays on trusted hosts.
        name, kb = sorted(bundles.items())[0]
        placed = pod.map.placement_ids(name, replicas=1)
        victim = next(h for h in pod.map.host_ids()
                      if h not in placed)
        pod.kill(victim)
        xs = rng.integers(0, 256, (96, NB), dtype=np.uint8)
        with pytest.warns(BackendFallbackWarning):
            got = pod.router.evaluate(name, xs, b=0, timeout=60) ^ \
                pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got, recon_oracle(prg, kb, xs))
        snap = pod.router.metrics_snapshot()
        assert snap["router_mesh_degraded_total"] == 2
        assert snap[f"router_suspected_total{{shard={victim}}}"] >= 1
        # Zero lost keys: every registered key still answers (small
        # batches — route-mode — avoiding the dead worker's ownership
        # where a replica exists).
        for kname, kkb in sorted(bundles.items()):
            if victim not in pod.map.placement_ids(kname, replicas=1):
                xs2 = rng.integers(0, 256, (4, NB), dtype=np.uint8)
                got2 = pod.router.evaluate(kname, xs2, b=0,
                                           timeout=60) ^ \
                    pod.router.evaluate(kname, xs2, b=1, timeout=60)
                assert np.array_equal(got2,
                                      recon_oracle(prg, kkb, xs2))
    finally:
        pod.close()


# ----------------------------------------------- the mid-batch soak


@pytest.mark.slow
def test_kill_mesh_worker_mid_batch_soak(dcf, bundles, prg, rng):
    """The acceptance soak: a mesh worker dies MID-BATCH — after its
    slice was scattered, before its share came back.  The gather
    degrades the WHOLE batch to route-mode (typed signal, counted,
    warned), the answer stays bit-exact, and afterwards every key
    still serves with no generation regression — zero lost keys."""
    # A custom pod: big max_batch + a long coalesce delay give a
    # deterministic window in which the victim holds its slice
    # un-evaluated while we kill it.
    svcs, servers, specs = [], [], []
    for i in range(3):
        svc = dcf.serve(max_batch=4096, max_delay_ms=300.0)
        svc.start()
        srv = EdgeServer(svc).start()
        svcs.append(svc)
        servers.append(srv)
        specs.append(ShardSpec(f"shard-{i}", *srv.address))
    ring = ShardMap(specs)
    index = {s.host_id: i for i, s in enumerate(specs)}
    from dcf_tpu.serve import DcfRouter

    router = DcfRouter(ring, n_bytes=NB, co_eval="auto",
                       co_eval_min_points=64)
    try:
        for name, kb in sorted(bundles.items()):
            for spec in ring.placement(name, replicas=1):
                svcs[index[spec.host_id]].register_key(name, kb)
        router.set_mesh()
        for name, kb in sorted(bundles.items()):
            router.register_mesh_key(name, kb)
        gens_before = {
            name: svcs[index[ring.owner(name).host_id]]
            .registry.digest()[name]
            for name in sorted(bundles)}
        name, kb = sorted(bundles.items())[0]
        placed = ring.placement_ids(name, replicas=1)
        victim = next(h for h in ring.host_ids() if h not in placed)
        xs = rng.integers(0, 256, (96, NB), dtype=np.uint8)
        fut = router.submit(name, xs, b=0)  # scattered: 3 slices
        # Kill the victim inside the coalesce window — its slice is
        # accepted but unanswered; the pending share future dies with
        # the connection.
        servers[index[victim]].close()
        svcs[index[victim]].close(drain=False)
        with pytest.warns(BackendFallbackWarning):
            got0 = fut.result(60)
        got1 = router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(got0 ^ got1, recon_oracle(prg, kb, xs))
        snap = router.metrics_snapshot()
        assert snap["router_mesh_degraded_total"] >= 1
        assert snap[f"router_suspected_total{{shard={victim}}}"] >= 1
        # Zero lost keys, zero generation regressions: every key whose
        # placement survives the victim still answers bit-exact, at a
        # generation no older than before the kill.
        for kname, kkb in sorted(bundles.items()):
            if victim in ring.placement_ids(kname, replicas=1):
                continue
            xs2 = rng.integers(0, 256, (8, NB), dtype=np.uint8)
            got = router.evaluate(kname, xs2, b=0, timeout=60) ^ \
                router.evaluate(kname, xs2, b=1, timeout=60)
            assert np.array_equal(got, recon_oracle(prg, kkb, xs2))
            gen_now = svcs[index[ring.owner(kname).host_id]] \
                .registry.digest()[kname]
            assert gen_now >= gens_before[kname], kname
    finally:
        router.close()
        for srv in servers:
            srv.close()
        for svc in svcs:
            try:
                svc.close(drain=False)
            except Exception:  # fallback-ok: best-effort teardown of
                # the killed shard
                pass


# ------------------------------------------- pod-wide registration


def test_register_mesh_key_resident_everywhere(dcf, bundles):
    """``register_mesh_key`` makes the key resident on EVERY worker —
    including those outside its ring placement — at ONE generation."""
    pod = _mesh_pod(dcf, bundles)
    try:
        name = sorted(bundles)[0]
        gens = {h: pod.svc_of(h).registry.digest()[name]
                for h in pod.map.host_ids()}
        assert len(set(gens.values())) == 1, gens
        assert pod.router.metrics_snapshot()[
            "router_mesh_registered_total"] == len(bundles)
        # Without a group, mesh registration refuses typed.
        pod.router.clear_mesh()
        with pytest.raises(MeshUnavailableError):
            pod.router.register_mesh_key(name, bundles[name])
    finally:
        pod.close()


def test_mesh_future_is_threadsafe_waitable(dcf, bundles, prg, rng):
    """Gathers from a different thread than the submitter (the edge
    writer's pattern) — no thread affinity in the mesh future."""
    pod = _mesh_pod(dcf, bundles)
    try:
        name, kb = sorted(bundles.items())[0]
        xs = rng.integers(0, 256, (96, NB), dtype=np.uint8)
        fut = pod.router.submit(name, xs, b=0)
        out = {}

        def waiter():
            out["y"] = fut.result(60)

        t = threading.Thread(target=waiter)
        t.start()
        t.join(60)
        assert not t.is_alive()
        y1 = pod.router.evaluate(name, xs, b=1, timeout=60)
        assert np.array_equal(out["y"] ^ y1, recon_oracle(prg, kb, xs))
    finally:
        pod.close()
