"""CLI: ``python -m tools.dcflint [paths...] [--format F] [--pass NAME]``.

Exit 0 when every scanned file is clean, 1 when violations survive
suppression, 2 on usage errors.  ``--format json`` emits a
machine-readable report, ``--format sarif`` a SARIF 2.1.0 report for
CI code-scanning upload; the default (human) output is one
``path:line: [pass] message`` line per finding (clickable in editors
and CI logs).  ``--changed-only REF`` narrows the scan to the files
``git diff --name-only REF`` reports — a PR fast path only; it can
miss violations a change causes in UNCHANGED files (wire-taxonomy-sync
spans errors.py/edge.py), so CI pairs it with an unconditional full
sweep.
"""

from __future__ import annotations

import argparse
import pathlib
import subprocess
import sys

from tools.dcflint import (
    all_passes,
    render_human,
    render_json,
    render_sarif,
    run_path,
)


def _changed_files(ref: str) -> set[pathlib.Path]:
    """Resolved paths of the ``*.py`` files differing from ``ref``
    (committed, staged, and working-tree changes alike)."""
    proc = subprocess.run(
        ["git", "diff", "--name-only", ref, "--"],
        capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"git diff --name-only {ref} failed: "
            f"{proc.stderr.strip() or proc.stdout.strip()}")
    out = set()
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.endswith(".py"):
            out.add(pathlib.Path(line).resolve())
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.dcflint",
        description="Repo static-analysis suite (see tools/dcflint).")
    p.add_argument("paths", nargs="*", default=["dcf_tpu"],
                   help="package directories or files to scan "
                        "(default: dcf_tpu)")
    p.add_argument("--format", dest="format", default=None,
                   choices=["human", "json", "sarif"],
                   help="report format (default: human)")
    p.add_argument("--json", action="store_true",
                   help="alias for --format json (back-compat)")
    p.add_argument("--output", metavar="FILE", default=None,
                   help="write the report to FILE instead of stdout")
    p.add_argument("--changed-only", metavar="REF", default=None,
                   help="scan only *.py files that differ from git REF "
                        "(fast path; pair with a full sweep in CI)")
    p.add_argument("--pass", dest="passes", action="append", default=None,
                   metavar="NAME",
                   help="run only the named pass (repeatable)")
    p.add_argument("--list-passes", action="store_true",
                   help="list registered passes and exit")
    args = p.parse_args(argv)

    if args.format is not None and args.json and args.format != "json":
        print("error: --json conflicts with "
              f"--format {args.format}", file=sys.stderr)
        return 2
    fmt = args.format or ("json" if args.json else "human")

    if args.list_passes:
        for name, inst in sorted(all_passes().items()):
            print(f"{name}: {inst.description}")
        return 0

    only = None
    if args.changed_only is not None:
        try:
            only = _changed_files(args.changed_only)
        except RuntimeError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    violations = []
    for raw in args.paths or ["dcf_tpu"]:
        root = pathlib.Path(raw)
        if not root.exists():
            print(f"error: no such path {raw!r}", file=sys.stderr)
            return 2
        try:
            violations += run_path(root, args.passes, only=only)
        except KeyError as e:
            print(f"error: {e.args[0]}", file=sys.stderr)
            return 2
    label = ", ".join(str(p) for p in args.paths)
    render = {"human": render_human,
              "json": render_json,
              "sarif": render_sarif}[fmt]
    report = render(violations, label)
    if args.output:
        pathlib.Path(args.output).write_text(report + "\n")
        if fmt == "human" and violations:
            # Keep failures visible in the CI log even when the report
            # goes to a file.
            print(report, file=sys.stderr)
    else:
        print(report)
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
