"""CLI: ``python -m tools.dcflint [paths...] [--json] [--pass NAME]``.

Exit 0 when every scanned file is clean, 1 when violations survive
suppression, 2 on usage errors.  ``--json`` emits a machine-readable
report for CI annotation; the default output is one ``path:line:
[pass] message`` line per finding (clickable in editors and CI logs).
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from tools.dcflint import all_passes, render_human, render_json, run_path


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.dcflint",
        description="Repo static-analysis suite (see tools/dcflint).")
    p.add_argument("paths", nargs="*", default=["dcf_tpu"],
                   help="package directories or files to scan "
                        "(default: dcf_tpu)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--pass", dest="passes", action="append", default=None,
                   metavar="NAME",
                   help="run only the named pass (repeatable)")
    p.add_argument("--list-passes", action="store_true",
                   help="list registered passes and exit")
    args = p.parse_args(argv)

    if args.list_passes:
        for name, inst in sorted(all_passes().items()):
            print(f"{name}: {inst.description}")
        return 0

    violations = []
    for raw in args.paths or ["dcf_tpu"]:
        root = pathlib.Path(raw)
        if not root.exists():
            print(f"error: no such path {raw!r}", file=sys.stderr)
            return 2
        try:
            violations += run_path(root, args.passes)
        except KeyError as e:
            print(f"error: {e.args[0]}", file=sys.stderr)
            return 2
    label = ", ".join(str(p) for p in args.paths)
    if args.json:
        print(render_json(violations, label))
    else:
        print(render_human(violations, label))
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
