"""dcflint — the repo's static-analysis suite.

The crate's value proposition is *bit-exact* two-party DCF evaluation: a
silently-wrong share is worse than a crash, so the invariants that
guarantee parity must hold in every file, not just the ones a reviewer
happened to read.  dcflint machine-enforces them as small AST passes over
a shared file walk:

    compat-shim         version-skew-renamed jax APIs only via _compat.py
    exception-hygiene   no unmarked blanket ``except`` handlers
    crypto-dtype        integer-only math on the key/CW/value paths
    typed-error         every raise is a DcfError / NotImplementedError /
                        marked API-edge ValueError-TypeError
    secret-hygiene      key material never reaches print/logging; key
                        classes define a redacting __repr__
    determinism         no wall-clock/unseeded randomness in library code
    guarded-by          ``# guarded-by:`` annotated attributes touched
                        only under their lock (or ``# holds-lock:``)
    blocking-under-lock no socket/subprocess/sleep/untimed-wait inside
                        a ``with <lock>:`` body
    wire-taxonomy-sync  errors.py taxonomy, edge.py wire codes, and the
                        typed-error DCF_ERRORS list mutually exhaustive

Each pass is a ``LintPass`` subclass registered by module import (see
``tools/dcflint/passes/``); the framework owns the file walk, the
suppression grammar, and the output/exit-code contract.

Suppressing a finding
---------------------

A violation line may carry::

    # dcflint: disable=<pass>[,<pass>] <reason>

on the flagged line itself or on a standalone comment line directly
above it.  The reason is mandatory — an allowance nobody can justify in
the diff that introduces it is not an allowance.  Two passes also accept
purpose-built markers that double as documentation: ``# fallback-ok:
<reason>`` (exception-hygiene, the pre-dcflint spelling) and
``# api-edge: <reason>`` (typed-error: a ValueError/TypeError that is
the documented constructor/argument contract at the public API edge).

Exit codes: 0 clean, 1 violations found, 2 usage/internal error.
Run ``python -m tools.dcflint --help`` for the CLI.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
from dataclasses import asdict, dataclass
from typing import Iterable, Iterator

__all__ = [
    "Violation",
    "FileContext",
    "LintPass",
    "register",
    "all_passes",
    "run_path",
    "render_human",
    "render_json",
    "render_sarif",
]

_SUPPRESS_RE = re.compile(
    r"#\s*dcflint:\s*disable=([A-Za-z0-9_,-]+)(.*)$")


@dataclass(frozen=True)
class Violation:
    """One finding: ``path:line: [pass] message``."""

    path: str
    line: int
    pass_name: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_name}] {self.message}"


class LintPass:
    """One named invariant.  Subclasses set ``name``/``description`` and
    implement ``check(ctx)`` yielding ``(lineno, message)`` pairs; the
    framework applies suppressions and builds ``Violation`` records."""

    name: str = ""
    description: str = ""

    def check(self, ctx: "FileContext") -> Iterator[tuple[int, str]]:
        raise NotImplementedError


_REGISTRY: dict[str, LintPass] = {}


def register(cls: type) -> type:
    """Class decorator: instantiate the pass and add it to the registry."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"pass {cls.__name__} has no name")
    _REGISTRY[inst.name] = inst
    return cls


def all_passes() -> dict[str, LintPass]:
    """name -> pass instance, importing the pass modules on first use."""
    from tools.dcflint import passes  # noqa: F401  (registers on import)

    return dict(_REGISTRY)


class FileContext:
    """One parsed file: source, lines, AST, and its suppression table.

    ``relpath`` is the path relative to the scanned root with ``/``
    separators — passes use it for scoping (e.g. crypto-dtype applies
    under ``ops/`` and ``backends/`` only), so fixtures replicate scoping
    by directory layout, not by repo-absolute paths.
    """

    def __init__(self, path: pathlib.Path, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        # lineno -> set of disabled pass names for that line
        self.suppressions: dict[int, set[str]] = {}
        self.suppression_errors: list[tuple[int, str]] = []
        self._parse_suppressions()

    @property
    def parts(self) -> tuple[str, ...]:
        return tuple(self.relpath.split("/"))

    @property
    def basename(self) -> str:
        return self.parts[-1]

    def _parse_suppressions(self) -> None:
        known = set(_REGISTRY)
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            names = {n.strip() for n in m.group(1).split(",") if n.strip()}
            reason = m.group(2).strip()
            if not reason:
                self.suppression_errors.append(
                    (i, "suppression without a reason: write "
                        "'# dcflint: disable=<pass> <why this is OK>'"))
                continue
            unknown = names - known if known else set()
            if unknown:
                self.suppression_errors.append(
                    (i, f"suppression names unknown pass(es) "
                        f"{sorted(unknown)}; known: {sorted(known)}"))
                names -= unknown
            self.suppressions.setdefault(i, set()).update(names)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, lineno: int, pass_name: str) -> bool:
        """A finding is suppressed by a disable comment on its own line or
        anywhere in the contiguous standalone-comment block directly above
        it (multi-line justifications are encouraged)."""
        if pass_name in self.suppressions.get(lineno, ()):
            return True
        i = lineno - 1
        while i >= 1 and self.line_text(i).strip().startswith("#"):
            if pass_name in self.suppressions.get(i, ()):
                return True
            i -= 1
        return False


def _iter_files(root: pathlib.Path) -> Iterator[pathlib.Path]:
    if root.is_file():
        yield root
        return
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        yield path


def run_path(root: str | pathlib.Path,
             pass_names: Iterable[str] | None = None,
             only: Iterable[str | pathlib.Path] | None = None,
             ) -> list[Violation]:
    """Run the suite (or the named subset) over every ``*.py`` under
    ``root``; returns the surviving (unsuppressed) violations.

    ``only``: an optional file filter — when given, only files whose
    resolved path is in the set are scanned (the ``--changed-only``
    mode: the CLI passes ``git diff --name-only`` output).  It narrows
    the walk, never widens it, so a violation OUTSIDE the filter is
    deliberately invisible to a filtered run — which is why CI keeps
    an unconditional full sweep next to the changed-only fast path.
    """
    registry = all_passes()
    if pass_names is None:
        selected = list(registry.values())
    else:
        unknown = sorted(set(pass_names) - set(registry))
        if unknown:
            raise KeyError(
                f"unknown pass(es) {unknown}; known: {sorted(registry)}")
        selected = [registry[n] for n in pass_names]
    root = pathlib.Path(root)
    only_set = (None if only is None
                else {pathlib.Path(p).resolve() for p in only})
    out: list[Violation] = []
    for path in _iter_files(root):
        if only_set is not None and path.resolve() not in only_set:
            continue
        # Single-file mode keeps the path's own directory segments so the
        # directory-scoped rules (ops//backends/ inclusion, testing/ and
        # bench-layer exemptions) behave exactly as in a directory scan —
        # a bare filename would silently change which passes apply.
        rel = (root.as_posix() if root.is_file()
               else path.relative_to(root).as_posix())
        try:
            ctx = FileContext(path, rel, path.read_text())
        except SyntaxError as e:
            out.append(Violation(str(path), e.lineno or 0, "parse",
                                 f"does not parse: {e.msg}"))
            continue
        # Malformed suppressions are findings themselves (and are not
        # suppressible — a broken allowance must not hide itself).
        for lineno, msg in ctx.suppression_errors:
            out.append(Violation(str(path), lineno, "suppression", msg))
        for p in selected:
            for lineno, msg in p.check(ctx):
                if not ctx.suppressed(lineno, p.name):
                    out.append(Violation(str(path), lineno, p.name, msg))
    out.sort(key=lambda v: (v.path, v.line, v.pass_name))
    return out


def render_human(violations: list[Violation], root: str) -> str:
    lines = [str(v) for v in violations]
    if violations:
        per = {}
        for v in violations:
            per[v.pass_name] = per.get(v.pass_name, 0) + 1
        summary = ", ".join(f"{n}: {c}" for n, c in sorted(per.items()))
        lines.append(f"\n{len(violations)} violation(s) under {root} "
                     f"({summary})")
    else:
        lines.append(f"dcflint OK under {root} "
                     f"({len(all_passes())} passes)")
    return "\n".join(lines)


def render_json(violations: list[Violation], root: str) -> str:
    return json.dumps(
        {"root": str(root),
         "passes": sorted(all_passes()),
         "count": len(violations),
         "violations": [asdict(v) for v in violations]},
        indent=2)


def render_sarif(violations: list[Violation], root: str) -> str:
    """SARIF 2.1.0 report — the format CI code-scanning uploads speak,
    so findings annotate the PR diff instead of hiding in a log.  One
    rule per registered pass (violations reference rules by index),
    one result per finding; parse/suppression findings get synthetic
    rules so they annotate too."""
    passes = all_passes()
    rule_ids = sorted(passes) + ["parse", "suppression"]
    rules = []
    for rid in rule_ids:
        desc = (passes[rid].description if rid in passes else
                "file does not parse" if rid == "parse" else
                "malformed dcflint suppression comment")
        rules.append({
            "id": rid,
            "shortDescription": {"text": desc},
            "defaultConfiguration": {"level": "error"},
        })
    index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for v in violations:
        results.append({
            "ruleId": v.pass_name,
            "ruleIndex": index.get(v.pass_name, 0),
            "level": "error",
            "message": {"text": v.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": pathlib.Path(v.path).as_posix(),
                        "uriBaseId": "SRCROOT",
                    },
                    # SARIF regions are 1-based; parse errors with no
                    # line report the top of the file.
                    "region": {"startLine": max(1, v.line)},
                },
            }],
        })
    return json.dumps({
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "dcflint",
                "informationUri":
                    "https://example.invalid/tools/dcflint",
                "rules": rules,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": f"{root}/"}},
            "results": results,
        }],
    }, indent=2)
