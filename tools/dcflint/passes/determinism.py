"""determinism: no wall-clock or unseeded randomness in library code.

Parity across ten backends only holds if control flow and data are a
pure function of the inputs; a ``time.time()`` branch or an unseeded RNG
in the library means two runs of the "same" evaluation can diverge —
unreproducible by construction, and in a two-party protocol an
unreproducible share is an undebuggable one.  Flags:

* ``time.time/time_ns/monotonic*/perf_counter*`` calls (timing belongs
  in the bench layer);
* any stdlib ``random.*`` call (module-level global RNG, process-seeded);
* numpy legacy global RNG calls (``np.random.rand/randint/seed/...``)
  and unseeded ``np.random.default_rng()`` — seeded ``default_rng(x)``
  and ``Generator`` objects passed by the caller are fine.

Exempt: ``cli.py`` and ``utils/benchtime.py`` (the bench layer is
*about* wall time), ``testing/`` (test scaffolding), and
``benchmarks/`` (round 6 — the measurement harnesses joined the lint
run for the OTHER five passes; wall-clock reads and seeded workload
generation are their whole job).  Intentional entropy — fresh key
seeds MUST be unpredictable — is exactly what the
suppression-with-reason mechanism is for.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.dcflint import FileContext, LintPass, register

_TIME_FUNCS = ("time", "time_ns", "monotonic", "monotonic_ns",
               "perf_counter", "perf_counter_ns")
_NP_LEGACY = ("rand", "randn", "randint", "random", "random_sample",
              "ranf", "sample", "seed", "choice", "shuffle", "permutation",
              "bytes", "uniform", "normal", "standard_normal", "integers")
_EXEMPT_FILES = ("cli.py", "benchtime.py")
_EXEMPT_DIRS = ("testing", "benchmarks")


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@register
class DeterminismPass(LintPass):
    name = "determinism"
    description = ("no time.time()/unseeded random/np.random in library "
                   "code (cli.py, utils/benchtime.py, testing/ exempt)")

    def check(self, ctx: FileContext) -> Iterator[tuple[int, str]]:
        # Scoping checks the scan-relative parts AND the scanned root's
        # own directory name: ``python -m tools.dcflint benchmarks``
        # hands files whose relpath no longer contains the root dir name
        # (relpath is relative to the scanned root).  Only that one
        # on-disk component is consulted — matching arbitrary ancestors
        # (ctx.path.parts) would silently disable the pass for a repo
        # that happens to live under a dir named "benchmarks"/"testing".
        root_parts = ctx.path.parts[:len(ctx.path.parts) - len(ctx.parts)]
        scan_root = root_parts[-1] if root_parts else ""
        if ctx.basename in _EXEMPT_FILES \
                or scan_root in _EXEMPT_DIRS \
                or any(d in ctx.parts[:-1] for d in _EXEMPT_DIRS):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted.startswith("time.") \
                    and dotted.split(".", 1)[1] in _TIME_FUNCS:
                yield (node.lineno,
                       f"{dotted}() in library code: wall-clock reads "
                       "belong in the bench layer (cli.py / "
                       "utils/benchtime.py)")
            elif dotted.startswith("random."):
                yield (node.lineno,
                       f"{dotted}() uses the process-seeded stdlib "
                       "global RNG: take an np.random.Generator from "
                       "the caller instead")
            elif dotted in ("np.random.default_rng",
                            "numpy.random.default_rng"):
                if not node.args and not node.keywords:
                    yield (node.lineno,
                           "unseeded np.random.default_rng() in library "
                           "code: take the rng (or an explicit seed) "
                           "from the caller so runs are reproducible")
            elif dotted.startswith(("np.random.", "numpy.random.")) \
                    and dotted.rsplit(".", 1)[1] in _NP_LEGACY:
                yield (node.lineno,
                       f"{dotted}() is the numpy legacy global RNG "
                       "(process-wide hidden state): use an "
                       "np.random.Generator passed by the caller")
