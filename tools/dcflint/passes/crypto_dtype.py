"""crypto-dtype: integer-only math on the key/CW/value paths.

Scope: files under ``ops/`` and ``backends/`` — the modules that touch
seeds, correction words and value shares — plus the fixed-point gate
pair (ISSUE 20): ``protocols/fixedpoint.py`` and
``workloads/gates.py``, where additive shares are ARITHMETIC and a
float is the likeliest way for a rounding step to corrupt one (the
dealer's sigma table is scalar ``math`` rounded to int before any
ndarray exists, so the rule holds there too).  Two rules:

1. No float dtypes.  The GGM walk, the PRG and the CW algebra are
   GF(2)/integer math; a float anywhere on those paths means a rounding
   step crept in, and a rounded share is a silently-wrong share.
2. No dtype-less ``jnp.zeros/ones/arange/array/empty/full``.  Without an
   explicit dtype these pick up jax's weak-type/promotion defaults,
   which vary with ``jax_enable_x64`` and version — the result can be a
   promoted intermediate that truncates differently across platforms.
   Parity demands the dtype be written down.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.dcflint import FileContext, LintPass, register

_SCOPE_DIRS = ("ops", "backends")
# The fixed-point gate pair (ISSUE 20): (containing dir, file name).
_SCOPE_FILES = (("protocols", "fixedpoint.py"), ("workloads", "gates.py"))
_JNP_NAMES = ("jnp", "jax.numpy")
_FLOAT_ATTRS = ("float16", "float32", "float64", "bfloat16", "float_",
                "double", "half")
# dtype parameter position (0-based) per constructor: a call with fewer
# positional args and no dtype= keyword is dtype-less.
_CTOR_DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "array": 1,
                   "full": 2, "arange": 3}


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@register
class CryptoDtypePass(LintPass):
    name = "crypto-dtype"
    description = ("no float dtypes or dtype-less jnp constructors in "
                   "ops/ and backends/")

    def check(self, ctx: FileContext) -> Iterator[tuple[int, str]]:
        in_scope = any(d in ctx.parts[:-1] for d in _SCOPE_DIRS) \
            or any(d in ctx.parts[:-1] and ctx.parts[-1] == f
                   for d, f in _SCOPE_FILES)
        if not in_scope:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr in _FLOAT_ATTRS \
                    and _dotted(node.value) in ("np", "numpy", *_JNP_NAMES):
                yield (node.lineno,
                       f"float dtype {_dotted(node)} on a crypto path: "
                       "the key/CW/value math is integer-only "
                       "(a rounded share is a silently-wrong share)")
            elif isinstance(node, ast.Call):
                func = node.func
                if not isinstance(func, ast.Attribute):
                    continue
                pos = _CTOR_DTYPE_POS.get(func.attr)
                if pos is None or _dotted(func.value) not in _JNP_NAMES:
                    continue
                if any(kw.arg == "dtype" for kw in node.keywords):
                    continue
                if len(node.args) > pos:
                    continue  # dtype passed positionally
                yield (node.lineno,
                       f"dtype-less jnp.{func.attr}(...) invokes implicit "
                       "promotion/weak-type defaults; write the dtype "
                       "explicitly on key/CW/value paths")
            elif isinstance(node, ast.keyword) and node.arg == "dtype" \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str) \
                    and node.value.value.lstrip("<>=").startswith(
                        ("float", "bfloat", "f2", "f4", "f8")):
                yield (node.value.lineno,
                       f"float dtype string {node.value.value!r} on a "
                       "crypto path: the key/CW/value math is "
                       "integer-only")
