"""typed-error: every raise is typed, so callers can catch the family.

PR 1 introduced the ``dcf_tpu/errors.py`` taxonomy precisely so that no
failure surfaces as an opaque builtin; a raw ``RuntimeError`` bypassing
it silently erodes the ``except DcfError`` contract.  Allowed raises:

* a ``DcfError`` subclass (the taxonomy) or ``NotImplementedError``;
* a bare re-raise (``raise`` / ``raise e`` of a caught name);
* ``ValueError``/``TypeError`` carrying an ``# api-edge: <reason>``
  marker — the documented constructor/argument contract at the public
  API edge, where builtin semantics are what callers expect (the
  taxonomy's ValueError-derived classes cover the rest);
* ``SystemExit`` in ``cli.py`` (argparse-style usage errors);
* ``ForcedVerdict`` (ISSUE 16) — the ``capacity.decide`` seam's
  control-flow exception: raised only inside armed fault handlers and
  consumed by the seam's own except clause, it can never reach an
  ``except DcfError`` caller.

Scope: all of ``dcf_tpu/`` except ``testing/`` (the fault-injection
harness raises its own ``InjectedFault`` by design).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.dcflint import FileContext, LintPass, register

API_EDGE_MARKER = "api-edge"

# The dcf_tpu.errors taxonomy (kept in sync by tests/test_dcflint.py,
# which derives the live list from the module and compares).
DCF_ERRORS = frozenset({
    "DcfError",
    "KeyFormatError",
    "ShapeError",
    "BackendUnavailableError",
    "StaleStateError",
    "NativeBuildError",
    "QueueFullError",
    "DeadlineExceededError",
    "CircuitOpenError",
    "KeyQuarantinedError",
    "BatchTimeoutError",
    "RingEpochError",
    "StandbyExhaustedError",
    "LockOrderError",
    "MeshUnavailableError",
})
_ALWAYS_OK = DCF_ERRORS | {"NotImplementedError", "ForcedVerdict"}
_MARKED_OK = frozenset({"ValueError", "TypeError"})


def _raised_names(exc: ast.AST) -> list[tuple[int, str]]:
    """(lineno, class name) for every exception an exc expression can
    instantiate; unknown constructs yield ('', ...) so they get flagged."""
    if isinstance(exc, ast.IfExp):  # raise A if cond else B
        return _raised_names(exc.body) + _raised_names(exc.orelse)
    if isinstance(exc, ast.Call):
        func = exc.func
        if isinstance(func, ast.Name):
            return [(exc.lineno, func.id)]
        if isinstance(func, ast.Attribute):
            return [(exc.lineno, func.attr)]
        return [(exc.lineno, "")]
    if isinstance(exc, ast.Name):
        # ``raise e``: a re-raise of a bound name — its type was decided
        # (and checked) where it was constructed or caught.
        return []
    if isinstance(exc, ast.Attribute):
        return [(exc.lineno, exc.attr)]
    return [(exc.lineno if hasattr(exc, "lineno") else 0, "")]


def _marked(ctx: FileContext, lineno: int) -> bool:
    """``# api-edge:`` on the flagged line or anywhere in the contiguous
    standalone-comment block directly above it (mirrors the framework's
    suppression placement rules, so multi-line reasons wrap freely)."""
    if f"# {API_EDGE_MARKER}" in ctx.line_text(lineno):
        return True
    i = lineno - 1
    while i >= 1 and ctx.line_text(i).strip().startswith("#"):
        if f"# {API_EDGE_MARKER}" in ctx.line_text(i):
            return True
        i -= 1
    return False


@register
class TypedErrorPass(LintPass):
    name = "typed-error"
    description = ("raises must be DcfError subclasses, "
                   "NotImplementedError, or marked api-edge "
                   "ValueError/TypeError")

    def check(self, ctx: FileContext) -> Iterator[tuple[int, str]]:
        if "testing" in ctx.parts[:-1]:
            return
        is_cli = ctx.basename == "cli.py"
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            for lineno, name in _raised_names(node.exc):
                if name in _ALWAYS_OK:
                    continue
                if name == "SystemExit" and is_cli:
                    continue
                if name in _MARKED_OK:
                    if _marked(ctx, lineno):
                        continue
                    yield (lineno,
                           f"raise {name} without '# {API_EDGE_MARKER}: "
                           "<reason>': either raise the matching "
                           "DcfError subclass (ShapeError/KeyFormatError "
                           "cover most contract violations) or mark the "
                           "site as a documented API edge")
                    continue
                yield (lineno,
                       f"raise {name or 'of a computed expression'} "
                       "bypasses the dcf_tpu.errors taxonomy; raise a "
                       "DcfError subclass so 'except DcfError' callers "
                       "see it")
