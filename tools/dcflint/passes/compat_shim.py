"""compat-shim: version-skew-renamed jax APIs only via the _compat shims.

The exact class of skew that failed 42 seed tier-1 tests: ``shard_map``
moved from ``jax.experimental.shard_map`` into the ``jax`` namespace
(kwarg ``check_rep`` -> ``check_vma`` along the way) and ``pallas.tpu``
renamed ``TPUCompilerParams`` -> ``CompilerParams``.  Exactly two modules
are allowed to touch the raw names and resolve whichever this jax ships:
``dcf_tpu/ops/_compat.py`` and ``dcf_tpu/parallel/_compat.py``.  Every
other file must import the resolved symbol from them, so a future rename
is one shim edit, not an AttributeError scattered over ten backends.

ISSUE 18 adds the multi-process surface to the guarded set:
``jax.distributed`` (its CPU-collectives knob has moved between a
config option and an env var) and ``jax.experimental.multihost_utils``
(the host-local -> global conversion has grown a ``jax``-namespace
sibling spelling) resolve ONLY through
``dcf_tpu.parallel._compat.distributed_initialize`` /
``host_to_global`` — the mesh tier must not re-scatter the skew the
shim exists to contain.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.dcflint import FileContext, LintPass, register

_RENAMED_ATTRS = ("TPUCompilerParams", "CompilerParams")
_SHIM_HINT = ("resolve it through dcf_tpu.ops._compat / "
              "dcf_tpu.parallel._compat instead")
# Multi-process modules (ISSUE 18) whose APIs skew across jax
# versions: any import of / attribute walk into them outside the
# _compat shims is flagged.
_MP_MODULES = ("jax.distributed", "jax.experimental.multihost_utils")
_MP_HINT = ("use dcf_tpu.parallel._compat (distributed_initialize / "
            "host_to_global), which resolves the skew")


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name for Name/Attribute chains ('' otherwise)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@register
class CompatShimPass(LintPass):
    name = "compat-shim"
    description = ("skew-renamed jax APIs (shard_map location/kwarg, "
                   "pallas CompilerParams, jax.distributed/"
                   "multihost_utils) only inside _compat.py shims")

    def check(self, ctx: FileContext) -> Iterator[tuple[int, str]]:
        if ctx.basename == "_compat.py":
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module.startswith("jax.experimental.shard_map"):
                    yield (node.lineno,
                           "direct import from jax.experimental.shard_map "
                           "(moved across jax versions); " + _SHIM_HINT)
                elif node.module in ("jax", "jax.experimental") and any(
                        a.name == "shard_map" for a in node.names):
                    yield (node.lineno,
                           f"direct import of {node.module}.shard_map "
                           "(location moved across jax versions); "
                           + _SHIM_HINT)
                if any(node.module == m or node.module.startswith(m + ".")
                       for m in _MP_MODULES):
                    yield (node.lineno,
                           f"direct import from {node.module} (multi-"
                           "process API, skews across jax versions); "
                           + _MP_HINT)
                elif node.module in ("jax", "jax.experimental"):
                    for a in node.names:
                        if a.name in ("distributed", "multihost_utils"):
                            yield (node.lineno,
                                   f"direct import of {node.module}."
                                   f"{a.name} (multi-process API, skews "
                                   "across jax versions); " + _MP_HINT)
                if node.module.split(".")[0] == "jax":
                    # importing the resolved name FROM a _compat shim is
                    # the sanctioned pattern; only raw jax imports skew
                    for a in node.names:
                        if a.name in _RENAMED_ATTRS:
                            yield (node.lineno,
                                   f"direct import of {a.name} from "
                                   f"{node.module} (renamed "
                                   "TPUCompilerParams -> CompilerParams "
                                   "across jax versions); " + _SHIM_HINT)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith("jax.experimental.shard_map"):
                        yield (node.lineno,
                               "direct import of jax.experimental."
                               "shard_map; " + _SHIM_HINT)
                    elif any(a.name == m or a.name.startswith(m + ".")
                             for m in _MP_MODULES):
                        yield (node.lineno,
                               f"direct import of {a.name} (multi-"
                               "process API, skews across jax versions); "
                               + _MP_HINT)
            elif isinstance(node, ast.Attribute):
                dotted = _dotted(node)
                if dotted in ("jax.shard_map",
                              "jax.experimental.shard_map"):
                    yield (node.lineno,
                           f"direct use of {dotted} (location moved "
                           "across jax versions); " + _SHIM_HINT)
                elif any(dotted == m or dotted.startswith(m + ".")
                         for m in _MP_MODULES):
                    yield (node.lineno,
                           f"direct use of {dotted} (multi-process API, "
                           "skews across jax versions); " + _MP_HINT)
                elif node.attr in _RENAMED_ATTRS:
                    yield (node.lineno,
                           f"direct use of .{node.attr} (renamed "
                           "TPUCompilerParams -> CompilerParams across "
                           "jax versions); " + _SHIM_HINT)
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == "check_rep":
                        yield (node.lineno,
                               "check_rep= is the pre-rename spelling of "
                               "check_vma=; call the _compat shard_map "
                               "wrapper, which translates")
