"""blocking-under-lock: locks bound critical sections, never I/O.

The PR 11 ``_mint_sync`` review round established the rule this pass
generalizes: a lock protects shared STATE, and everything slow —
network, subprocesses, sleeps, unbounded waits — happens outside it,
or every other thread contending for that lock inherits the latency
(and, for the serve tier's pump/admission locks, the pod inherits a
convoy).  Inside any ``with <lock>:`` body — a with-subject whose
final name segment is ``lock``/``mutex``/``cond``/``condition`` or
ends in ``_lock``/``_mutex`` — the pass flags:

* ``socket`` traffic: any ``.connect/.accept/.send*/.recv*`` method
  call, and ``socket.create_connection(...)``;
* ``subprocess.*`` calls (build/exec under a lock serializes the
  world on an external process);
* ``<x>.wait()`` with no timeout (``Event.wait``/``Condition.wait``
  — an unbounded wait under a lock is a deadlock with extra steps;
  pass a timeout and re-check the predicate);
* ``<x>.join()`` with no arguments (``Thread.join`` — same reason;
  ``str.join``/``os.path.join`` always take an argument and are not
  flagged);
* ``time.sleep(...)`` (the PR 11 rule verbatim).

The analysis is lexical: nested ``def``/``lambda`` bodies are NOT
treated as inside the ``with`` (they run later, when the lock is
long released).  Deliberate exceptions — e.g. the edge client's
``_send_lock``, which exists precisely to serialize whole-frame
socket writes — carry the mandatory-reason suppression grammar, so
the justification is in the diff.  ``testing/`` is exempt (the fault
and lock-order harnesses hold locks around arbitrary seams by
design).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.dcflint import FileContext, LintPass, register

_LOCK_SUFFIXES = ("lock", "mutex")
_LOCK_NAMES = frozenset({"lock", "mutex", "cond", "condition"})

_SOCKET_METHODS = frozenset({
    "connect", "connect_ex", "accept",
    "send", "sendall", "sendto", "sendmsg", "sendfile",
    "recv", "recv_into", "recvfrom", "recvfrom_into", "recvmsg",
    "recvmsg_into",
})


def _final_name(node: ast.AST) -> str:
    """The last dotted segment of a name/attribute expression."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_lock_name(name: str) -> bool:
    name = name.lower()
    return (name in _LOCK_NAMES
            or any(name.endswith("_" + s) or name == s
                   for s in _LOCK_SUFFIXES))


def _dotted(node: ast.AST) -> str:
    """Dotted call-target name (``a.b.c``) or '' when not a plain
    name chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if parts:
        # x().attr / subscripted chains: keep the method name so
        # socket-method detection still sees it.
        return "." + ".".join(reversed(parts))
    return ""


def _has_timeout(call: ast.Call) -> bool:
    return bool(call.args) or any(
        kw.arg == "timeout" for kw in call.keywords)


def _flag_call(call: ast.Call) -> str | None:
    dotted = _dotted(call.func)
    if not dotted:
        return None
    head = dotted.split(".", 1)[0]
    last = dotted.rsplit(".", 1)[-1]
    is_method = "." in dotted
    if dotted == "time.sleep":
        return ("time.sleep under a lock stalls every contender; "
                "sleep outside the critical section")
    if head == "subprocess":
        return (f"{dotted}(...) under a lock serializes every "
                "contender on an external process; run it outside "
                "and publish the result under the lock")
    if dotted == "socket.create_connection" \
            or (is_method and last in _SOCKET_METHODS):
        return (f"socket {last}() under a lock holds every contender "
                "hostage to the peer; do the I/O outside and take "
                "the lock only to publish the result")
    if is_method and last == "wait" and not _has_timeout(call):
        return ("wait() with no timeout under a lock is an unbounded "
                "stall (lost wakeup => deadlock); pass a timeout and "
                "re-check the predicate")
    if is_method and last == "join" and not call.args and not any(
            kw.arg == "timeout" for kw in call.keywords):
        return ("join() with no timeout under a lock waits on a "
                "thread that may need this very lock to exit; pass a "
                "timeout (str.join/os.path.join take arguments and "
                "are not flagged)")
    return None


@register
class BlockingUnderLockPass(LintPass):
    name = "blocking-under-lock"
    description = ("no socket/subprocess/untimed-wait/untimed-join/"
                   "sleep calls inside 'with <lock>' bodies")

    def check(self, ctx: FileContext) -> Iterator[tuple[int, str]]:
        if "testing" in ctx.parts[:-1]:
            return

        findings: list[tuple[int, str]] = []

        def visit(node: ast.AST, under: str | None) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # Runs after the with-block exits: not under the lock.
                for child in ast.iter_child_nodes(node):
                    visit(child, None)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                locks = [n for n in
                         (_final_name(i.context_expr)
                          for i in node.items)
                         if _is_lock_name(n)]
                inner = locks[0] if locks else under
                for item in node.items:
                    visit(item, under)
                for child in node.body:
                    visit(child, inner)
                return
            if under is not None and isinstance(node, ast.Call):
                msg = _flag_call(node)
                if msg:
                    findings.append(
                        (node.lineno,
                         f"inside 'with {under}': {msg}"))
            for child in ast.iter_child_nodes(node):
                visit(child, under)

        visit(ctx.tree, None)
        yield from findings
