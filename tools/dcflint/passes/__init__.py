"""Pass modules: importing this package registers every pass.

To add a pass: create a module here defining a ``LintPass`` subclass
decorated with ``@register``, import it below, and give it a
seeded-violation fixture in ``tests/test_dcflint.py`` proving detection
power (a pass nobody has seen fire is a pass nobody can trust).
"""

from tools.dcflint.passes import (  # noqa: F401
    blocking_under_lock,
    compat_shim,
    crypto_dtype,
    determinism,
    exception_hygiene,
    guarded_by,
    secret_hygiene,
    typed_error,
    wire_taxonomy,
)
