"""secret-hygiene: key material never reaches print/logging/metrics, and
key classes redact their __repr__.

In a two-party FSS deployment the seeds and correction words ARE the
security: a seed in a log line hands the other party the function.
Three rules:

1. No ``print``/``logging`` call (including the CLI's ``log`` helper)
   whose argument expression references a name bound to key material —
   ``seed*``, ``s0``/``s0s``, ``cw_*``/``cws``/``cw_np1``, ``bundle``/
   ``kb``/``key_bundle``, ``cipher_keys``, ``combine_masks`` (PR 5: a
   protocol bundle's mask is ``pub*beta`` — the secret function value
   in the clear for wraparound intervals).  The check is name-based and
   deliberately conservative: printing ``bundle.num_keys`` is safe and
   gets a suppression with a reason, which is exactly the audit trail a
   reviewer wants at such a site.
2. (PR 4, the serving layer's observability surface) The same rule for
   METRIC sinks: a recording-method call (``.inc``/``.observe``/
   ``.set``/``.add``/``.labels``) or the serve ``labeled(...)``
   label-builder whose arguments reference key-material names — metric
   label values and observations end up in dashboards and committed
   RESULTS JSONL lines, which are log lines with better formatting.
3. Every class holding key-material fields (dataclass or assignment
   fields matching the same patterns) must define an explicit
   ``__repr__`` — the dataclass default repr prints field values, so a
   stray ``f"{bundle}"`` in a traceback or debug line would leak seed
   and CW bytes.
4. (ISSUE 8, the durable store layer) In ``serve/store.py``, no
   builtin ``open(...)`` call in a write/append/create mode: store
   files hold DCFK frames — key material on disk — and must be
   created through the ``os.open(..., 0o600)`` + fsync atomic-write
   helper, never with the umask-default permissions builtin ``open``
   gives a freshly-created file.  (The name set also knows ``frame``:
   a serialized DCFK frame is the key material it encodes.)
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tools.dcflint import FileContext, LintPass, register

SECRET_NAME_RE = re.compile(
    r"^(seed\w*|s0s?|cw(_\w+)?|cws|key_bundle|bundle|kb|key_material"
    r"|cipher_keys?|combine_masks?|frames?|frame_bytes|key_frame"
    r"|repl(ica)?_frames?|shares?(_\w+)?|t_words?|sel(ection)?_vecs?"
    r"|key_betas?|const_shares?)$")
# ``frame`` (ISSUE 8, dcf_tpu/serve/store.py): a serialized DCFK frame
# is the seeds and correction words it encodes — logging one is
# logging the key.
# ``repl_frame``/``replica_frame`` (ISSUE 13, dcf_tpu/serve/store.py
# ``replicate_to`` + the pod provisioning path): a replication buffer
# is the SAME DCFK frame on its way to another host's store — the
# pod tier must not get a logging loophole by renaming the buffer.
# ``frame_bytes`` (ISSUE 14, dcf_tpu/serve/replicate.py + the DCFE
# REGISTER/SYNC wire path): the live-replication and anti-entropy
# buffers hold serialized DCFK frames — bundle bytes in flight between
# registries are key material under a third name, same rule.
# ``share``/``shares``/``share_*``/``shares_*`` (ISSUE 12,
# dcf_tpu/serve/edge.py): the network edge holds evaluated SHARE bytes
# in wire buffers on their way to a party — one logged share next to
# the other party's is the reconstructed function value, so
# share-named buffers are held to the same sink rule as key material.
# Deliberately NOT ``share\w*``: ``shared``/``shared_image``/
# ``shared_lock`` are ordinary state names, not secrets.
# ``combine_masks`` (PR 5, dcf_tpu/protocols): a protocol bundle's
# per-interval combine mask is ``pub * beta`` — beta in the clear for
# wraparound intervals, i.e. the secret function value itself.
# ``t_word``/``t_words``/``sel_vec``/``selection_vec`` (ISSUE 19,
# dcf_tpu/workloads/pir.py + backends/evalall.py): one party's leaf
# t-bit lane words are its SHARE of the PIR selection vector — logged
# next to the other party's they reconstruct the one-hot at alpha,
# i.e. WHICH record the client asked for.  The query privacy the whole
# 2-server construction exists to provide dies in one log line.
# ``key_betas`` (ISSUE 20, dcf_tpu/protocols/keygen.py): the per-key
# signed payloads of an additive interval bundle — beta up to sign,
# the secret function value.  ``const_share``/``const_shares`` (ISSUE
# 20, dcf_tpu/protocols/fixedpoint.py): the truncation gate's additive
# scalar shares of ``-(r >> f)`` — one share is uniform noise, but the
# PAIR reveals the input mask's high bits, so the sink rule and the
# redacted-repr rule both apply.
_PRINT_FUNCS = ("print", "log", "labeled")
_LOGGING_METHODS = ("debug", "info", "warning", "error", "critical",
                    "exception", "log")
_METRIC_METHODS = ("inc", "observe", "set", "add", "labels")


def _secret_refs(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and SECRET_NAME_RE.match(sub.id):
            yield sub.id
        elif isinstance(sub, ast.Attribute) \
                and SECRET_NAME_RE.match(sub.attr):
            yield sub.attr


def _is_sink(func: ast.AST) -> str | None:
    """'print'/'logging.info'/metric-recording calls — anywhere data
    leaves the process as human-readable output."""
    if isinstance(func, ast.Name) and func.id in _PRINT_FUNCS:
        return func.id
    if isinstance(func, ast.Attribute) \
            and func.attr in _LOGGING_METHODS \
            and isinstance(func.value, ast.Name) \
            and ("log" in func.value.id.lower()):
        return f"{func.value.id}.{func.attr}"
    if isinstance(func, ast.Attribute) and func.attr in _METRIC_METHODS:
        # Receiver-agnostic on purpose: serve code holds instruments
        # under arbitrary names (self._c_shed and friends).  Only fires
        # when an ARGUMENT references a key-material name, so ordinary
        # set.add(x)/gauge.set(n) calls never trip it.
        recv = func.value
        recv_name = (recv.attr if isinstance(recv, ast.Attribute)
                     else recv.id if isinstance(recv, ast.Name) else "?")
        return f"{recv_name}.{func.attr}"
    return None


def _is_writing_open(node: ast.Call) -> bool:
    """A builtin ``open(path, mode)`` call whose literal mode creates
    or writes (``w``/``x``/``a``/``+``).  Conservative by design: a
    computed mode is not flagged (suppression-with-reason covers the
    exotic case), and read-mode ``open`` stays legal — restore must
    read frames back."""
    if not (isinstance(node.func, ast.Name) and node.func.id == "open"):
        return False
    mode = node.args[1] if len(node.args) > 1 else None
    if mode is None:
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
    return (isinstance(mode, ast.Constant)
            and isinstance(mode.value, str)
            and any(c in mode.value for c in "wxa+"))


@register
class SecretHygienePass(LintPass):
    name = "secret-hygiene"
    description = ("no key material in print/logging; key classes must "
                   "define a redacting __repr__")

    def check(self, ctx: FileContext) -> Iterator[tuple[int, str]]:
        # Rule 4 scope: the durable store module (serve/store.py) —
        # the one place in the tree where key frames meet a filesystem.
        is_store = (ctx.basename == "store.py"
                    and "serve" in ctx.parts[:-1])
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                if is_store and _is_writing_open(node):
                    yield (node.lineno,
                           "builtin open(...) in a write mode inside "
                           "the store layer: store files hold DCFK "
                           "frames (key material) — create them via "
                           "os.open(..., 0o600) + fsync (the atomic-"
                           "write helper), never with umask-default "
                           "permissions")
                    continue
                sink = _is_sink(node.func)
                if sink is None:
                    continue
                refs = sorted({r for a in (*node.args, *node.keywords)
                               for r in _secret_refs(
                                   a.value if isinstance(a, ast.keyword)
                                   else a)})
                if refs:
                    yield (node.lineno,
                           f"{sink}(...) references key-material "
                           f"name(s) {refs}: a logged seed/CW hands the "
                           "other party the function")
            elif isinstance(node, ast.ClassDef):
                fields = []
                has_repr = False
                for stmt in node.body:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        if stmt.name == "__repr__":
                            has_repr = True
                        continue
                    targets = []
                    if isinstance(stmt, ast.AnnAssign) \
                            and isinstance(stmt.target, ast.Name):
                        targets = [stmt.target.id]
                    elif isinstance(stmt, ast.Assign):
                        targets = [t.id for t in stmt.targets
                                   if isinstance(t, ast.Name)]
                    fields += [t for t in targets
                               if SECRET_NAME_RE.match(t)]
                if fields and not has_repr:
                    yield (node.lineno,
                           f"class {node.name} holds key-material "
                           f"field(s) {sorted(set(fields))} but defines "
                           "no __repr__: the default (dataclass) repr "
                           "prints field values — define one showing "
                           "shapes/geometry, never seed or CW bytes")
