"""wire-taxonomy-sync: errors.py, the edge wire codes, and dcflint's
own DCF_ERRORS list stay mutually exhaustive.

Three artifacts describe the SAME error taxonomy: the class tree in
``dcf_tpu/errors.py``; the wire mapping in ``dcf_tpu/serve/edge.py``
(``E_*`` codes, the decode table ``WIRE_CODES``, the encode table
``_EXC_CODES``, and ``WIRE_INTERNAL_ONLY`` — the explicit list of
taxonomy classes that deliberately cross the wire as ``E_INTERNAL``);
and the ``DCF_ERRORS`` frozenset the typed-error pass enforces raises
against.  Before this pass, one pairing was runtime-tested
(``test_taxonomy_list_in_sync``) and the rest was reviewer memory —
so a new typed error could ship raisable but wire-opaque (every pod
hop collapses it to ``E_INTERNAL``, the router loses the signal it
routes failover on), or a wire code could outlive its class.

This pass proves the triangle statically, using ``DCF_ERRORS`` as the
hub.  On ``errors.py`` (any file of that basename defining
``DcfError``): the ``DcfError``-rooted class closure must equal
``DCF_ERRORS``, both directions.  On ``edge.py`` (any file of that
basename defining ``WIRE_CODES``):

* every ``E_*`` constant is a ``WIRE_CODES`` key, values unique,
  every key an ``E_*`` constant — no orphan codes either way;
* every ``DCF_ERRORS`` class either appears as a ``WIRE_CODES`` value
  or is declared in ``WIRE_INTERNAL_ONLY`` (never both — a class
  cannot be simultaneously coded and internal-only), and
  ``WIRE_INTERNAL_ONLY`` names only taxonomy classes;
* the encode table ``_EXC_CODES`` covers exactly the decode table's
  classes, and each ``(cls, code)`` entry round-trips
  (``WIRE_CODES[code] is cls``) — flavor codes like ``E_EVICTED``/
  ``E_RATE_LIMITED`` are decode-side aliases and exempt from the
  reverse direction.

All checks are AST-level (no imports of the scanned file), so the
pass works on fixtures and fails loudly on the real tree the moment
any corner of the triangle drifts.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.dcflint import FileContext, LintPass, register
from tools.dcflint.passes.typed_error import DCF_ERRORS


def _name_set(node: ast.AST) -> set[str] | None:
    """Names inside ``frozenset({A, B})`` / ``{A, B}`` / ``(A, B)``."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("frozenset", "set") and node.args:
        return _name_set(node.args[0])
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        out = set()
        for elt in node.elts:
            if isinstance(elt, ast.Name):
                out.add(elt.id)
        return out
    return None


def _check_errors_module(ctx: FileContext) -> Iterator[tuple[int, str]]:
    classes: dict[str, ast.ClassDef] = {
        n.name: n for n in ctx.tree.body if isinstance(n, ast.ClassDef)}
    if "DcfError" not in classes:
        return
    # The DcfError-rooted closure, in definition order (bases are
    # defined before subclasses in straight-line Python).
    taxonomy = {"DcfError"}
    for name, node in classes.items():
        bases = {b.id for b in node.bases if isinstance(b, ast.Name)}
        if bases & taxonomy:
            taxonomy.add(name)
    for name in sorted(taxonomy - DCF_ERRORS):
        yield (classes[name].lineno,
               f"taxonomy class {name} is missing from DCF_ERRORS in "
               "tools/dcflint/passes/typed_error.py — the typed-error "
               "pass would reject raising it")
    for name in sorted(DCF_ERRORS - taxonomy):
        yield (1, f"DCF_ERRORS names {name} but this module defines "
                  "no such DcfError subclass — dead entry or missing "
                  "class")


def _check_edge_module(ctx: FileContext) -> Iterator[tuple[int, str]]:
    e_consts: dict[str, tuple[int, int]] = {}  # name -> (value, line)
    wire_codes: ast.Dict | None = None
    wire_line = internal_line = exc_line = 1
    internal_only: set[str] | None = None
    exc_codes: list[tuple[str, str, int]] | None = None
    for node in ctx.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        target = node.targets[0].id
        if target.startswith("E_") and isinstance(node.value,
                                                  ast.Constant) \
                and isinstance(node.value.value, int):
            e_consts[target] = (node.value.value, node.lineno)
        elif target == "WIRE_CODES" and isinstance(node.value, ast.Dict):
            wire_codes, wire_line = node.value, node.lineno
        elif target == "WIRE_INTERNAL_ONLY":
            internal_only = _name_set(node.value)
            internal_line = node.lineno
        elif target == "_EXC_CODES" and isinstance(
                node.value, (ast.Tuple, ast.List)):
            exc_line = node.lineno
            exc_codes = []
            for elt in node.value.elts:
                if isinstance(elt, ast.Tuple) and len(elt.elts) == 2 \
                        and all(isinstance(e, ast.Name)
                                for e in elt.elts):
                    exc_codes.append((elt.elts[0].id, elt.elts[1].id,
                                      elt.lineno))
    if wire_codes is None:
        return

    # -- E_* <-> WIRE_CODES keys ------------------------------------
    key_names: set[str] = set()
    decode: dict[str, str] = {}  # E_ name -> class name
    for key, value in zip(wire_codes.keys, wire_codes.values):
        if not isinstance(key, ast.Name) \
                or key.id not in e_consts:
            yield (getattr(key, "lineno", wire_line),
                   "WIRE_CODES key is not a module-level E_* integer "
                   "constant — codes must be named, documented "
                   "constants")
            continue
        key_names.add(key.id)
        if isinstance(value, ast.Name):
            decode[key.id] = value.id
    for name, (_, lineno) in sorted(e_consts.items()):
        if name not in key_names:
            yield (lineno,
                   f"wire code {name} has no WIRE_CODES entry — the "
                   "client cannot decode it (it would raise "
                   "KeyFormatError on a frame the server legally "
                   "sends)")
    values = [v for v, _ in e_consts.values()]
    if len(values) != len(set(values)):
        dupes = sorted({v for v in values if values.count(v) > 1})
        yield (wire_line,
               f"duplicate E_* code value(s) {dupes}: two names, one "
               "wire byte — the decode table cannot be injective")

    # -- taxonomy coverage ------------------------------------------
    coded = set(decode.values()) & DCF_ERRORS
    if internal_only is None:
        yield (wire_line,
               "edge.py defines no WIRE_INTERNAL_ONLY — declare "
               "(possibly empty) the taxonomy classes that "
               "deliberately cross the wire as E_INTERNAL, so "
               "coverage is a checked decision, not an accident")
        internal_only = set()
    for name in sorted(DCF_ERRORS - coded - internal_only):
        yield (wire_line,
               f"taxonomy class {name} has no wire code and is not "
               "declared in WIRE_INTERNAL_ONLY: a pod hop would "
               "silently collapse it to E_INTERNAL — add a code or "
               "declare the collapse")
    for name in sorted(internal_only & coded):
        yield (internal_line,
               f"{name} is declared WIRE_INTERNAL_ONLY but has a "
               "wire code — it cannot be both; drop one")
    for name in sorted(internal_only - DCF_ERRORS):
        yield (internal_line,
               f"WIRE_INTERNAL_ONLY names {name}, which is not in "
               "the DCF_ERRORS taxonomy")

    # -- encode table <-> decode table ------------------------------
    if exc_codes is not None:
        enc_names = {c for c, _, _ in exc_codes}
        dec_names = set(decode.values())
        for name in sorted(dec_names - enc_names):
            yield (exc_line,
                   f"WIRE_CODES decodes to {name} but _EXC_CODES "
                   "never encodes it — the server would collapse it "
                   "to E_INTERNAL and the code is dead")
        for name in sorted(enc_names - dec_names):
            yield (exc_line,
                   f"_EXC_CODES encodes {name} but no WIRE_CODES "
                   "entry decodes to it")
        for cls, code, lineno in exc_codes:
            if code in decode and decode[code] != cls:
                yield (lineno,
                       f"_EXC_CODES maps {cls} -> {code}, but "
                       f"{code} decodes to {decode[code]} — the "
                       "round trip changes the exception type")


@register
class WireTaxonomySyncPass(LintPass):
    name = "wire-taxonomy-sync"
    description = ("errors.py classes, edge.py E_*/WIRE_CODES/"
                   "WIRE_INTERNAL_ONLY, and DCF_ERRORS stay mutually "
                   "exhaustive")

    def check(self, ctx: FileContext) -> Iterator[tuple[int, str]]:
        if ctx.basename == "errors.py":
            yield from _check_errors_module(ctx)
        elif ctx.basename == "edge.py":
            yield from _check_edge_module(ctx)
