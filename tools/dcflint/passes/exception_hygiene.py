"""exception-hygiene: no unmarked blanket exception handlers.

A blanket handler is a bare ``except:`` or an ``except Exception``/
``except BaseException`` (alone or in a tuple).  Swallowing arbitrary
failures is how a two-party FSS deployment ends up serving
silently-wrong shares; the only legitimate sites are the fallback chain
itself (auto backend canary, native portable degradation, TPU-presence
probes), and each must carry ``# fallback-ok: <reason>`` on the
``except`` line so the allowance is visible in the diff that introduces
it.  This is the PR-1 exception-hygiene gate (originally a standalone
``tools/check_exception_hygiene.py`` script, deleted in PR 4), ported
in as a pass (the standalone script is now a shim over it).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.dcflint import FileContext, LintPass, register

MARKER = "fallback-ok"


def _is_blanket(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in ("Exception", "BaseException"):
            return True
    return False


@register
class ExceptionHygienePass(LintPass):
    name = "exception-hygiene"
    description = ("blanket except handlers must carry "
                   "'# fallback-ok: <reason>'")

    def check(self, ctx: FileContext) -> Iterator[tuple[int, str]]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_blanket(node):
                continue
            line = ctx.line_text(node.lineno)
            if MARKER in line:
                continue
            yield (node.lineno,
                   f"blanket handler ({line.strip()!r}) without "
                   f"'# {MARKER}: <reason>'")
