"""guarded-by: annotated shared state is only touched under its lock.

The serving tier (``dcf_tpu/serve/``) is ~15 threaded modules whose
correctness rests on "attribute X is only read/written under lock L"
contracts that, before this pass, lived in comments and reviewer
memory — and that is exactly where the PR 6/7/11/12 review-round bugs
(unguarded hysteresis timestamps, double-invalidation, a pump-lock
race on worker spawn) kept appearing.  This pass turns the comment
into a checked annotation:

    def __init__(self):
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._standby = []

declares ``self._standby`` guarded by ``self._lock``.  From then on,
every write to ``self._standby`` — and every read outside
``__init__`` — must occur lexically inside a ``with self._lock:``
block, or inside a method whose ``def`` line (or the contiguous
standalone-comment block above it) carries ``# holds-lock: _lock``
(the documented "caller holds the lock" helper idiom, e.g. the
registry's eviction sweep).  Both markers accept a comma-separated
lock list.

The analysis is *lexical* by design: it proves the cheap 95% (the
access sits inside the right ``with``) and leaves the clever 5% —
lock handoffs, benign unlocked fast-path reads, ``__repr__``
diagnostics — to the mandatory-reason suppression grammar, where the
justification is visible in the diff that introduces it.  Code inside
nested ``def``/``lambda`` bodies does NOT inherit the enclosing
``with``: a closure outlives the critical section it was created in
(worker-thread targets being the canonical trap), so it must take the
lock itself or be suppressed with a reason.

Annotation hygiene is checked too: a ``# guarded-by:`` that names no
lock, names a lock attribute never assigned in ``__init__``, or is
not attached to a ``self.<attr> = ...`` statement in ``__init__`` is
itself a finding — a contract that silently fails to bind is worse
than none.  The pass is opt-in per attribute, so it needs no
directory scoping: un-annotated classes are untouched.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tools.dcflint import FileContext, LintPass, register

GUARDED_MARKER = "guarded-by"
HOLDS_MARKER = "holds-lock"

_MARKER_RE = re.compile(r"#\s*(guarded-by|holds-lock):\s*([^#]*)")

_ATTR_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _marker_lines(ctx: FileContext) -> dict[int, tuple[str, str]]:
    """lineno -> (marker kind, raw name list) for every annotation
    comment in the file."""
    out = {}
    for i, line in enumerate(ctx.lines, start=1):
        m = _MARKER_RE.search(line)
        if m:
            out[i] = (m.group(1), m.group(2))
    return out


def _names_at(ctx: FileContext, markers: dict, lineno: int,
              kind: str, consumed: set[int]) -> list[tuple[int, str]]:
    """Annotation names attached to ``lineno``: markers of ``kind`` on
    the line itself or anywhere in the contiguous standalone-comment
    block directly above (the framework's suppression placement rules,
    so multi-line justifications wrap freely).  Marks the lines it
    reads as consumed so orphaned markers can be reported."""
    found: list[tuple[int, str]] = []

    def take(i: int) -> None:
        entry = markers.get(i)
        if entry and entry[0] == kind:
            consumed.add(i)
            for raw in entry[1].split(","):
                found.append((i, raw.strip()))

    take(lineno)
    i = lineno - 1
    while i >= 1 and ctx.line_text(i).strip().startswith("#"):
        take(i)
        i -= 1
    return found


def _self_attr(node: ast.AST) -> str | None:
    """``X`` for an ``self.X`` attribute expression, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _ClassContract:
    """One class's annotation table: attr -> guarding lock(s)."""

    def __init__(self) -> None:
        self.guards: dict[str, set[str]] = {}
        self.lock_attrs: set[str] = set()
        self.findings: list[tuple[int, str]] = []


def _collect_contract(ctx: FileContext, cls: ast.ClassDef,
                      markers: dict,
                      consumed: set[int]) -> _ClassContract:
    contract = _ClassContract()
    init = next((n for n in cls.body
                 if isinstance(n, ast.FunctionDef)
                 and n.name == "__init__"), None)
    if init is None:
        return contract
    for node in ast.walk(init):
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        else:
            continue
        attrs = [a for a in (_self_attr(t) for t in targets) if a]
        if not attrs:
            continue
        # Every self-assignment in __init__ may declare a lock attr
        # (used to validate guard names) …
        contract.lock_attrs.update(attrs)
        # … and may carry a guarded-by annotation.
        for lineno, name in _names_at(ctx, markers, node.lineno,
                                      GUARDED_MARKER, consumed):
            if not _ATTR_NAME_RE.match(name):
                contract.findings.append(
                    (lineno, f"malformed '# {GUARDED_MARKER}:' — write "
                             f"'# {GUARDED_MARKER}: <lock-attr>' (a "
                             "self attribute name, comma-separated "
                             "for several)"))
                continue
            for attr in attrs:
                contract.guards.setdefault(attr, set()).add(name)
    for attr, locks in sorted(contract.guards.items()):
        for lock in sorted(locks - contract.lock_attrs):
            contract.findings.append(
                (init.lineno,
                 f"attribute self.{attr} is guarded-by self.{lock}, "
                 f"but __init__ never assigns self.{lock} — the "
                 "contract names a lock that does not exist"))
    return contract


def _with_locks(node: ast.With | ast.AsyncWith) -> set[str]:
    """Lock attrs this ``with`` statement acquires (``with self.X:``,
    including tuple/multiple items)."""
    out = set()
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr:
            out.add(attr)
    return out


def _check_method(ctx: FileContext, contract: _ClassContract,
                  fn: ast.FunctionDef, markers: dict,
                  consumed: set[int]) -> Iterator[tuple[int, str]]:
    held: set[str] = set()
    for lineno, name in _names_at(ctx, markers, fn.lineno,
                                  HOLDS_MARKER, consumed):
        if not _ATTR_NAME_RE.match(name):
            yield (lineno, f"malformed '# {HOLDS_MARKER}:' — write "
                           f"'# {HOLDS_MARKER}: <lock-attr>'")
            continue
        held.add(name)

    findings: list[tuple[int, str]] = []

    def visit(node: ast.AST, held: frozenset[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held | _with_locks(node)
            for item in node.items:
                visit(item, held)
            for child in node.body:
                visit(child, frozenset(inner))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # A nested def/lambda runs OUTSIDE this critical section
            # (thread targets, callbacks): it inherits nothing.
            for child in ast.iter_child_nodes(node):
                visit(child, frozenset())
            return
        attr = _self_attr(node)
        if attr is not None and attr in contract.guards:
            need = contract.guards[attr]
            if not (need & held):
                lock = "/".join(sorted(need))
                verb = ("written" if isinstance(
                    node.ctx, (ast.Store, ast.Del)) else "read")
                findings.append(
                    (node.lineno,
                     f"self.{attr} {verb} without holding "
                     f"self.{lock} (guarded-by contract): wrap the "
                     f"access in 'with self.{lock}:' or mark the "
                     f"method '# {HOLDS_MARKER}: {lock}'"))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, frozenset(held))
    yield from findings


@register
class GuardedByPass(LintPass):
    name = "guarded-by"
    description = ("'# guarded-by: <lock>' attributes are accessed "
                   "only under 'with self.<lock>' or in "
                   "'# holds-lock:' methods")

    def check(self, ctx: FileContext) -> Iterator[tuple[int, str]]:
        if f"# {GUARDED_MARKER}:" not in ctx.source \
                and f"# {HOLDS_MARKER}:" not in ctx.source:
            return
        markers = _marker_lines(ctx)
        consumed: set[int] = set()
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            contract = _collect_contract(ctx, cls, markers, consumed)
            yield from contract.findings
            if not contract.guards:
                # holds-lock markers still need consuming (and
                # validating) even in a class with no guarded attrs in
                # THIS file — but without a contract there is nothing
                # to check against.
                for fn in cls.body:
                    if isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        _names_at(ctx, markers, fn.lineno,
                                  HOLDS_MARKER, consumed)
                continue
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                        and fn.name != "__init__":
                    yield from _check_method(ctx, contract, fn,
                                             markers, consumed)
        for lineno, (kind, _) in sorted(markers.items()):
            if lineno not in consumed:
                where = ("a 'self.<attr> = ...' statement in __init__"
                         if kind == GUARDED_MARKER
                         else "a method 'def' line")
                yield (lineno,
                       f"orphaned '# {kind}:' annotation — it must sit "
                       f"on (or in the comment block directly above) "
                       f"{where}, otherwise it binds nothing")
