#!/usr/bin/env python
"""Static check: no blanket exception handlers in dcf_tpu/ outside the
fallback chain.

A blanket handler is a bare ``except:`` or an ``except Exception`` (alone
or in a tuple).  Swallowing arbitrary failures is how a two-party FSS
deployment ends up serving silently-wrong shares; the only legitimate
sites are the fallback chain itself (auto backend canary, native
portable degradation, TPU-presence probes), and each of those must carry
a ``# fallback-ok: <reason>`` marker on the ``except`` line so the
allowance is visible in the diff that introduces it.

Exit 0 when clean; exit 1 listing every unmarked blanket handler.

Usage: python tools/check_exception_hygiene.py [package_dir]
"""

from __future__ import annotations

import ast
import pathlib
import sys

MARKER = "fallback-ok"


def _is_blanket(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in ("Exception", "BaseException"):
            return True
    return False


def check(pkg_dir: pathlib.Path) -> list[str]:
    offenders = []
    for path in sorted(pkg_dir.rglob("*.py")):
        src = path.read_text()
        lines = src.splitlines()
        try:
            tree = ast.parse(src, filename=str(path))
        except SyntaxError as e:
            offenders.append(f"{path}: does not parse: {e}")
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_blanket(node):
                continue
            line = lines[node.lineno - 1]
            if MARKER in line:
                continue
            offenders.append(
                f"{path}:{node.lineno}: blanket handler "
                f"({line.strip()!r}) without '# {MARKER}: <reason>'")
    return offenders


def main() -> int:
    root = pathlib.Path(__file__).resolve().parent.parent
    pkg = pathlib.Path(sys.argv[1]) if len(sys.argv) > 1 else root / "dcf_tpu"
    offenders = check(pkg)
    for line in offenders:
        print(line)
    if offenders:
        print(f"\n{len(offenders)} unmarked blanket handler(s); narrow the "
              "except or mark the line with '# fallback-ok: <reason>'")
        return 1
    print(f"exception hygiene OK under {pkg}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
