#!/usr/bin/env python
"""DEPRECATED shim: the exception-hygiene gate lives in dcflint now.

This entrypoint is kept so existing callers (scripts, muscle memory)
keep working; it runs exactly the ``exception-hygiene`` dcflint pass and
preserves the original exit-code contract (0 clean, 1 violations).
Prefer::

    python -m tools.dcflint <package_dir> [--pass exception-hygiene]

which runs the full six-pass suite (or the one named pass).

Usage: python tools/check_exception_hygiene.py [package_dir]
"""

from __future__ import annotations

import pathlib
import sys


def main() -> int:
    here = pathlib.Path(__file__).resolve().parent
    sys.path.insert(0, str(here.parent))  # make `tools` importable when
    # invoked by path from anywhere, as the old script allowed
    from tools.dcflint import run_path

    pkg = (pathlib.Path(sys.argv[1]) if len(sys.argv) > 1
           else here.parent / "dcf_tpu")
    offenders = run_path(pkg, ["exception-hygiene"])
    for v in offenders:
        print(v)
    if offenders:
        print(f"\n{len(offenders)} unmarked blanket handler(s); narrow the "
              "except or mark the line with '# fallback-ok: <reason>'")
        return 1
    print(f"exception hygiene OK under {pkg} "
          "(via the dcflint exception-hygiene pass; this entrypoint is "
          "deprecated — use `python -m tools.dcflint`)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
