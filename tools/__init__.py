"""Repo tooling: static-analysis (dcflint) and maintenance scripts."""
