"""Benchmark regression gate: ``python -m tools.bench_gate`` (ISSUE 16).

The repo's RESULTS files are append-only emit-then-assert ledgers: the
NEWEST line of each ``benchmarks/RESULTS_*.jsonl`` is the current
claim.  This gate pins a numeric floor under each claim in
``benchmarks/FLOORS.json`` and fails CI when a newly committed line
regresses below it — the per-PR analogue of the PR 3 floor-entry
discipline (a perf claim you stop measuring is a perf claim you have
silently walked back).

``FLOORS.json`` maps RESULTS file names to entries::

    {"RESULTS_pod.jsonl": {
        "field": "value",          # JSON key holding the number
        "floor": 123.4,            # the pinned bound
        "direction": "at_least",   # or "at_most" (latency-style)
        "pinned_value": 176.3,     # the value the floor was cut from
        "reason": "..."            # WHY this pin (disclosed, audited)
    }, ...}

Semantics, all disclosed in the report (no silent caps):

* a PINNED file whose newest line violates its floor -> **regression**
  (exit 1);
* a pinned file that is missing, empty, or lacks the pinned field ->
  **broken pin** (exit 1: a floor that can no longer be read is a
  regression in the gate itself, not a skip);
* a ``RESULTS_*.jsonl`` with no floor entry -> reported unpinned
  (exit 0: new benches pin on their first ``--update``);
* entries under keys starting with ``_`` are metadata, ignored.

``--update`` re-pins every entry from the CURRENT newest lines at
``--ratio`` (default 0.7: headroom for host noise, same discipline as
the serve floors) and REQUIRES ``--reason`` — a floor move without a
disclosed why is exactly the silent walk-back this tool exists to
prevent.  ``at_most`` entries re-pin at ``1/ratio``.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

__all__ = ["newest_line", "check_entry", "run_gate", "update_floors",
           "main"]

AT_LEAST = "at_least"
AT_MOST = "at_most"


def newest_line(path: pathlib.Path) -> dict | None:
    """The last non-empty JSON line of ``path`` (the current claim),
    or None when the file is missing/empty/unparseable."""
    try:
        text = path.read_text(encoding="utf-8")
    except OSError:
        return None
    for raw in reversed(text.splitlines()):
        raw = raw.strip()
        if not raw:
            continue
        try:
            doc = json.loads(raw)
        except ValueError:
            return None  # a corrupt ledger tail is a broken pin
        return doc if isinstance(doc, dict) else None
    return None


def check_entry(name: str, entry: dict,
                benchmarks: pathlib.Path) -> tuple[str, str]:
    """One pin's verdict: returns ``(status, detail)`` with status in
    ``ok`` / ``regression`` / ``broken``."""
    field = entry.get("field", "value")
    floor = entry.get("floor")
    direction = entry.get("direction", AT_LEAST)
    if not isinstance(floor, (int, float)) \
            or direction not in (AT_LEAST, AT_MOST):
        return "broken", (f"{name}: malformed floor entry "
                          f"(floor={floor!r}, direction={direction!r})")
    doc = newest_line(benchmarks / name)
    if doc is None:
        return "broken", (f"{name}: pinned but missing/empty/corrupt "
                          "(a floor that cannot be read is a "
                          "regression in the gate)")
    got = doc.get(field)
    if not isinstance(got, (int, float)):
        return "broken", (f"{name}: newest line has no numeric "
                          f"{field!r} (got {got!r})")
    if direction == AT_LEAST and got < floor:
        return "regression", (
            f"{name}: {field}={got:g} fell below the pinned floor "
            f"{floor:g} (pinned from {entry.get('pinned_value')!r}: "
            f"{entry.get('reason', 'no reason recorded')})")
    if direction == AT_MOST and got > floor:
        return "regression", (
            f"{name}: {field}={got:g} rose above the pinned ceiling "
            f"{floor:g} (pinned from {entry.get('pinned_value')!r}: "
            f"{entry.get('reason', 'no reason recorded')})")
    bound = "floor" if direction == AT_LEAST else "ceiling"
    return "ok", f"{name}: {field}={got:g} vs {bound} {floor:g}"


def run_gate(benchmarks: pathlib.Path,
             floors_path: pathlib.Path) -> tuple[list, list]:
    """Check every pin; returns ``(failures, report_lines)`` —
    failures non-empty means exit 1."""
    try:
        floors = json.loads(floors_path.read_text(encoding="utf-8"))
    except (OSError, ValueError) as e:
        return ([f"cannot read {floors_path}: {e}"],
                [f"FAIL {floors_path}: unreadable"])
    failures: list = []
    report: list = []
    pinned = {k for k in floors if not k.startswith("_")}
    for name in sorted(pinned):
        status, detail = check_entry(name, floors[name], benchmarks)
        report.append(f"{'PASS' if status == 'ok' else 'FAIL'} {detail}")
        if status != "ok":
            failures.append(detail)
    for path in sorted(benchmarks.glob("RESULTS_*.jsonl")):
        if path.name not in pinned:
            # Disclosed, not fatal: a brand-new bench pins on its
            # first --update; hiding it would be a silent cap.
            report.append(f"SKIP {path.name}: no floor pinned "
                          "(pin with --update --reason ...)")
    return failures, report


def update_floors(benchmarks: pathlib.Path, floors_path: pathlib.Path,
                  ratio: float, reason: str) -> list:
    """Re-pin every entry from the current newest lines; returns the
    report lines.  Only existing entries move — pinning a NEW file is
    an editorial act (add the entry skeleton by hand, then --update)."""
    floors = json.loads(floors_path.read_text(encoding="utf-8"))
    report = []
    for name in sorted(k for k in floors if not k.startswith("_")):
        entry = floors[name]
        doc = newest_line(benchmarks / name)
        got = (doc or {}).get(entry.get("field", "value"))
        if not isinstance(got, (int, float)):
            report.append(f"SKIP {name}: no current value to pin from")
            continue
        if entry.get("direction", AT_LEAST) == AT_MOST:
            entry["floor"] = round(got / ratio, 4)
        else:
            entry["floor"] = round(got * ratio, 4)
        entry["pinned_value"] = got
        entry["reason"] = reason
        report.append(f"PIN  {name}: floor={entry['floor']:g} from "
                      f"{got:g} ({reason})")
    floors_path.write_text(json.dumps(floors, indent=2, sort_keys=True)
                           + "\n", encoding="utf-8")
    return report


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.bench_gate",
        description="Pin and enforce floors under the newest "
                    "RESULTS_*.jsonl lines (see tools/bench_gate.py).")
    p.add_argument("--benchmarks", default="benchmarks",
                   help="directory holding RESULTS_*.jsonl")
    p.add_argument("--floors", default="benchmarks/FLOORS.json",
                   help="the pinned-floors file")
    p.add_argument("--update", action="store_true",
                   help="re-pin every floor from the current newest "
                        "lines (requires --reason)")
    p.add_argument("--ratio", type=float, default=0.7,
                   help="--update: floor = ratio * current value "
                        "(ceilings pin at value / ratio)")
    p.add_argument("--reason", default="",
                   help="--update: the disclosed WHY for moving the "
                        "floors (recorded per entry)")
    args = p.parse_args(argv)
    benchmarks = pathlib.Path(args.benchmarks)
    floors_path = pathlib.Path(args.floors)
    if args.update:
        if not args.reason.strip():
            print("error: --update requires --reason (a floor move "
                  "without a disclosed why is a silent walk-back)",
                  file=sys.stderr)
            return 2
        if not 0 < args.ratio <= 1:
            print(f"error: --ratio must be in (0, 1], got {args.ratio}",
                  file=sys.stderr)
            return 2
        for line in update_floors(benchmarks, floors_path,
                                  args.ratio, args.reason):
            print(line)
        return 0
    failures, report = run_gate(benchmarks, floors_path)
    for line in report:
        print(line)
    if failures:
        print(f"\nbench_gate: {len(failures)} regression(s)",
              file=sys.stderr)
        return 1
    print("\nbench_gate: all pinned floors hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
